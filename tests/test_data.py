"""Data readers + task data service (reference: data_reader_test.py)."""

import numpy as np
import pytest

from elasticdl_tpu.data.reader import (
    SyntheticDataReader,
    TextLineDataReader,
    create_data_reader,
)
from elasticdl_tpu.worker.task_data_service import TaskDataService


def test_textline_reader(tmp_path):
    f1 = tmp_path / "a.csv"
    f1.write_text("".join(f"row{i}\n" for i in range(25)))
    f2 = tmp_path / "b.csv"
    f2.write_text("".join(f"other{i}\n" for i in range(5)))
    reader = TextLineDataReader(str(tmp_path / "*.csv"))
    shards = reader.create_shards()
    assert [(s[1], s[2]) for s in shards] == [(0, 25), (0, 5)]
    recs = list(reader.read_records(str(f1), 10, 13))
    assert recs == [b"row10", b"row11", b"row12"]


def test_textline_skip_header(tmp_path):
    f = tmp_path / "h.csv"
    f.write_text("header\nrow0\nrow1\n")
    reader = TextLineDataReader(str(f), skip_header=True)
    (name, s, e), = reader.create_shards()
    assert e - s == 2
    assert list(reader.read_records(name, 0, 2)) == [b"row0", b"row1"]


def test_synthetic_reader_deterministic():
    r1 = SyntheticDataReader(kind="mnist", num_records=100, num_shards=3)
    r2 = SyntheticDataReader(kind="mnist", num_records=100, num_shards=3)
    shards = r1.create_shards()
    assert sum(e - s for _, s, e in shards) == 100
    a = list(r1.read_records(*shards[1]))
    b = list(r2.read_records(*shards[1]))
    assert a == b
    assert len(a[0]) == 785


def test_create_data_reader_url():
    r = create_data_reader("synthetic://criteo?n=50&shards=2")
    shards = r.create_shards()
    assert len(shards) == 2
    rec = next(r.read_records(*shards[0]))
    assert rec.count(b"\t") == 39  # label + 13 dense + 26 cat


def test_task_data_service_batches_and_padding():
    reader = SyntheticDataReader(kind="mnist", num_records=50, num_shards=1)

    def parse(rec):
        buf = np.frombuffer(rec, np.uint8)
        return buf[1:].astype(np.float32), np.int32(buf[0])

    svc = TaskDataService(reader, parse, batch_size=16, batch_multiple=8)
    batches = list(svc.batches("s", 0, 50))
    assert len(batches) == 4                      # 16+16+16+2(padded)
    for b in batches[:3]:
        assert b["features"].shape == (16, 784)
        assert b["mask"].sum() == 16
    last = batches[-1]
    assert last["features"].shape == (16, 784)
    assert last["mask"].sum() == 2

    # batch size rounded up to the mesh multiple
    svc2 = TaskDataService(reader, parse, batch_size=10, batch_multiple=8)
    assert svc2.batch_size == 16


def test_task_data_service_dict_features():
    reader = SyntheticDataReader(kind="criteo", num_records=20, num_shards=1)
    from model_zoo.deepfm.deepfm import dataset_fn

    parse = dataset_fn("training", reader.metadata)
    svc = TaskDataService(reader, parse, batch_size=8)
    b = next(iter(svc.batches("s", 0, 20)))
    assert b["features"]["dense"].shape == (8, 13)
    assert b["features"]["cat"].shape == (8, 26)


def test_csv_reader_header_and_columns(tmp_path):
    from elasticdl_tpu.data.reader import CSVDataReader

    f = tmp_path / "census.csv"
    f.write_text("age,workclass,label\n39,Private,0\n50,Self-emp,1\n")
    r = CSVDataReader(str(f))
    assert r.metadata["columns"] == ["age", "workclass", "label"]
    shards = r.create_shards()
    assert shards == [(str(f), 0, 2)]
    rows = list(r.read_records(str(f), 0, 2))
    assert rows == [b"39,Private,0", b"50,Self-emp,1"]
    # factory route
    r2 = create_data_reader(str(f), "csv")
    assert r2.metadata["columns"] == ["age", "workclass", "label"]


def test_csv_reader_explicit_columns_and_delimiter(tmp_path):
    from elasticdl_tpu.data.reader import CSVDataReader

    f = tmp_path / "t.tsv"
    f.write_text("h1\th2\n1\t2\n")
    r = CSVDataReader(str(f), delimiter="\t", columns=["a", "b"])
    assert r.metadata["columns"] == ["a", "b"]
    assert list(r.read_records(str(f), 0, 1)) == [b"1\t2"]


def test_odps_reader_requires_pyodps():
    import pytest
    from elasticdl_tpu.data.reader import ODPSDataReader

    try:
        import odps  # noqa: F401
        pytest.skip("pyodps installed; gating not exercised")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="pyodps"):
        ODPSDataReader("some_table")
    with pytest.raises(ImportError, match="pyodps"):
        create_data_reader("odps://some_table#pt=20200101")


class _FakeODPSReader:
    """Stands in for pyodps's table reader: count + row slicing."""

    def __init__(self, rows):
        self._rows = rows

    @property
    def count(self):
        return len(self._rows)

    def __getitem__(self, sl):
        return self._rows[sl]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _FakeODPSRow:
    def __init__(self, mapping):
        self._m = dict(mapping)

    def __getitem__(self, col):
        return self._m[col]

    @property
    def values(self):
        return list(self._m.values())


def _install_fake_odps(monkeypatch, rows, columns):
    """Inject a minimal `odps` module into sys.modules so ODPSDataReader's
    read path runs without pyodps (VERDICT round-2 weak #8: the reader was
    only import-gating-tested, never exercised)."""
    import sys
    import types

    class _Col:
        def __init__(self, name):
            self.name = name

    class _Schema:
        def __init__(self):
            self.columns = [_Col(c) for c in columns]

    class _Table:
        def __init__(self, name):
            self.name = name
            self.table_schema = _Schema()
            self.open_partition = None

        def open_reader(self, partition=None):
            self.open_partition = partition
            return _FakeODPSReader(rows)

    class _ODPS:
        def __init__(self, access_id, access_key, project=None, endpoint=None):
            self.args = (access_id, access_key, project, endpoint)
            self.tables = {}

        def get_table(self, name):
            return self.tables.setdefault(name, _Table(name))

    fake = types.ModuleType("odps")
    fake.ODPS = _ODPS
    monkeypatch.setitem(sys.modules, "odps", fake)
    for var, val in (
        ("ODPS_PROJECT_NAME", "proj"),
        ("ODPS_ACCESS_ID", "id"),
        ("ODPS_ACCESS_KEY", "key"),
        ("ODPS_ENDPOINT", "http://fake"),
    ):
        monkeypatch.setenv(var, val)


def test_odps_reader_read_path_with_fake_module(monkeypatch):
    """Shards, metadata, CSV-encoded rows, and partition plumbing over a
    faked pyodps (the reference guards its ODPS tests behind credentials;
    this is the in-process twin that always runs)."""
    rows = [
        _FakeODPSRow({"age": 30 + i, "name": f"p,{i}", "label": i % 2})
        for i in range(5)
    ]
    _install_fake_odps(monkeypatch, rows, ["age", "name", "label"])
    from elasticdl_tpu.data.reader import ODPSDataReader

    r = create_data_reader("odps://people#pt=20200101", records_per_shard=2)
    assert isinstance(r, ODPSDataReader)
    assert r.metadata == {"columns": ["age", "name", "label"], "table": "people"}
    assert r.create_shards() == [("people", 0, 2), ("people", 2, 4), ("people", 4, 5)]

    recs = list(r.read_records("people", 1, 3))
    # string containing the delimiter is CSV-quoted, not split
    assert recs == [b'31,"p,1",1', b'32,"p,2",0']
    # the partition from the odps:// fragment reaches open_reader
    assert r._table.open_partition == "pt=20200101"

    # column projection
    r2 = ODPSDataReader("people", columns=["label", "age"])
    assert list(r2.read_records("people", 0, 1)) == [b"0,30"]


def test_odps_reader_missing_credentials(monkeypatch):
    _install_fake_odps(monkeypatch, [], ["a"])
    monkeypatch.delenv("ODPS_ACCESS_KEY")
    from elasticdl_tpu.data.reader import ODPSDataReader

    with pytest.raises(ValueError, match="ODPS_ACCESS_KEY"):
        ODPSDataReader("t")


def test_csv_header_mismatch_across_files_raises(tmp_path):
    """Round-3 (VERDICT #8): a directory mixing CSV column orders must fail
    loudly at reader construction, not silently misparse by position."""
    from elasticdl_tpu.data.reader import CSVDataReader

    (tmp_path / "a.csv").write_text("age,label\n1,0\n")
    (tmp_path / "b.csv").write_text("label,age\n0,1\n")
    with pytest.raises(ValueError, match="header mismatch"):
        CSVDataReader(str(tmp_path))
    # consistent headers stay fine
    (tmp_path / "b.csv").write_text("age,label\n2,1\n")
    r = CSVDataReader(str(tmp_path))
    assert r.metadata["columns"] == ["age", "label"]
    assert sum(e - s for _, s, e in r.create_shards()) == 2


class _FakeOdpsReaderCtx:
    def __init__(self, rows):
        self._rows = rows
        self.count = len(rows)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, sl):
        class Row:
            def __init__(self, values):
                self.values = values

            def __getitem__(self, col):
                return dict(zip(["a", "b"], self.values))[col]

        return [Row(v) for v in self._rows[sl]]


class _FakeOdpsTable:
    name = "t1"

    class table_schema:
        class _Col:
            def __init__(self, name):
                self.name = name

        columns = [_Col("a"), _Col("b")]

    def open_reader(self, partition=None):
        return _FakeOdpsReaderCtx([(1, "x"), (2, "y,z"), (3, None)])


def test_odps_reader_with_mocked_client(monkeypatch):
    """Round-3 (VERDICT #8): the ODPS reader logic under a faked pyodps —
    shard math, CSV-quoted record encoding, metadata columns."""
    import sys
    import types

    fake = types.ModuleType("odps")
    fake.ODPS = lambda *a, **kw: types.SimpleNamespace(
        get_table=lambda name: _FakeOdpsTable()
    )
    monkeypatch.setitem(sys.modules, "odps", fake)
    for v in ("ODPS_PROJECT_NAME", "ODPS_ACCESS_ID", "ODPS_ACCESS_KEY",
              "ODPS_ENDPOINT"):
        monkeypatch.setenv(v, "x")

    from elasticdl_tpu.data.reader import ODPSDataReader, create_data_reader

    r = ODPSDataReader("t1", records_per_shard=2)
    assert r.create_shards() == [("t1", 0, 2), ("t1", 2, 3)]
    assert r.metadata["columns"] == ["a", "b"]
    recs = list(r.read_records("t1", 0, 3))
    assert recs[0] == b"1,x"
    assert recs[1] == b'2,"y,z"'   # delimiter-containing field stays quoted
    assert recs[2] == b"3,"        # None -> empty
    # odps:// factory addressing with a partition suffix
    r2 = create_data_reader("odps://t1#pt=20260729")
    assert r2._partition == "pt=20260729"


def test_odps_reader_missing_env_raises(monkeypatch):
    import sys
    import types

    monkeypatch.setitem(sys.modules, "odps", types.ModuleType("odps"))
    for v in ("ODPS_PROJECT_NAME", "ODPS_ACCESS_ID", "ODPS_ACCESS_KEY",
              "ODPS_ENDPOINT"):
        monkeypatch.delenv(v, raising=False)
    from elasticdl_tpu.data.reader import ODPSDataReader

    with pytest.raises(ValueError, match="ODPS credentials"):
        ODPSDataReader("t1")


def test_client_verbs_require_matching_data_flags():
    """Round-3 (VERDICT #8): each verb validates ITS data flag up front."""
    from elasticdl_tpu.client import api
    from elasticdl_tpu.common.config import JobConfig

    cfg = JobConfig(model_def="m.n.f")
    with pytest.raises(ValueError, match="--training_data"):
        api.train(cfg)
    with pytest.raises(ValueError, match="--validation_data"):
        api.evaluate(cfg)
    with pytest.raises(ValueError, match="--prediction_data"):
        api.predict(cfg)
