"""Alert engine (observability/alerts.py): declarative rules, edge
triggering with carried-forward blindness semantics, burn-rate windows,
page-severity flight dumps, the hook seam, and the concurrent-scrape
contract over /alerts + /timeseries."""

import json
import threading
import time
import urllib.request

import pytest

from elasticdl_tpu.observability.alerts import (
    AlertEngine,
    AlertRule,
    default_rules,
    rules_from_json,
)
from elasticdl_tpu.observability.registry import MetricsRegistry
from elasticdl_tpu.observability.timeseries import TimeSeriesStore

SERIES = "edl_fleet_probe"


def make_engine(rules, **kw):
    store = TimeSeriesStore(
        capacity=512, interval_s=0.0, registry=MetricsRegistry())
    dumps = []
    eng = AlertEngine(store, rules=rules,
                      flight_dump=dumps.append, **kw)
    return store, eng, dumps


def feed(store, eng, t0, values, step_s=5.0):
    """Sample value[i] at t0 + i*step and evaluate after each."""
    for i, v in enumerate(values):
        now = t0 + step_s * i
        extra = {} if v is None else {SERIES: v}
        store.sample(now=now, extra=extra)
        eng.evaluate(now=now)
    return t0 + step_s * (len(values) - 1)


def onsets(eng):
    return [h for h in eng.snapshot()["history"]
            if h["transition"] == "firing"]


def clears(eng):
    return [h for h in eng.snapshot()["history"]
            if h["transition"] == "cleared"]


# ---------------------------------------------------------------------- #
# rule validation


def test_rule_validation():
    with pytest.raises(ValueError):
        AlertRule("x", series=SERIES, threshold=1, mode="nope")
    with pytest.raises(ValueError):
        AlertRule("x", series=SERIES, threshold=1, op="!=")
    with pytest.raises(ValueError):
        AlertRule("x", series=SERIES, threshold=1, severity="critical")
    with pytest.raises(ValueError):
        AlertRule("x", series=SERIES, threshold=1, mode="burn_rate",
                  window_s=60, long_window_s=30)


def test_duplicate_rule_names_rejected():
    store = TimeSeriesStore(registry=MetricsRegistry())
    rules = [AlertRule("a", series=SERIES, threshold=1),
             AlertRule("a", series=SERIES, threshold=2)]
    with pytest.raises(ValueError):
        AlertEngine(store, rules=rules)


def test_rules_from_json_rejects_unknown_keys():
    good = rules_from_json([
        {"name": "a", "series": SERIES, "threshold": 2.0,
         "mode": "avg", "window_s": 30}
    ])
    assert good[0].name == "a" and good[0].mode == "avg"
    with pytest.raises(ValueError):
        rules_from_json([{"name": "a", "series": SERIES,
                          "threshold": 2.0, "treshold": 3.0}])
    with pytest.raises(ValueError):
        rules_from_json({"name": "a"})


def test_default_rules_are_valid_and_unique():
    rules = default_rules()
    names = [r.name for r in rules]
    assert len(set(names)) == len(names)
    assert {"straggler", "dispatcher_backlog_per_worker",
            "fleet_data_wait_dominant", "embedding_pull_p99",
            "embedding_shard_imbalance", "embedding_cache_hit_collapse",
            "goodput_burn", "wasted_work_ratio",
            "emb_attr_dominant_shift"} == set(names)
    # page rules are the flight-dumping ones
    pages = {r.name for r in rules if r.severity == "page"}
    assert pages == {"embedding_pull_p99", "embedding_shard_imbalance"}


# ---------------------------------------------------------------------- #
# edge triggering (the satellite's named coverage)


def test_onset_fires_once_and_clears_once():
    store, eng, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10, mode="value")])
    hook_calls = []
    eng.add_hook(hook_calls.append)
    t = feed(store, eng, 1000.0, [1, 1, 50, 60, 70, 80])   # bad from i=2
    assert len(onsets(eng)) == 1
    assert len(hook_calls) == 1
    assert hook_calls[0]["rule"] == "probe"
    assert [a["rule"] for a in eng.active()] == ["probe"]
    # recovery
    feed(store, eng, t + 5, [2, 2, 2])
    assert eng.active() == []
    assert len(clears(eng)) == 1
    assert len(onsets(eng)) == 1       # no re-onset anywhere


def test_for_s_holds_back_onset_until_held():
    store, eng, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10, mode="value",
                   for_s=12.0)])
    # bad at t=0 and t=5: held for 5s < 12s -> still pending
    feed(store, eng, 1000.0, [50, 50])
    assert eng.active() == []
    # bad at t=10 and t=15: held >= 12s at t=15 -> onset (once)
    feed(store, eng, 1010.0, [50, 50])
    assert len(onsets(eng)) == 1
    # a recovery resets the pending clock
    store2, eng2, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10, mode="value",
                   for_s=12.0)])
    feed(store2, eng2, 1000.0, [50, 50, 1, 50, 50])
    assert eng2.active() == []         # never held 12s continuously


def test_carried_forward_on_blindness_no_spurious_clear():
    """An ACTIVE alert whose series stops appearing (reporter died) is
    carried forward: no clear, no second onset when data returns bad,
    exactly one clear when data returns good."""
    store, eng, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10, mode="value",
                   window_s=30.0)])
    t = feed(store, eng, 1000.0, [50, 60])
    assert len(onsets(eng)) == 1
    # blindness: samples WITHOUT the series, long past the window
    t = feed(store, eng, t + 5, [None] * 20)
    active = eng.active()
    assert [a["rule"] for a in active] == ["probe"]
    assert active[0]["carried_forward"] is True
    assert clears(eng) == []
    # data returns, still bad: NO second onset
    t = feed(store, eng, t + 5, [70, 70])
    assert len(onsets(eng)) == 1
    assert eng.active()[0]["carried_forward"] is False
    # data returns good: exactly one clear
    feed(store, eng, t + 5, [1, 1])
    assert len(clears(eng)) == 1
    assert eng.active() == []


def test_burn_rate_requires_both_windows():
    """A transient spike breaches the short window but not the long one:
    no page. A sustained burn breaches both: page."""
    rule = AlertRule("probe", series=SERIES, threshold=100,
                     mode="burn_rate", window_s=30, long_window_s=300)
    store, eng, _ = make_engine([rule])
    # 300s of health, then one 30s spike, then health again
    t = feed(store, eng, 1000.0, [1] * 60)
    t = feed(store, eng, t + 5, [500] * 6)     # 30s spike
    assert eng.active() == []                  # long window still healthy
    t = feed(store, eng, t + 5, [1] * 10)
    assert onsets(eng) == []
    # sustained: long window saturates too
    feed(store, eng, t + 5, [500] * 70)
    assert len(onsets(eng)) == 1
    info = onsets(eng)[0]
    assert info["value"] > 100 and info["long_value"] > 100


def test_rate_mode_alerts_on_counter_rate_of_change():
    rule = AlertRule("probe_rate", series="edl_fleet_errs_total",
                     threshold=5.0, mode="rate", window_s=60)
    store, eng, _ = make_engine([rule])
    v = 0.0
    for i in range(10):                 # +1/s: rate 1 < 5
        v += 5.0
        store.sample(now=1000.0 + 5 * i,
                     extra={"edl_fleet_errs_total": v})
        eng.evaluate(now=1000.0 + 5 * i)
    assert eng.active() == []
    for i in range(10, 24):             # +50 per 5s: rate 10 > 5
        v += 50.0
        store.sample(now=1000.0 + 5 * i,
                     extra={"edl_fleet_errs_total": v})
        eng.evaluate(now=1000.0 + 5 * i)
    assert len(onsets(eng)) == 1


# ---------------------------------------------------------------------- #
# side effects: metrics, events, flight dump, persistence


def test_page_severity_dumps_flight_ring_warn_does_not():
    store, eng, dumps = make_engine([
        AlertRule("warny", series=SERIES, threshold=10, severity="warn"),
        AlertRule("pagey", series="edl_fleet_other", threshold=10,
                  severity="page"),
    ])
    store.sample(now=1000.0, extra={SERIES: 50, "edl_fleet_other": 1})
    eng.evaluate(now=1000.0)
    assert dumps == []                 # only warn fired
    store.sample(now=1005.0, extra={SERIES: 50, "edl_fleet_other": 99})
    eng.evaluate(now=1005.0)
    assert dumps == ["alert:pagey"]


def test_transition_metrics_and_events():
    from elasticdl_tpu.observability import tracing
    from elasticdl_tpu.observability.registry import default_registry

    reg = default_registry()
    active = reg.get("edl_alert_active")
    transitions = reg.get("edl_alert_transitions_total")
    store, eng, _ = make_engine(
        [AlertRule("probe_m", series=SERIES, threshold=10)])
    events = []

    def sink(rec):
        if rec.get("name", "").startswith("cluster.alert"):
            events.append(rec)

    tracing.get_tracer().add_sink(sink)
    try:
        t = feed(store, eng, 1000.0, [50, 60, 70])
        assert active.value(rule="probe_m") == 1
        assert transitions.value(rule="probe_m") == 1
        feed(store, eng, t + 5, [1])
        assert active.value(rule="probe_m") == 0
        assert transitions.value(rule="probe_m") == 2
    finally:
        tracing.get_tracer().remove_sink(sink)
    names = [e["name"] for e in events]
    assert names.count("cluster.alert") == 1
    assert names.count("cluster.alert_cleared") == 1
    onset = next(e for e in events if e["name"] == "cluster.alert")
    assert onset["rule"] == "probe_m" and onset["severity"] == "warn"


def test_failing_hook_never_breaks_evaluation():
    store, eng, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10)])
    eng.add_hook(lambda info: 1 / 0)
    ok = []
    eng.add_hook(ok.append)
    feed(store, eng, 1000.0, [50])
    assert len(ok) == 1                # later hooks still ran
    assert [a["rule"] for a in eng.active()] == ["probe"]


def test_evaluate_never_raises_even_with_broken_store():
    store, eng, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10)])
    eng._store = None                  # worst case: store gone
    snap = eng.evaluate(now=1000.0)
    assert snap["active"] == []


def test_write_json_atomic(tmp_path):
    path = str(tmp_path / "control" / "alerts.json")
    store, eng, _ = make_engine(
        [AlertRule("probe", series=SERIES, threshold=10)],
        json_path=path)
    feed(store, eng, 1000.0, [50])     # transition writes the file
    with open(path) as f:
        doc = json.load(f)
    assert [a["rule"] for a in doc["active"]] == ["probe"]
    assert doc["rules"][0]["name"] == "probe"
    assert doc["history"][0]["transition"] == "firing"


# ---------------------------------------------------------------------- #
# the satellite's concurrency coverage: /alerts + /timeseries scrape
# while rules evaluate


def test_concurrent_scrape_while_evaluating():
    from elasticdl_tpu.observability.http import ObservabilityServer

    reg = MetricsRegistry()
    store = TimeSeriesStore(capacity=256, interval_s=0.0, registry=reg)
    eng = AlertEngine(
        store,
        rules=[AlertRule("probe", series=SERIES, threshold=10)],
        flight_dump=lambda r: None,
    )
    server = ObservabilityServer(
        registry=reg, role="t", timeseries=store, alerts=eng)
    port = server.start(0)
    stop = threading.Event()
    errs = []

    def evaluator():
        i = 0
        while not stop.is_set():
            # values oscillate across the threshold: transitions happen
            # WHILE scrapes read state
            store.sample(extra={SERIES: 50 if (i // 3) % 2 else 1})
            eng.evaluate()
            i += 1

    def scraper(path):
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as resp:
                    assert resp.status == 200
                    json.loads(resp.read())
            except Exception as e:     # pragma: no cover
                errs.append((path, e))
                return

    threads = [
        threading.Thread(target=evaluator),
        threading.Thread(target=scraper, args=("/alerts",)),
        threading.Thread(target=scraper, args=("/timeseries?window=60",)),
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(0.8)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        server.stop()
    assert not errs, errs
    # transitions really happened under the scrape load
    assert eng.snapshot()["evaluations"] > 5


def test_alerts_endpoint_disabled_shape():
    from elasticdl_tpu.observability.http import ObservabilityServer

    server = ObservabilityServer(registry=MetricsRegistry(), role="t")
    port = server.start(0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/alerts", timeout=5
        ) as resp:
            doc = json.loads(resp.read())
        assert doc["enabled"] is False and doc["active"] == []
    finally:
        server.stop()
