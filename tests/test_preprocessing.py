"""Preprocessing parity tests (reference: elasticdl_preprocessing tests)."""

import numpy as np
import jax.numpy as jnp

from elasticdl_tpu.api import preprocessing as pp


def test_hash_bucket_deterministic_and_in_range():
    x = np.arange(1000, dtype=np.int32)
    a = np.asarray(pp.hash_bucket(x, 37))
    b = np.asarray(pp.hash_bucket(x, 37))
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < 37
    # spreads: no bucket hogs the distribution
    counts = np.bincount(a, minlength=37)
    assert counts.max() < 5 * counts.mean()


def test_bucketize():
    out = np.asarray(pp.bucketize([1.0, 5.0, 10.0, 100.0], [2.0, 10.0]))
    np.testing.assert_array_equal(out, [0, 1, 2, 2])


def test_normalize_and_log():
    out = np.asarray(pp.normalize([10.0], 5.0, 2.5))
    np.testing.assert_allclose(out, [2.0])
    np.testing.assert_allclose(np.asarray(pp.log_normalize([-3.0, 0.0])), [0.0, 0.0])


def test_concat_with_offset():
    a = jnp.asarray([[1], [2]], jnp.int32)
    b = jnp.asarray([[0, -1], [3, 1]], jnp.int32)
    out = np.asarray(pp.concat_with_offset([a, b], [10, 5]))
    np.testing.assert_array_equal(out, [[1, 10, -1], [2, 13, 11]])


def test_int_lookup():
    out = np.asarray(pp.int_lookup([5, 7, 999], vocab=[5, 7, 11], num_oov=1))
    assert out[0] == 1 and out[1] == 2   # vocab hits shift by num_oov
    assert out[2] == 0                    # OOV lands in [0, num_oov)


def test_hash_strings_stable():
    a = pp.hash_strings(["foo", "bar", b"foo"], 100)
    assert a[0] == a[2]
    assert 0 <= a.min() and a.max() < 100


def test_string_lookup():
    lookup = pp.StringLookup(["a", "b"], num_oov=2)
    out = lookup(["a", "b", "zzz"])
    assert out[0] == 2 and out[1] == 3 and 0 <= out[2] < 2
    assert lookup.vocab_size == 4


def test_pad_to_dense():
    out = pp.pad_to_dense([[1, 2, 3], [7]], max_len=2)
    np.testing.assert_array_equal(out, [[1, 2], [7, -1]])


def test_multi_hot_skips_padding_and_counts():
    """CategoryEncoding parity: multi-hot counts duplicate ids, skips
    negative padding slots, and applies per-slot weights."""
    import jax.numpy as jnp

    ids = np.asarray([[1, 1, 3, -1], [0, 2, -1, -1]], np.int32)
    out = np.asarray(pp.multi_hot(ids, 4))
    np.testing.assert_array_equal(
        out, [[0, 2, 0, 1], [1, 0, 1, 0]])
    w = np.asarray([[0.5, 0.5, 2.0, 9.0], [1.0, 3.0, 9.0, 9.0]], np.float32)
    outw = np.asarray(pp.multi_hot(ids, 4, weights=w))
    np.testing.assert_allclose(outw, [[0, 1.0, 0, 2.0], [1.0, 0, 3.0, 0]])


def test_fit_discretization_quantiles_feed_bucketize():
    """Discretization adapt() parity: fitted boundaries split the fitted
    data into near-equal-mass buckets and compose with bucketize."""
    r = np.random.RandomState(0)
    vals = np.concatenate([r.randn(4000), r.randn(1000) * 10 + 50])
    bounds = pp.fit_discretization(vals, num_bins=8)
    assert len(bounds) == 7 and np.all(np.diff(bounds) > 0)
    buckets = np.asarray(pp.bucketize(vals, bounds))
    counts = np.bincount(buckets, minlength=8)
    assert counts.min() > 0.7 * len(vals) / 8  # near-equal mass
    # degenerate inputs: too few bins / empty data -> no boundaries
    assert len(pp.fit_discretization(vals, 1)) == 0
    assert len(pp.fit_discretization([], 4)) == 0


def test_vocab_from_file_round_trip(tmp_path):
    """IndexLookup vocabulary-file parity: file -> tokens -> StringLookup
    gives stable ids; blanks and duplicates are dropped."""
    p = tmp_path / "vocab.txt"
    p.write_text("apple\nbanana\n\ncherry\nbanana\n", encoding="utf-8")
    vocab = pp.vocab_from_file(str(p))
    assert vocab == ["apple", "banana", "cherry"]
    assert pp.vocab_from_file(str(p), max_size=2) == ["apple", "banana"]
    lk = pp.StringLookup(vocab, num_oov=1)
    np.testing.assert_array_equal(
        lk(np.asarray(["banana", "durian", "apple"])), [2, 0, 1])
