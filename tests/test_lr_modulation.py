"""Runtime LR modulation (reference: learning_rate_modulation.py) — injected
hyperparams change between steps with no retrace, through plain and chained
optimizers, and through the Trainer state."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from elasticdl_tpu.training import lr_modulation as lrm


def test_set_get_learning_rate_plain():
    tx = lrm.modulated(optax.sgd, learning_rate=0.1)
    params = {"w": jnp.ones((3,))}
    state = tx.init(params)
    assert lrm.get_learning_rate(state) == pytest.approx(0.1)

    grads = {"w": jnp.ones((3,))}
    updates, state = tx.update(grads, state, params)
    np.testing.assert_allclose(updates["w"], -0.1 * np.ones(3), rtol=1e-6)

    state = lrm.set_learning_rate(state, 0.5)
    assert lrm.get_learning_rate(state) == pytest.approx(0.5)
    updates, state = tx.update(grads, state, params)
    np.testing.assert_allclose(updates["w"], -0.5 * np.ones(3), rtol=1e-6)


def test_set_learning_rate_inside_chain():
    tx = optax.chain(
        optax.clip_by_global_norm(10.0),
        lrm.modulated(optax.adam, learning_rate=1e-3),
    )
    params = {"w": jnp.ones((2,))}
    state = tx.init(params)
    assert lrm.get_learning_rate(state) == pytest.approx(1e-3)
    state = lrm.set_learning_rate(state, 1e-2)
    assert lrm.get_learning_rate(state) == pytest.approx(1e-2)
    # still usable after the rewrite
    updates, _ = tx.update({"w": jnp.ones((2,))}, state, params)
    assert np.all(np.isfinite(updates["w"]))


def test_uninjected_optimizer_raises():
    tx = optax.adam(1e-3)
    state = tx.init({"w": jnp.ones(2)})
    assert lrm.get_learning_rate(state) is None
    with pytest.raises(KeyError, match="modulated"):
        lrm.set_learning_rate(state, 0.1)


def test_trainer_set_learning_rate(mesh8):
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.training.model_spec import ModelSpec
    from elasticdl_tpu.training.trainer import Trainer

    cfg = JobConfig(
        model_zoo="model_zoo",
        model_def="deepfm.deepfm.custom_model",
        model_params={"field_vocab": 64, "hidden": "16,16"},
    )
    spec = ModelSpec.from_config(cfg)
    spec.optimizer = lrm.modulated(optax.adam, learning_rate=1e-3)
    trainer = Trainer(spec, mesh8)
    rng = np.random.RandomState(0)
    batch = {
        "features": {
            "dense": rng.rand(16, 13).astype(np.float32),
            "cat": rng.randint(0, 1 << 30, size=(16, 26)).astype(np.int32),
        },
        "labels": rng.randint(0, 2, size=(16,)).astype(np.int32),
        "mask": np.ones((16,), np.float32),
    }
    state = trainer.init_state(batch)
    state, _ = trainer.train_step(state, batch)
    state = trainer.set_learning_rate(state, 5e-3)
    assert lrm.get_learning_rate(state.opt_state) == pytest.approx(5e-3)
    # the jitted step keeps running with the same trace
    state, logs = trainer.train_step(state, batch)
    assert np.isfinite(float(logs["loss"]))
    assert state.model_version == 2


def test_scaling_formulas():
    assert lrm.linear_scale(0.1, 8, 4) == pytest.approx(0.2)
    assert lrm.linear_scale(0.1, 2, 4) == pytest.approx(0.05)
    assert lrm.staleness_modulation(0.1, 0) == pytest.approx(0.1)
    assert lrm.staleness_modulation(0.1, 3, factor=1.0) == pytest.approx(0.025)
