"""The north star's two halves composed in one CPU-provable artifact
(VERDICT r4 next #2 / BASELINE.json): a miniature Criteo DeepFM cohort
reaches its AUC target while surviving TWO injected member kills, with
exactly-once task accounting (no record loss), checkpoint-resume across
re-formations, and the recovery wall-clock overhead measured and reported.
"""

import glob
import os
import re
import time


from elasticdl_tpu.client.local import free_port
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.process_manager import ProcessManager

HERMETIC_ENV = {
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    "EDL_LOG_LEVEL": "INFO",
}

AUC_TARGET = 0.70   # the learnable synthetic stream passes 0.75 quickly;
                    # 0.70 keeps the assert robust to the short run


from tests.conftest import requires_multiprocess_backend


@requires_multiprocess_backend
def test_elastic_time_to_auc_survives_two_kills(tmp_path):
    n_tasks = 8
    cfg = JobConfig(
        job_name="elastic-auc",
        model_zoo=os.path.abspath("model_zoo"),
        model_def="deepfm.deepfm.custom_model",
        model_params={"field_vocab": 64, "hidden": "32,32"},
        training_data="synthetic://criteo?n=16384&shards=8",
        validation_data="synthetic://criteo?n=1024&shards=1",
        records_per_task=2048,
        minibatch_size=64,
        num_epochs=1,
        evaluation_steps=64,    # model-version steps between eval triggers
        num_workers=1,
        num_processes=2,
        master_addr=f"localhost:{free_port()}",
        worker_heartbeat_s=1.0,
        task_timeout_s=300.0,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=16,
        shuffle=False,
    )
    master = Master(cfg)
    manager = ProcessManager(
        cfg,
        membership=master.membership,
        extra_env=HERMETIC_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.dispatcher.finished,
        checkpoint_request_fn=lambda: master.servicer.request_checkpoint(0),
    )
    # Per-kill state machine: killed -> world_dead (the whole cohort has
    # been declared dead: alive_count()==0 — a SIGKILLed member takes the
    # leader down by cohort co-death, surfaced by heartbeat lapse) ->
    # recovered (a RE-FORMED cohort's leader joined: alive again AFTER the
    # death was observed). alive_count() alone is not a recovery signal:
    # the stale leader keeps counting as alive for the heartbeat timeout
    # right after the kill.
    kills = []          # [{"t_kill", "t_dead", "t_rec"}]
    kill_after = [1, 4]  # finished-task thresholds for kill #1 and #2

    def observer():
        if kills and kills[-1]["t_rec"] is None:
            if kills[-1]["t_dead"] is None:
                if master.membership.alive_count() == 0:
                    kills[-1]["t_dead"] = time.time()
            elif master.membership.alive_count() > 0:
                kills[-1]["t_rec"] = time.time()
            return   # a kill is in flight: never overlap the second one
        if len(kills) < len(kill_after):
            done = master.dispatcher.counts()["finished_training"]
            if done >= kill_after[len(kills)]:
                wp = manager._procs.get(1)
                if wp is not None and wp.proc.poll() is None:
                    wp.proc.kill()
                    kills.append(
                        {"t_kill": time.time(), "t_dead": None, "t_rec": None}
                    )

    master.start()
    manager.start_workers()
    t0 = time.time()
    try:
        deadline = time.time() + 900
        while not master.dispatcher.finished() and time.time() < deadline:
            master.membership.reap()
            master.dispatcher.poke()
            observer()
            time.sleep(0.2)
        counts = master.dispatcher.counts()
        assert master.dispatcher.finished(), counts
        wall_s = time.time() - t0
        results = master.evaluation.latest_results()
    finally:
        master.shutdown()
        manager.stop()

    # exactly-once accounting: every task retired exactly once, none lost,
    # none failed permanently — the "no record loss" half of the proof
    assert counts["finished_training"] == n_tasks, counts
    assert counts["failed_permanently"] == 0, counts

    # both kills fired, both worlds died, both cohorts re-formed
    assert len(kills) == 2, kills
    assert all(k["t_dead"] and k["t_rec"] for k in kills), kills
    # recovery overhead: kill -> re-formed leader registered, summed
    overhead_s = sum(k["t_rec"] - k["t_kill"] for k in kills)

    log = "".join(
        open(f, errors="replace").read()
        for f in sorted(glob.glob(str(tmp_path / "logs" / "*.log")))
    )
    # two re-formations: worlds v1 and v2 came up after v0
    for v in (0, 1, 2):
        assert f"distributed world v{v} up" in log, f"world v{v} missing"
    # monotone resume: every restore picks up at a strictly positive step,
    # and the sequence of resumed steps never regresses (checkpoint
    # monotonicity across generations)
    resumed = [int(s) for s in
               re.findall(r"cohort resumed from checkpoint at step (\d+)", log)]
    assert resumed, "no resume-from-checkpoint after kills"
    assert all(s > 0 for s in resumed), resumed
    assert resumed == sorted(resumed), f"step regression: {resumed}"

    # the north-star gate: eval AUC reached the target despite 2 kills
    auc = results.get("auc")
    assert auc is not None and auc >= AUC_TARGET, results

    print(
        '\n[elastic-time-to-auc] {"auc_reached": true, "auc": %.4f, '
        '"kills": 2, "overhead_s": %.2f, "wall_s": %.2f, '
        '"resumed_steps": %s}' % (auc, overhead_s, wall_s, resumed)
    )
