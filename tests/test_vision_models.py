"""CIFAR-10 ResNet-20 and ResNet-50 zoo configs on the 8-device CPU mesh:
BatchNorm (batch_stats in extra_vars) trains and evaluates, loss decreases,
record parsers round-trip. Mirrors the reference's cifar10/resnet50 zoo
coverage (reference: model_zoo/cifar10_functional_api, resnet50_subclass)."""

import numpy as np

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.training.model_spec import ModelSpec
from elasticdl_tpu.training.trainer import Trainer


def make_spec(model_def, **model_params):
    cfg = JobConfig(
        model_zoo="model_zoo", model_def=model_def, model_params=model_params
    )
    return ModelSpec.from_config(cfg)


def cifar_batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=(n,)).astype(np.int32)
    images = rng.rand(n, 32, 32, 3).astype(np.float32) * 0.1
    images += labels[:, None, None, None].astype(np.float32) / 10.0
    return {"features": images, "labels": labels, "mask": np.ones((n,), np.float32)}


def test_cifar_resnet20_trains(mesh8):
    spec = make_spec("cifar10.resnet.custom_model", learning_rate=0.05)
    trainer = Trainer(spec, mesh8, seed=0)
    state = trainer.init_state(cifar_batch())
    assert "batch_stats" in state.extra_vars

    losses = []
    for i in range(12):
        state, logs = trainer.train_step(state, cifar_batch(seed=i % 3))
        losses.append(float(logs["loss"]))
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses

    # BatchNorm running stats must have moved away from init (mean 0)
    bn_means = [
        np.asarray(v)
        for k, v in _flat(state.extra_vars["batch_stats"]).items()
        if k.endswith("mean")
    ]
    assert any(np.abs(m).max() > 1e-4 for m in bn_means)

    ms = trainer.eval_step(state, cifar_batch(seed=99), trainer.new_metric_states())
    res = trainer.metric_results(ms)
    assert 0.0 <= res["accuracy"] <= 1.0


def test_resnet50_forward_and_one_step(mesh8):
    # tiny stand-in shapes: 10 classes, 32px inputs — exercises the bottleneck
    # architecture and BN plumbing without ImageNet-sized compute
    spec = make_spec("resnet50.resnet50.custom_model", num_classes=10)
    trainer = Trainer(spec, mesh8, seed=0)
    batch = {
        "features": np.random.RandomState(0).rand(8, 32, 32, 3).astype(np.float32),
        "labels": np.zeros((8,), np.int32),
        "mask": np.ones((8,), np.float32),
    }
    state = trainer.init_state(batch)
    n_params = sum(x.size for x in _flat(state.params).values())
    assert n_params > 20e6  # ResNet-50 trunk is ~23.5M
    state, logs = trainer.train_step(state, batch)
    assert np.isfinite(logs["loss"])
    out = trainer.predict_step(state, batch)
    assert out.shape == (8, 10)


def test_cifar_record_parser():
    from model_zoo.cifar10 import resnet

    parse = resnet.dataset_fn("training", {})
    img = np.arange(3072, dtype=np.uint8)
    rec = bytes([7]) + img.tobytes()
    batch, labels = parse([rec])
    feats = batch[0]
    assert labels[0] == 7 and feats.shape == (32, 32, 1 * 3)
    # channel-major source layout: first 1024 bytes are the red plane
    assert np.allclose(feats[0, 0, 0], 0.0)
    assert np.allclose(feats[0, 1, 0], 1 / 255.0)


def test_resnet50_record_parser():
    from model_zoo.resnet50 import resnet50

    parse = resnet50.dataset_fn("training", {"image_size": 8})
    # full record: 2-byte label + complete image
    img = np.full((8 * 8 * 3,), 128, np.uint8)
    rec = (42).to_bytes(2, "little") + img.tobytes()
    feats, label = parse(rec)
    assert label == 42 and feats.shape == (8, 8, 3)
    assert np.isfinite(feats).all()
    # compact synthetic record: short seed block gets tiled up
    rec = (7).to_bytes(2, "little") + bytes(range(64))
    feats, label = parse(rec)
    assert label == 7 and feats.shape == (8, 8, 3)


def _flat(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out
