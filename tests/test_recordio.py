"""EDLR recordio: native ↔ pure-python cross-compat, corruption detection,
reader integration (reference: RecordIO + pyrecordio role, SURVEY §2.4)."""

import os

import pytest

from elasticdl_tpu.data import recordio as rio


@pytest.fixture(scope="module")
def native_available():
    return rio.build_native() is not None


def write_file(path, records, chunk_bytes=256):
    w = rio.RecordIOWriter(str(path), chunk_bytes=chunk_bytes)
    for r in records:
        w.write(r)
    return w.close()


def records(n=100):
    return [f"record-{i}".encode() * (1 + i % 7) for i in range(n)]


def test_native_builds(native_available):
    assert native_available, "g++ toolchain present; native build must succeed"


def test_roundtrip_native(tmp_path, native_available):
    recs = records()
    n = write_file(tmp_path / "a.rio", recs)
    assert n == 100
    r = rio.open_shard(str(tmp_path / "a.rio"), prefer_native=True)
    if native_available:
        assert isinstance(r, rio._NativeShardReader)
    assert r.num_records == 100
    assert list(r.read(0, 100)) == recs
    assert list(r.read(37, 42)) == recs[37:42]
    assert list(r.read(95, 200)) == recs[95:]
    assert list(r.read(50, 50)) == []


def test_python_reader_reads_native_file(tmp_path, native_available):
    recs = records(60)
    write_file(tmp_path / "b.rio", recs, chunk_bytes=128)
    pyr = rio._PyShardReader(str(tmp_path / "b.rio"))
    assert pyr.num_records == 60
    assert list(pyr.read(10, 20)) == recs[10:20]


def test_python_writer_file_read_by_native(tmp_path, native_available):
    recs = records(40)
    w = rio.RecordIOWriter(
        str(tmp_path / "c.rio"), chunk_bytes=200, prefer_native=False
    )
    for r in recs:
        w.write(r)
    assert w.close() == 40
    if native_available:
        nr = rio._NativeShardReader(w._path, rio._load_lib())
        assert nr.num_records == 40
        assert list(nr.read(5, 15)) == recs[5:15]
    assert list(rio._PyShardReader(w._path).read(0, 40)) == recs


def test_corruption_detected(tmp_path, native_available):
    recs = records(30)
    path = tmp_path / "d.rio"
    write_file(path, recs, chunk_bytes=128)
    data = bytearray(path.read_bytes())
    # flip a byte inside the first chunk's payload
    data[40] ^= 0xFF
    path.write_bytes(bytes(data))
    with pytest.raises(IOError):
        list(rio._PyShardReader(str(path)).read(0, 30))
    if native_available:
        nr = rio._NativeShardReader(str(path), rio._load_lib())
        with pytest.raises(IOError, match="crc"):
            list(nr.read(0, 30))


def test_empty_file_roundtrip(tmp_path):
    n = write_file(tmp_path / "e.rio", [])
    assert n == 0
    r = rio.open_shard(str(tmp_path / "e.rio"))
    assert r.num_records == 0
    assert list(r.read(0, 10)) == []


def test_data_reader_over_directory(tmp_path):
    for i in range(3):
        write_file(tmp_path / f"part-{i}.rio", records(20 + i))
    reader = rio.RecordIODataReader(str(tmp_path))
    shards = reader.create_shards()
    assert [e for _, _, e in shards] == [20, 21, 22]
    recs = list(reader.read_records(shards[1][0], 5, 8))
    assert recs == records(21)[5:8]


def test_factory_dispatch(tmp_path):
    from elasticdl_tpu.data.reader import create_data_reader

    write_file(tmp_path / "x.rio", records(10))
    r = create_data_reader(str(tmp_path / "x.rio"))
    assert sum(e - s for _, s, e in r.create_shards()) == 10


def test_large_records_cross_chunks(tmp_path):
    # records larger than chunk target: one record per chunk
    recs = [os.urandom(5000) for _ in range(8)]
    write_file(tmp_path / "big.rio", recs, chunk_bytes=1024)
    r = rio.open_shard(str(tmp_path / "big.rio"))
    assert list(r.read(0, 8)) == recs


def test_failed_chunk_load_does_not_poison_cache(tmp_path, native_available):
    """A CRC failure in chunk N must not leave chunk N's bytes served under a
    previously cached chunk id (native reader chunk-cache invalidation)."""
    if not native_available:
        pytest.skip("needs native reader")
    recs = records(30)
    path = tmp_path / "poison.rio"
    write_file(path, recs, chunk_bytes=128)
    nr = rio._NativeShardReader(str(path), rio._load_lib())
    # find a record index inside the second chunk
    assert nr.num_records == 30
    first = list(nr.read(0, 2))
    assert first == recs[:2]
    # corrupt a later chunk's payload on disk; reopen to see the new bytes
    data = bytearray(path.read_bytes())
    data[-200] ^= 0xFF
    path.write_bytes(bytes(data))
    nr2 = rio._NativeShardReader(str(path), rio._load_lib())
    assert list(nr2.read(0, 2)) == recs[:2]        # caches chunk 0
    with pytest.raises(IOError):
        list(nr2.read(0, 30))                      # fails in a later chunk
    assert list(nr2.read(0, 2)) == recs[:2]        # chunk 0 still correct


def test_negative_end_matches_python_twin(tmp_path, native_available):
    recs = records(10)
    path = tmp_path / "neg.rio"
    write_file(path, recs)
    assert list(rio._PyShardReader(str(path)).read(0, -1)) == []
    if native_available:
        nr = rio._NativeShardReader(str(path), rio._load_lib())
        assert list(nr.read(0, -1)) == []


def test_directory_of_rio_infers_recordio_reader(tmp_path):
    from elasticdl_tpu.data.reader import create_data_reader

    write_file(tmp_path / "part-00000.rio", records(10))
    r = create_data_reader(str(tmp_path))
    assert isinstance(r, rio.RecordIODataReader)


def test_oversized_record_rejected_not_truncated(tmp_path, native_available):
    """Native writer must reject len > u32 range like the python twin does,
    never silently wrap. (Exercised via the ctypes arg, not a real 4GiB buf.)"""
    if not native_available:
        pytest.skip("needs native writer")
    lib = rio._load_lib()
    h = lib.edlr_writer_open(str(tmp_path / "o.rio").encode(), 1 << 20)
    assert h
    assert lib.edlr_writer_write(h, b"x", (1 << 32) + 100) == -1
    assert lib.edlr_writer_close(h) == 0


@pytest.mark.parametrize("prefer_native", [True, False])
def test_interleaved_generators_survive_lru_eviction(tmp_path, prefer_native):
    """Readers backing a partially-consumed generator are pinned: interleaving
    more generators than the LRU bound must not close files mid-iteration.
    The pure-Python reader is the load-bearing case — it streams chunks from
    the file handle, so a mid-iteration close corrupts it; the native reader
    buffers the whole span up front."""
    n_shards = 12  # > _max_open (8)
    for i in range(n_shards):
        write_file(tmp_path / f"part-{i:02d}.rio", records(10))
    reader = rio.RecordIODataReader(str(tmp_path), prefer_native=prefer_native)
    shards = reader.create_shards()
    gens = [reader.read_records(name, 0, 10) for name, _, _ in shards]
    # start every generator, then round-robin drain them all
    out = [[next(g)] for g in gens]
    for k in range(9):
        for i, g in enumerate(gens):
            out[i].append(next(g))
    for i, recs in enumerate(out):
        assert recs == records(10), f"shard {i} corrupted by eviction"
    # closing (or exhausting) a generator releases its pin
    for g in gens:
        g.close()
    assert len(reader._pins) == 0
