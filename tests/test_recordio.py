"""EDLR recordio: native ↔ pure-python cross-compat, corruption detection,
reader integration (reference: RecordIO + pyrecordio role, SURVEY §2.4)."""

import os
import struct

import numpy as np
import pytest

from elasticdl_tpu.data import recordio as rio


@pytest.fixture(scope="module")
def native_available():
    return rio.build_native() is not None


def write_file(path, records, chunk_bytes=256):
    w = rio.RecordIOWriter(str(path), chunk_bytes=chunk_bytes)
    for r in records:
        w.write(r)
    return w.close()


def records(n=100):
    return [f"record-{i}".encode() * (1 + i % 7) for i in range(n)]


def test_native_builds(native_available):
    assert native_available, "g++ toolchain present; native build must succeed"


def test_roundtrip_native(tmp_path, native_available):
    recs = records()
    n = write_file(tmp_path / "a.rio", recs)
    assert n == 100
    r = rio.open_shard(str(tmp_path / "a.rio"), prefer_native=True)
    if native_available:
        assert isinstance(r, rio._NativeShardReader)
    assert r.num_records == 100
    assert list(r.read(0, 100)) == recs
    assert list(r.read(37, 42)) == recs[37:42]
    assert list(r.read(95, 200)) == recs[95:]
    assert list(r.read(50, 50)) == []


def test_python_reader_reads_native_file(tmp_path, native_available):
    recs = records(60)
    write_file(tmp_path / "b.rio", recs, chunk_bytes=128)
    pyr = rio._PyShardReader(str(tmp_path / "b.rio"))
    assert pyr.num_records == 60
    assert list(pyr.read(10, 20)) == recs[10:20]


def test_python_writer_file_read_by_native(tmp_path, native_available):
    recs = records(40)
    # force the pure-python writer
    w = rio.RecordIOWriter.__new__(rio.RecordIOWriter)
    w._path = str(tmp_path / "c.rio")
    w._native = None
    w.num_records = 0
    w._closed = False
    w._f = open(w._path, "wb")
    w._f.write(rio._FILE_MAGIC + struct.pack("<I", rio._VERSION))
    w._chunk_bytes = 200
    w._payload = bytearray()
    w._chunk_records = 0
    w._index = []
    for r in recs:
        w.write(r)
    assert w.close() == 40
    if native_available:
        nr = rio._NativeShardReader(w._path, rio._load_lib())
        assert nr.num_records == 40
        assert list(nr.read(5, 15)) == recs[5:15]
    assert list(rio._PyShardReader(w._path).read(0, 40)) == recs


def test_corruption_detected(tmp_path, native_available):
    recs = records(30)
    path = tmp_path / "d.rio"
    write_file(path, recs, chunk_bytes=128)
    data = bytearray(path.read_bytes())
    # flip a byte inside the first chunk's payload
    data[40] ^= 0xFF
    path.write_bytes(bytes(data))
    with pytest.raises(IOError):
        list(rio._PyShardReader(str(path)).read(0, 30))
    if native_available:
        nr = rio._NativeShardReader(str(path), rio._load_lib())
        with pytest.raises(IOError, match="crc"):
            list(nr.read(0, 30))


def test_empty_file_roundtrip(tmp_path):
    n = write_file(tmp_path / "e.rio", [])
    assert n == 0
    r = rio.open_shard(str(tmp_path / "e.rio"))
    assert r.num_records == 0
    assert list(r.read(0, 10)) == []


def test_data_reader_over_directory(tmp_path):
    for i in range(3):
        write_file(tmp_path / f"part-{i}.rio", records(20 + i))
    reader = rio.RecordIODataReader(str(tmp_path))
    shards = reader.create_shards()
    assert [e for _, _, e in shards] == [20, 21, 22]
    recs = list(reader.read_records(shards[1][0], 5, 8))
    assert recs == records(21)[5:8]


def test_factory_dispatch(tmp_path):
    from elasticdl_tpu.data.reader import create_data_reader

    write_file(tmp_path / "x.rio", records(10))
    r = create_data_reader(str(tmp_path / "x.rio"))
    assert sum(e - s for _, s, e in r.create_shards()) == 10


def test_large_records_cross_chunks(tmp_path):
    # records larger than chunk target: one record per chunk
    recs = [os.urandom(5000) for _ in range(8)]
    write_file(tmp_path / "big.rio", recs, chunk_bytes=1024)
    r = rio.open_shard(str(tmp_path / "big.rio"))
    assert list(r.read(0, 8)) == recs
