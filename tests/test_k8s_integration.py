"""Cluster-gated k8s integration test: the REAL KubectlApi against a REAL
cluster (SURVEY §4 — the reference's CI ran actual minikube jobs; the unit
suite's scripted-watch tests can't prove kubectl flag/stream compatibility).

Skipped unless `kubectl` is on PATH and can reach a cluster within 10 s —
i.e. it runs on a developer machine with minikube/kind/a test cluster and is
skipped (not absent) in sandboxes without one. The worker pod's command is
patched to a plain `sleep` (EDL_K8S_TEST_IMAGE, default busybox:stable): the
subject under test is the manager's create -> watch -> kill -> watch-driven
relaunch loop, not worker training.
"""

import os
import shutil
import subprocess
import time
import uuid

import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import PodStatus
from elasticdl_tpu.master.k8s_instance_manager import K8sInstanceManager

NAMESPACE = os.environ.get("EDL_K8S_TEST_NAMESPACE", "default")
IMAGE = os.environ.get("EDL_K8S_TEST_IMAGE", "busybox:stable")


_PROBE_CACHE = []


def _cluster_reason():
    """Skip reason, or '' when a cluster is reachable. Evaluated lazily at
    test RUNTIME (not collection — the kubectl probe can take the full 10 s
    request timeout on a machine with kubectl but no cluster) and cached."""
    if _PROBE_CACHE:
        return _PROBE_CACHE[0]
    if shutil.which("kubectl") is None:
        reason = "kubectl not on PATH"
    else:
        try:
            proc = subprocess.run(
                ["kubectl", "get", "namespaces", "--request-timeout=10s"],
                capture_output=True, timeout=20,
            )
            reason = "" if proc.returncode == 0 else (
                "no reachable cluster: "
                + proc.stderr.decode(errors="replace").strip()[-200:]
            )
        except Exception as e:
            reason = f"kubectl probe failed: {e}"
    _PROBE_CACHE.append(reason)
    return reason


@pytest.fixture()
def k8s_cluster():
    reason = _cluster_reason()
    if reason:
        pytest.skip(reason)


def _sleep_pod(cfg, worker_id, pod_name=""):
    from elasticdl_tpu.client.k8s import JOB_LABEL

    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name or f"{cfg.job_name}-worker-{worker_id}",
            "namespace": cfg.namespace,
            "labels": {
                JOB_LABEL: cfg.job_name,
                "app": "elasticdl-tpu",
                "role": "worker",
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "worker",
                "image": IMAGE,
                "command": ["sh", "-c", "sleep 3600"],
            }],
        },
    }


def _wait_for(cond, timeout_s):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.5)
    return False


def test_pod_kill_drives_watch_relaunch(monkeypatch, k8s_cluster):
    """Create a real pod, kill it with an out-of-band `kubectl delete`, and
    assert the manager's watch stream (not any timeout) drives a
    generation-suffixed relaunch that reaches Running again."""
    import elasticdl_tpu.client.k8s as k8s_client

    monkeypatch.setattr(k8s_client, "render_worker_pod", _sleep_pod)
    cfg = JobConfig(
        job_name=f"edl-it-{uuid.uuid4().hex[:8]}",
        model_def="mnist.mnist_cnn.custom_model",
        num_workers=1,
        relaunch_max=2,
        image_name=IMAGE,
        namespace=NAMESPACE,
        job_type="evaluation_only",
    )
    mgr = K8sInstanceManager(cfg)
    try:
        mgr.start_workers()
        # image pulls on a cold cluster can take a while
        assert _wait_for(
            lambda: mgr.statuses().get(0) == PodStatus.RUNNING, 180
        ), f"gen-0 pod never reached Running: {mgr.statuses()}"

        pod0 = f"{cfg.job_name}-worker-0-g0"
        subprocess.run(
            ["kubectl", "-n", NAMESPACE, "delete", "pod", pod0,
             "--wait=false", "--request-timeout=30s"],
            check=True, capture_output=True, timeout=60,
        )

        # watch-driven: DELETED event -> _on_pod_death -> relaunch as -g1
        assert _wait_for(
            lambda: mgr.statuses().get(0) == PodStatus.RUNNING
            and mgr._gen.get(0) == 1,
            180,
        ), f"relaunch never reached Running: {mgr.statuses()}, gen={mgr._gen}"

        get = subprocess.run(
            ["kubectl", "-n", NAMESPACE, "get", "pod",
             f"{cfg.job_name}-worker-0-g1", "-o", "jsonpath={.status.phase}",
             "--request-timeout=30s"],
            capture_output=True, timeout=60,
        )
        assert get.returncode == 0 and get.stdout.decode() == "Running"
    finally:
        mgr.stop()
        subprocess.run(
            ["kubectl", "-n", NAMESPACE, "delete", "pods", "-l",
             f"{k8s_client.JOB_LABEL}={cfg.job_name}", "--wait=false",
             "--request-timeout=30s"],
            capture_output=True, timeout=60,
        )
