"""Incident correlator + CLI (observability/incident.py) over checked-in
golden fixtures (tests/fixtures/incident/): multi-role bundle merge with
cross-source dedup, the journal tail, torn-bundle tolerance, and the
--strict / usage exit-code conventions shared with the trace analyzer."""

import json
import os

import pytest

from elasticdl_tpu.observability import incident

FIXTURES = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "incident"
)
CLEAN = os.path.join(FIXTURES, "clean")
TORN = os.path.join(FIXTURES, "torn")
BAD = os.path.join(FIXTURES, "badschema")


def test_multi_role_merge_and_timeline_order():
    report = incident.correlate([CLEAN])
    assert {b["role"] for b in report["bundles"]} == {"master", "worker-0"}
    assert report["torn_bundles"] == []
    assert report["strict_violations"] == []

    names = [e["name"] for e in report["timeline"]]
    # the story reads in order: straggler flag -> crash -> recovery ->
    # reconnect -> the dumps that preserved it all
    for earlier, later in (
        ("cluster.straggler", "master.crash"),
        ("master.crash", "master.recovered"),
        ("master.recovered", "worker.reconnect"),
        ("worker.reconnect", "flight.dump"),
    ):
        assert names.index(earlier) < names.index(later), names

    # cross-source dedup: the rescale span exists in BOTH bundles AND the
    # trace.jsonl, but appears on the timeline exactly once
    assert names.count("rescale") == 1

    # the log line captured by the ring is on the timeline
    assert any(
        e["kind"] == "log" and "CRASHED" in e.get("msg", "")
        for e in report["timeline"]
    )


def test_journal_tail_and_health_snapshots_join_the_report():
    report = incident.correlate([CLEAN])
    journal = report["journal"]
    assert journal["generations"] == [2]
    assert journal["records"] == 5
    assert any(rec.get("t") == "world_version" for rec in journal["tail"])
    health = report["health"]
    assert len(health) == 1 and health[0]["straggler_count"] == 1


def test_resize_spans_reuse_analyzer_critical_path():
    report = incident.correlate([CLEAN])
    traces = report["traces"]["traces"]
    rescale = [t for t in traces if t["trace_id"] == "aaaa000011112222"]
    assert rescale and rescale[0]["is_resize"]
    tl = rescale[0]["timeline"]
    assert tl["wall_s"] == pytest.approx(3.0)
    assert tl["phases"].get("compile", 0) == pytest.approx(2.0)


def test_render_text_places_crash_and_reconnect():
    report = incident.correlate([CLEAN])
    text = incident.render_text(report)
    assert "master.crash" in text and "worker.reconnect" in text
    assert text.index("master.crash") < text.index("worker.reconnect")
    assert "flight bundle(s)" in text and "journal:" in text


def test_torn_bundle_tolerated_even_strict(capsys):
    report = incident.correlate([TORN])
    assert len(report["torn_bundles"]) == 1
    assert "flight-worker-1-102.json" in report["torn_bundles"][0]
    # the whole bundles still merged
    assert {b["role"] for b in report["bundles"]} == {"master", "worker-0"}
    rc = incident.main([TORN, "--strict"])
    capsys.readouterr()
    assert rc == 0         # torn = the documented crash shape, never red


def test_bad_schema_bundle_is_strict_violation(capsys):
    rc = incident.main([BAD])
    capsys.readouterr()
    assert rc == 0         # advisory without --strict
    rc = incident.main([BAD, "--strict"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "flight-worker-2-103.json" in err


def test_no_inputs_and_unreadable_are_usage_errors(tmp_path, capsys):
    rc = incident.main([str(tmp_path)])
    assert rc == 2
    capsys.readouterr()
    missing = str(tmp_path / "flight-nope-1.json")
    rc = incident.main([missing])
    capsys.readouterr()
    assert rc == 2


def test_json_report_roundtrips(capsys):
    rc = incident.main([CLEAN, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    report = json.loads(out)
    assert report["roles"] and report["timeline"]


# ---------------------------------------------------------------------- #
# cluster.alert merge (ISSUE 11 golden fixture: tests/fixtures/incident/
# alerts/ — an edge-triggered page alert whose event is in BOTH the
# master's trace and the flight bundle its onset dumped)

ALERTS = os.path.join(FIXTURES, "alerts")


def test_alert_events_merge_into_timeline_once():
    report = incident.correlate([ALERTS])
    alert_entries = [e for e in report["timeline"]
                     if e["name"] == "cluster.alert"]
    # the same onset lives in the trace AND the bundle's ring: the
    # cross-source dedup must keep exactly ONE timeline entry
    assert len(alert_entries) == 1
    entry = alert_entries[0]
    assert entry["rule"] == "embedding_pull_p99"
    assert entry["severity"] == "page"
    assert entry["value"] == 412.5 and entry["threshold"] == 250.0
    cleared = [e for e in report["timeline"]
               if e["name"] == "cluster.alert_cleared"]
    assert len(cleared) == 1
    # alerts are KEY events: the curated view must carry both
    key_names = [e["name"] for e in report["key_events"]]
    assert "cluster.alert" in key_names
    assert "cluster.alert_cleared" in key_names
    # ordering: straggler context precedes the alert precedes the clear
    names = [e["name"] for e in report["timeline"]]
    assert names.index("cluster.straggler") < names.index("cluster.alert")
    assert names.index("cluster.alert") < names.index(
        "cluster.alert_cleared")


def test_alert_fixture_strict_clean_and_renders_age(capsys):
    rc = incident.main([ALERTS, "--strict"])
    out = capsys.readouterr().out
    assert rc == 0
    # the alert's identity is readable in the text timeline
    assert "cluster.alert" in out
    assert "rule=embedding_pull_p99" in out
    # the health snapshot's serve-time staleness stamp (ISSUE 11
    # satellite) surfaces next to the rollup line
    assert "rollup age 2.5s" in out
