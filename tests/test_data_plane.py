"""Partition-tolerant gRPC embedding data plane (ISSUE 15).

Covers the wire (codec, end-to-end equivalence with LocalTransport,
error mapping, deadline propagation), the robustness layer (deadline
budgets, per-owner breakers + channel refresh, hedged reads, the
degraded-mode ladder), the push queue (bounded, journaled, in-order
drain, replay identity), the exactly-once fence under response-side
(.recv) fault drops over the REAL transport, and the owner address
book (registration -> shard-map response -> journal replay).

Everything runs host-mode stores on loopback gRPC — no jax, no
subprocesses; fast enough for tier-1.
"""

import socket
import time

import numpy as np
import pytest

from elasticdl_tpu.common import faults
from elasticdl_tpu.embedding import data_plane as dp
from elasticdl_tpu.embedding import sharding, tier
from elasticdl_tpu.embedding.store import (
    EmbeddingShardStore,
    StaleShardMapError,
)
from elasticdl_tpu.embedding.transport import (
    DEGRADED_READS,
    LocalTransport,
    OwnerUnavailableError,
    SimWireTransport,
)

SPEC = sharding.TableSpec("users", vocab=4096, dim=8, seed=3)


def make_view(num_shards=2, owners=(0, 0), replicas=((1,), (1,)),
              version=1):
    return sharding.ShardMapView(
        version=version, num_shards=num_shards, owners=tuple(owners),
        tables=(SPEC,), replicas=tuple(tuple(r) for r in replicas),
    )


@pytest.fixture()
def served_pair():
    """(primary store+server, replica store+server, addrs) — owner 0
    primary for both shards, owner 1 holding synced replica copies."""
    view = make_view()
    st0 = EmbeddingShardStore(0, device=False)
    st0.attach(view)
    st0.set_delta_logging(True)
    srv0 = dp.EmbeddingDataServer(st0)
    p0 = srv0.start()
    st1 = EmbeddingShardStore(1, device=False)
    st1.attach(view)
    srv1 = dp.EmbeddingDataServer(st1)
    p1 = srv1.start()
    peer = dp.GrpcTransport({0: f"127.0.0.1:{p0}"})
    for s in range(view.num_shards):
        st1.sync_replica_from(peer, 0, "users", s)
    yield {
        "view": view, "st0": st0, "st1": st1,
        "addr0": f"127.0.0.1:{p0}", "addr1": f"127.0.0.1:{p1}",
        "sync": lambda: [st1.sync_replica_from(peer, 0, "users", s)
                         for s in range(view.num_shards)],
    }
    srv0.stop()
    srv1.stop()
    peer.close()


@pytest.fixture()
def blackhole():
    """A listener that accepts and never answers — the worst partition
    shape (connects succeed, every call hangs to its deadline)."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(16)
    yield f"127.0.0.1:{sock.getsockname()[1]}"
    sock.close()


# ------------------------------------------------------------------ #
# wire


def test_codec_round_trip():
    ids = np.array([3, -1, 7, 4095], np.int32)
    assert np.array_equal(dp.ids_from_bytes(dp.ids_to_bytes(ids)), ids)
    rows = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = dp.rows_from_bytes(dp.rows_to_bytes(rows), 4)
    assert np.array_equal(out, rows)


def test_grpc_end_to_end_matches_local(served_pair):
    pair = served_pair
    tr = dp.GrpcTransport({0: pair["addr0"]})
    local = LocalTransport()
    local.register(pair["st0"])
    ids = np.array([0, 2, 4, -1], np.int32)
    rows_g, wm_g = tr.pull(0, "users", 0, ids, map_version=1,
                           with_watermark=True)
    rows_l, wm_l = local.pull(0, "users", 0, ids, map_version=1,
                              with_watermark=True)
    assert wm_g == wm_l and np.allclose(rows_g, rows_l)
    assert np.all(rows_g[3] == 0.0)   # sentinel row zeroed over the wire

    g = np.ones((4, 8), np.float32)
    ack_g = tr.push(0, "users", 0, ids, g, client_id="cg", seq=1,
                    map_version=1, with_watermark=True)
    assert ack_g[0] is True
    # duplicate seq: fence holds over the wire, watermark still returns
    dup = tr.push(0, "users", 0, ids, g, client_id="cg", seq=1,
                  map_version=1, with_watermark=True)
    assert dup == (False, ack_g[1])

    payload_g = tr.fetch_shard(0, "users", 0)
    payload_l = local.fetch_shard(0, "users", 0)
    assert np.allclose(payload_g["rows"], payload_l["rows"])
    assert payload_g["applied"] == payload_l["applied"]
    assert payload_g["wm"] == payload_l["wm"]
    assert (tr.shard_watermark(0, "users", 0)
            == local.shard_watermark(0, "users", 0))
    delta_g = tr.fetch_delta(0, "users", 0, 0)
    delta_l = local.fetch_delta(0, "users", 0, 0)
    assert delta_g["wm"] == delta_l["wm"]
    assert len(delta_g["entries"]) == len(delta_l["entries"])
    e_g, e_l = delta_g["entries"][0], delta_l["entries"][0]
    assert e_g["seq"] == e_l["seq"] and e_g["client_id"] == e_l["client_id"]
    assert np.allclose(e_g["rows"], e_l["rows"])
    # too-far-back delta: None on both transports
    assert tr.fetch_delta(0, "users", 0, -5) is None
    tr.close()


def test_grpc_errors_map_to_tier_vocabulary(served_pair, blackhole):
    pair = served_pair
    tr = dp.GrpcTransport({0: pair["addr0"], 9: blackhole})
    ids = np.arange(4, dtype=np.int32)
    with pytest.raises(StaleShardMapError):
        tr.pull(0, "users", 0, ids, map_version=99, with_watermark=True)
    with pytest.raises(OwnerUnavailableError):
        tr.pull(7, "users", 0, ids)          # no address at all
    t0 = time.perf_counter()
    with pytest.raises(dp.DeadlineExceededError):
        tr.pull(9, "users", 0, ids, map_version=1, timeout_s=0.2)
    assert 0.15 <= time.perf_counter() - t0 < 2.0
    tr.close()


def test_replica_pull_and_watermark_over_grpc(served_pair):
    pair = served_pair
    tr = dp.GrpcTransport({1: pair["addr1"]})
    ids = np.arange(4, dtype=np.int32)
    rows, wm = tr.pull(1, "users", 0, ids, map_version=1,
                       with_watermark=True, replica=True)
    assert rows.shape == (4, 8)
    assert tr.shard_watermark(1, "users", 0, replica=True) == wm
    # a replica store rejects pushes as stale-map over the wire too
    with pytest.raises(StaleShardMapError):
        tr.push(1, "users", 0, ids, np.ones((4, 8), np.float32),
                client_id="c", seq=1, map_version=1)
    tr.close()


# ------------------------------------------------------------------ #
# response-side fault sites + exactly-once over the real wire


def test_recv_fault_sites_exist_on_local_transport():
    st = EmbeddingShardStore(0, device=False)
    st.attach(make_view(replicas=((), ())))
    local = LocalTransport()
    local.register(st)
    ids = np.arange(4, dtype=np.int32)
    inj = faults.install("emb.pull.recv:drop@at=1")
    try:
        with pytest.raises(faults.FaultInjected):
            local.pull(0, "users", 0, ids, map_version=1,
                       with_watermark=True)
        # the owner DID serve before the reply was lost
        assert inj.hits("emb.pull.recv") == 1
        local.pull(0, "users", 0, ids, map_version=1, with_watermark=True)
    finally:
        faults.uninstall()
    inj = faults.install("emb.fetch_delta.recv:drop@at=1")
    try:
        with pytest.raises(faults.FaultInjected):
            local.fetch_delta(0, "users", 0, 0)
        assert inj.hits("emb.fetch_delta.recv") == 1
    finally:
        faults.uninstall()


def test_lost_push_ack_over_grpc_absorbed_by_seq_fence(served_pair):
    """The PR 10 lost-ack test covered LocalTransport only; this pins
    the same contract over the REAL transport: a push whose reply is
    dropped AFTER the owner applied re-sends under the same seq through
    the robustness layer, and the store's fence turns the duplicate
    into an ack with no second apply."""
    pair = served_pair
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: pair["addr0"]}),
        policies={"push": dp.CallPolicy(budget_s=2.0, max_attempts=3)},
        queue_max=0, backoff_base_s=0.001,
    )
    ids = np.arange(4, dtype=np.int32)
    g = np.ones((4, 8), np.float32)
    before = np.array(pair["st0"].pull("users", 0, ids))
    faults.install("emb.push.recv:drop@at=1")
    try:
        applied, wm = res.push(0, "users", 0, ids, g, client_id="lost",
                               seq=1, map_version=1, with_watermark=True)
    finally:
        faults.uninstall()
    # the retried send was deduped: applied=False is the duplicate ack
    assert applied is False
    after = np.array(pair["st0"].pull("users", 0, ids))
    assert np.allclose(after - before, g)      # exactly once, not twice
    res.close()


# ------------------------------------------------------------------ #
# robustness layer: budgets, breakers, hedging, degraded ladder


def test_deadline_budget_bounds_the_whole_call(blackhole):
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: blackhole}),
        policies={"pull": dp.CallPolicy(budget_s=0.4, max_attempts=3)},
        hedge=False, queue_max=0,
    )
    ids = np.arange(4, dtype=np.int32)
    t0 = time.perf_counter()
    with pytest.raises(OwnerUnavailableError):
        res.pull(0, "users", 0, ids, map_version=1, with_watermark=True)
    wall = time.perf_counter() - t0
    # retries SPLIT the budget; they never extend it
    assert wall < 1.5, wall
    res.close()


def test_breaker_opens_fails_fast_and_refreshes_channel(blackhole):
    refreshed = []
    inner = dp.GrpcTransport({0: blackhole})
    orig = inner.refresh_channel
    inner.refresh_channel = lambda owner: (refreshed.append(owner),
                                           orig(owner))
    res = dp.ResilientTransport(
        inner,
        policies={"pull": dp.CallPolicy(budget_s=0.15, max_attempts=1)},
        hedge=False, queue_max=0, breaker_failures=2,
        breaker_cooldown_s=30.0, refresh_after=2,
    )
    from elasticdl_tpu.proto import service as proto_service

    master_open0 = proto_service._BREAKER_OPEN.value()
    master_trips0 = proto_service._BREAKER_TRIPS.value()
    ids = np.arange(4, dtype=np.int32)
    for _ in range(2):
        with pytest.raises(OwnerUnavailableError):
            res.pull(0, "users", 0, ids, map_version=1)
    assert res.owner_degraded(0)
    assert refreshed == [0]       # wedge recovery kicked in
    # the per-owner breaker must NOT read as a master outage: the
    # inherited CircuitBreaker runs telemetry-free for the data plane
    assert proto_service._BREAKER_OPEN.value() == master_open0
    assert proto_service._BREAKER_TRIPS.value() == master_trips0
    t0 = time.perf_counter()
    with pytest.raises(OwnerUnavailableError):
        res.pull(0, "users", 0, ids, map_version=1)
    # breaker open -> fail fast, not another 150 ms wire wait
    assert time.perf_counter() - t0 < 0.1
    res.close()


def test_hedged_read_serves_from_replica_when_primary_partitions(
        served_pair, blackhole):
    pair = served_pair
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: pair["addr0"], 1: pair["addr1"]}),
        policies={"pull": dp.CallPolicy(budget_s=1.0, max_attempts=2)},
        staleness_bound=4, view_fn=lambda: pair["view"],
        hedge_delay_ms=5.0, queue_max=0, breaker_cooldown_s=30.0,
    )
    ids = np.arange(4, dtype=np.int32)
    healthy, wm0 = res.pull(0, "users", 0, ids, map_version=1,
                            with_watermark=True)
    deg0 = DEGRADED_READS.value(mode="replica")
    res.update_addresses({0: blackhole})
    t0 = time.perf_counter()
    rows, wm = res.pull(0, "users", 0, ids, map_version=1,
                        with_watermark=True)
    wall = time.perf_counter() - t0
    assert np.allclose(rows, healthy) and wm == wm0
    assert wall < 0.5, wall       # hedge delay + replica rtt, not budget
    assert DEGRADED_READS.value(mode="replica") > deg0
    res.close()


def test_hedged_read_refuses_stale_replica(served_pair, blackhole):
    """Credibility: a replica further behind than the staleness bound
    must NOT win the hedge — a partition is not a license to serve
    arbitrarily stale rows (the degraded ladder's 'block' rung)."""
    pair = served_pair
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: pair["addr0"], 1: pair["addr1"]}),
        policies={"pull": dp.CallPolicy(budget_s=0.4, max_attempts=2)},
        staleness_bound=1, view_fn=lambda: pair["view"],
        hedge_delay_ms=5.0, queue_max=0,
    )
    ids = np.arange(4, dtype=np.int32)
    # advance the primary past the replica's sync point by > bound
    for seq in (1, 2, 3):
        res.push(0, "users", 0, ids, np.ones((4, 8), np.float32),
                 client_id="w", seq=seq, map_version=1,
                 with_watermark=True)
    assert res.observed_wm("users", 0) >= 3
    blocked0 = DEGRADED_READS.value(mode="blocked")
    res.update_addresses({0: blackhole})
    with pytest.raises(OwnerUnavailableError):
        res.pull(0, "users", 0, ids, map_version=1, with_watermark=True)
    assert DEGRADED_READS.value(mode="blocked") > blocked0
    # after the replica catches up, the same read serves
    pair["sync"]()
    rows, wm = res.pull(0, "users", 0, ids, map_version=1,
                        with_watermark=True)
    assert wm >= 3
    res.close()


# ------------------------------------------------------------------ #
# degraded cache rung + the staleness contract (satellite)


def _reader_client(pair, blackhole_addr=None, staleness=2):
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: pair["addr0"], 1: pair["addr1"]}),
        policies={
            "pull": dp.CallPolicy(budget_s=0.6, max_attempts=2),
            "watermark": dp.CallPolicy(budget_s=0.3, max_attempts=1),
        },
        staleness_bound=staleness, view_fn=lambda: pair["view"],
        hedge_delay_ms=5.0, queue_max=0, breaker_failures=1,
        breaker_cooldown_s=30.0,
    )
    client = tier.EmbeddingTierClient(
        lambda: pair["view"], res, client_id="reader",
        cache_rows=512, cache_staleness=staleness,
        max_retries=2, retry_backoff_s=0.01,
    )
    client.wm_probe_every = 1
    return res, client


def test_degraded_cache_hits_are_attributed(served_pair, blackhole):
    pair = served_pair
    res, client = _reader_client(pair)
    ids = np.array([1, 3, 5, 7], np.int64)
    warm = client.pull("users", ids)               # cache warms
    res.update_addresses({0: blackhole})
    # open the breaker: one failed/hedged read condemns the primary
    client.pull("users", ids + 2)
    assert res.owner_degraded(0)
    cache0 = DEGRADED_READS.value(mode="cache")
    again = client.pull("users", ids)              # pure cache hits
    assert np.allclose(again, warm)
    assert DEGRADED_READS.value(mode="cache") > cache0
    client.close()
    res.close()


def test_staleness_bound_honored_during_partition_with_foreign_pushes(
        served_pair, blackhole):
    """THE contract test (satellite): reader partitioned from the
    primary, a foreign writer keeps pushing. The reader's cached row
    must never be served once the owner is more than the staleness
    bound past it — the replica-probe fallback is what keeps the bound
    enforceable, and the read must come back FRESH (via the replica),
    not stale-from-cache."""
    pair = served_pair
    staleness = 2
    res, client = _reader_client(pair, staleness=staleness)
    ids = np.array([4, 6], np.int64)               # shard 0 rows
    stale_rows = client.pull("users", ids)         # cached at wm=0
    # partition the reader from the primary
    res.update_addresses({0: blackhole})
    client.pull("users", np.array([8, 10], np.int64))  # trips the breaker
    assert res.owner_degraded(0)
    # foreign writer pushes K > staleness bound to the REAL primary
    writer = dp.GrpcTransport({0: pair["addr0"]})
    delta = np.ones((2, 8), np.float32)
    for seq in (1, 2, 3):
        writer.push(0, "users", 0,
                    np.array([2, 3], np.int32),     # local rows of 4, 6
                    delta, client_id="foreign", seq=seq, map_version=1,
                    with_watermark=True)
    pair["sync"]()                                  # replica catches up
    # the reader's next lookups: a full-hit read first probes (primary
    # dead -> REPLICA watermark = 3 > 0 + staleness) — the stale row
    # must evict and the re-fetch must carry the foreign pushes
    fresh = None
    for _ in range(4):          # probe cadence is per full-hit lookup
        fresh = client.pull("users", ids)
    assert np.allclose(fresh, stale_rows + 3 * delta), (
        "reader served a row beyond the staleness bound during the "
        "partition")
    client.close()
    res.close()
    writer.close()


# ------------------------------------------------------------------ #
# push queue: bounded, journaled, in-order drain


def test_push_queue_bounded_and_replays_in_order(served_pair, blackhole,
                                                 tmp_path):
    pair = served_pair
    journal = str(tmp_path / "pq.jsonl")
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: pair["addr0"]}),
        policies={"push": dp.CallPolicy(budget_s=0.2, max_attempts=1)},
        hedge=False, queue_journal=journal, queue_max=3,
        breaker_failures=1, breaker_cooldown_s=0.2,
    )
    ids = np.arange(4, dtype=np.int32)
    g = np.ones((4, 8), np.float32)
    before = np.array(pair["st0"].pull("users", 0, ids))
    res.update_addresses({0: blackhole})
    for seq in (1, 2, 3):
        ack = res.push(0, "users", 0, ids, g * seq, client_id="q",
                       seq=seq, map_version=1, with_watermark=True)
        assert ack[0] is False     # parked, honestly not-applied
    assert res.queue.depth(0) == 3
    # bounded: the 4th push is refused, never silently buffered
    with pytest.raises(OwnerUnavailableError):
        res.push(0, "users", 0, ids, g, client_id="q", seq=4,
                 map_version=1, with_watermark=True)
    # heal -> cooldown -> a NEW push drains the backlog first (order
    # fence), then applies itself
    res.update_addresses({0: pair["addr0"]})
    time.sleep(0.25)
    applied, wm = res.push(0, "users", 0, ids, g * 4, client_id="q",
                           seq=4, map_version=1, with_watermark=True)
    assert applied is True and wm == 4
    assert res.queue.depth() == 0
    after = np.array(pair["st0"].pull("users", 0, ids))
    assert np.allclose(after - before, g * (1 + 2 + 3 + 4))
    replay = dp.PushQueue.replay_journal(journal)
    assert [e["seq"] for e in replay["enqueued"]] == [1, 2, 3]
    assert [e["seq"] for e in replay["drained"]] == [1, 2, 3]
    assert np.allclose(replay["enqueued"][1]["rows"], g * 2)
    res.close()


def test_drain_stops_at_first_failure_preserving_order(served_pair,
                                                       blackhole):
    pair = served_pair
    res = dp.ResilientTransport(
        dp.GrpcTransport({0: pair["addr0"]}),
        policies={"push": dp.CallPolicy(budget_s=0.15, max_attempts=1)},
        hedge=False, queue_max=8, breaker_failures=1,
        breaker_cooldown_s=0.1,
    )
    ids = np.arange(2, dtype=np.int32)
    g = np.ones((2, 8), np.float32)
    res.update_addresses({0: blackhole})
    for seq in (1, 2):
        res.push(0, "users", 0, ids, g, client_id="d", seq=seq,
                 map_version=1)
    # still partitioned: the drain attempt fails and the backlog stays
    # whole and ordered
    time.sleep(0.15)
    assert res.drain_queued() == 0
    assert res.queue.depth(0) == 2
    res.update_addresses({0: pair["addr0"]})
    time.sleep(0.15)
    assert res.drain_queued() == 2
    assert res.queue.depth() == 0
    res.close()


# ------------------------------------------------------------------ #
# owner address book


def test_address_book_rides_registration_and_shard_map(tmp_path):
    from elasticdl_tpu.embedding.sharding import ShardMapOwner
    from elasticdl_tpu.master.journal import ControlPlaneJournal
    from elasticdl_tpu.master.membership import Membership
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    journal = ControlPlaneJournal(str(tmp_path))
    membership = Membership(journal=journal)
    dispatcher = TaskDispatcher(
        training_shards=[("t", 0, 40)], records_per_task=10,
        shuffle=False,
    )
    emb = ShardMapOwner(num_shards=2, journal=journal)
    emb.register_table(SPEC)
    servicer = MasterServicer(dispatcher, membership, embedding=emb)
    resp = servicer.RegisterWorker(
        pb.RegisterWorkerRequest(
            worker_name="w0", data_plane_addr="127.0.0.1:1234"),
        None,
    )
    servicer.RegisterWorker(
        pb.RegisterWorkerRequest(worker_name="w1"), None)  # no endpoint
    map_resp = servicer.GetEmbeddingShardMap(
        pb.GetEmbeddingShardMapRequest(worker_id=resp.worker_id), None)
    assert list(map_resp.addr_worker_ids) == [resp.worker_id]
    assert list(map_resp.addrs) == ["127.0.0.1:1234"]
    view = tier.view_from_response(map_resp)
    assert view.addrs == ((resp.worker_id, "127.0.0.1:1234"),)
    journal.close()

    # a successor master replays the SAME address book
    successor = ControlPlaneJournal(str(tmp_path))
    restored = Membership(journal=successor)
    assert restored.data_addresses() == [
        (resp.worker_id, "127.0.0.1:1234")]
    successor.close()


def test_tier_refresh_adopts_address_book(served_pair):
    pair = served_pair
    tr = dp.GrpcTransport()
    view_with_addrs = sharding.ShardMapView(
        version=1, num_shards=2, owners=(0, 0), tables=(SPEC,),
        addrs=((0, pair["addr0"]),),
    )
    client = tier.EmbeddingTierClient(
        lambda: view_with_addrs, tr, client_id="bookworm")
    # the refresh inside __init__ adopted the book: pulls route
    rows = client.pull("users", np.array([1, 2], np.int64))
    assert rows.shape == (2, 8)
    assert tr.address_of(0) == pair["addr0"]
    client.close()
    tr.close()


# ------------------------------------------------------------------ #
# sim wire behind the shared contract (satellite)


def test_sim_wire_transport_implements_the_contract():
    st = EmbeddingShardStore(0, device=False)
    st.attach(make_view(replicas=((), ())))
    local = LocalTransport()
    local.register(st)
    sim = SimWireTransport(local, call_us=200, row_us=1)
    ids = np.arange(8, dtype=np.int32)
    t0 = time.perf_counter()
    rows, wm = sim.pull(0, "users", 0, ids, map_version=1,
                        with_watermark=True)
    assert time.perf_counter() - t0 >= 200e-6     # the modeled wire
    bare, _ = local.pull(0, "users", 0, ids, map_version=1,
                         with_watermark=True)
    assert np.allclose(rows, bare)
    assert sim.shard_watermark(0, "users", 0) == 0
    assert sim.owners() == [0]                    # registry passthrough


def test_resilient_transport_over_local_transport():
    """The robustness layer composes over ANY transport — deadline
    budgets degrade to retry bounds when the inner has no wire."""
    st = EmbeddingShardStore(0, device=False)
    st.attach(make_view(replicas=((), ())))
    local = LocalTransport()
    local.register(st)
    res = dp.ResilientTransport(local, queue_max=0)
    ids = np.arange(4, dtype=np.int32)
    rows, wm = res.pull(0, "users", 0, ids, map_version=1,
                        with_watermark=True)
    assert rows.shape == (4, 8) and wm == 0
    applied, wm = res.push(0, "users", 0, ids,
                           np.ones((4, 8), np.float32),
                           client_id="c", seq=1, map_version=1,
                           with_watermark=True)
    assert applied is True and wm == 1
    local.deregister(0)
    with pytest.raises(OwnerUnavailableError):
        res.pull(0, "users", 0, ids, map_version=1, with_watermark=True)
    res.close()
