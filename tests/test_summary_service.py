"""Summary service (reference: tensorboard_service.py) — JSONL event stream
always, TensorBoard event files when TF is importable."""

import glob
import json

from elasticdl_tpu.master.summary_service import SummaryService


def test_summary_service_writes_train_and_eval(tmp_path):
    svc = SummaryService(str(tmp_path))
    svc.on_task_report(model_version=10, loss_sum=6.0, loss_count=3)
    svc.on_task_report(model_version=20, loss_sum=2.0, loss_count=2)
    svc.on_task_report(model_version=30, loss_sum=0.0, loss_count=0)  # no-op
    svc.on_eval_results(20, {"auc": 0.8, "accuracy": 0.7})
    svc.close()

    train = [
        json.loads(l)
        for l in open(tmp_path / "train" / "events.jsonl").read().splitlines()
    ]
    assert [(r["step"], r["loss"]) for r in train] == [(10, 2.0), (20, 1.0)]
    ev = [
        json.loads(l)
        for l in open(tmp_path / "eval" / "events.jsonl").read().splitlines()
    ]
    assert ev[0]["step"] == 20 and ev[0]["auc"] == 0.8

    try:
        import tensorflow  # noqa: F401
    except ImportError:
        return
    # TB event files mirror the scalars
    assert glob.glob(str(tmp_path / "train" / "events.out.tfevents.*"))
    assert glob.glob(str(tmp_path / "eval" / "events.out.tfevents.*"))
