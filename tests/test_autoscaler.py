"""Closed-loop autoscaler (ISSUE 14): deterministic policy unit tests —
cost gate, cooldown, hysteresis hold, world bounds, action budget,
conflicting signals, journaled decisions (applied + suppressed) with
replay-inherited cooldown — plus the satellite pins: hook failures are
counted (edl_hook_errors_total), the straggler quorum is configurable
with a floor of 2 (a 2-worker fleet CAN flag its straggler), and the
fleet series read "no data" (absent), never fake zeros, when reporters
churn away mid-poll. Jax-free and fast."""

import json
import time
from dataclasses import asdict

import pytest

from elasticdl_tpu.master.autoscaler import (
    Autoscaler,
    CostModel,
    ProcessManagerTarget,
)
from elasticdl_tpu.master.journal import (
    ControlPlaneJournal,
    replay_lines,
)
from elasticdl_tpu.observability.health import ClusterHealth
from elasticdl_tpu.observability.registry import default_registry


class Clock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeTarget:
    def __init__(self, world=4, ok=True):
        self.world = world
        self.ok = ok
        self.calls = []

    def world_size(self):
        return self.world

    def evict(self, worker_id, worker_name=""):
        self.calls.append(("evict", worker_id))
        if self.ok:
            self.world -= 1
        return self.ok

    def grow(self):
        self.calls.append(("grow", None))
        if self.ok:
            self.world += 1
        return self.ok

    def shrink(self):
        self.calls.append(("shrink", None))
        if self.ok:
            self.world -= 1
        return self.ok


def straggler_info(wid=3, p50=0.050, med=0.005):
    return {
        "worker_id": wid, "worker_name": f"w{wid}", "score": 12.0,
        "step_time_p50_s": p50, "median_step_time_s": med,
    }


def make(clock=None, target=None, journal=None, **kw):
    kw.setdefault("cooldown_s", 60.0)
    kw.setdefault("hold_s", 0.0)
    kw.setdefault("action_budget", 4)
    kw.setdefault("cost_model", CostModel(rescale_cost_s=1.0,
                                          horizon_s=300.0))
    a = Autoscaler(journal=journal, clock=clock or Clock(), **kw)
    if target is not None:
        a.bind_target(target)
    return a


# ------------------------------------------------------------------ #
# cost model


def test_cost_model_projections_and_ewma():
    cm = CostModel(rescale_cost_s=10.0, horizon_s=100.0)
    # evict: slowdown 0.9 * world 4 * horizon 100 = 360 vs cost 40
    p = cm.project("evict", 4, straggler_info(p50=0.050, med=0.005))
    assert p["gain_s"] == pytest.approx(0.9 * 4 * 100, rel=1e-3)
    assert p["cost_s"] == 40.0
    # grow: one worker's horizon vs the fleet's recovery bill
    p = cm.project("grow", 4, {})
    assert p == {"gain_s": 100.0, "cost_s": 40.0}
    # shrink: the freed worker's data_wait fraction
    p = cm.project("shrink", 4, {"value": 0.8})
    assert p["gain_s"] == pytest.approx(80.0)
    assert p["cost_s"] == 30.0   # survivors pay
    # observed recoveries move the estimate (EWMA, never raises)
    cm.observe_recovery(2.0)
    assert cm.rescale_cost_s == pytest.approx(6.0)
    cm.observe_recovery("garbage")
    cm.observe_recovery(-1)
    assert cm.observed_recoveries == 1


def test_cost_gate_suppresses_marginal_actions():
    clock = Clock()
    target = FakeTarget(world=4)
    # a barely-slow straggler: slowdown ~0.17, gain 0.17*4*10 = 6.7 <
    # cost 5*4 = 20 -> suppressed
    a = make(clock, target,
             cost_model=CostModel(rescale_cost_s=5.0, horizon_s=10.0))
    a._on_straggler(straggler_info(p50=0.006, med=0.005))
    assert a.evaluate() is None
    assert target.calls == []
    snap = a.snapshot()
    assert snap["last_decision"]["suppress_reason"] == "cost_gate"
    # a real straggler clears the gate
    a2 = make(clock, FakeTarget(world=4),
              cost_model=CostModel(rescale_cost_s=5.0, horizon_s=10.0))
    a2._on_straggler(straggler_info(p50=0.100, med=0.005))
    assert a2.evaluate() is not None


# ------------------------------------------------------------------ #
# gates: cooldown, hold, bounds, budget, conflicts


def test_evict_applies_then_cooldown_suppresses_then_reopens():
    clock = Clock()
    target = FakeTarget(world=4)
    a = make(clock, target, cooldown_s=100.0)
    a._on_straggler(straggler_info(wid=3))
    d = a.evaluate()
    assert d["decision"] == "applied" and d["kind"] == "evict"
    assert target.calls == [("evict", 3)]
    # a new straggler inside the cooldown window: suppressed
    clock.advance(10)
    a._on_straggler(straggler_info(wid=2))
    assert a.evaluate() is None
    assert a.snapshot()["last_decision"]["suppress_reason"] == "cooldown"
    # past the window: acts again
    clock.advance(200)
    d = a.evaluate()
    assert d is not None and d["worker_id"] == 2
    assert a.snapshot()["actions_applied"] == 2


def test_hold_hysteresis_delays_action_until_signal_persists():
    clock = Clock()
    target = FakeTarget(world=4)
    a = make(clock, target, hold_s=30.0)
    a._on_straggler(straggler_info())
    assert a.evaluate() is None          # not held long enough
    assert target.calls == []
    clock.advance(29)
    assert a.evaluate() is None
    clock.advance(2)
    assert a.evaluate() is not None      # persisted past hold_s


def test_world_bounds_suppress():
    clock = Clock()
    a = make(clock, FakeTarget(world=2), min_world=2)
    a._on_straggler(straggler_info())
    assert a.evaluate() is None
    assert a.snapshot()["last_decision"]["suppress_reason"] == "world_at_min"
    a2 = make(clock, FakeTarget(world=4), max_world=4)
    a2._on_alert({"rule": "dispatcher_backlog_per_worker",
                  "value": 200.0, "threshold": 64.0})
    assert a2.evaluate() is None
    assert a2.snapshot()["last_decision"]["suppress_reason"] == "world_at_max"


def test_action_budget_caps_blast_radius():
    clock = Clock()
    target = FakeTarget(world=10)
    a = make(clock, target, action_budget=2, cooldown_s=0.0)
    for wid in (1, 2, 3):
        a._on_straggler(straggler_info(wid=wid))
        a.evaluate()
        clock.advance(1)
    assert a.snapshot()["actions_applied"] == 2
    assert len([c for c in target.calls if c[0] == "evict"]) == 2
    assert a.snapshot()["last_decision"]["suppress_reason"] \
        == "budget_exhausted"


def test_conflicting_grow_and_shrink_suppress_each_other():
    clock = Clock()
    target = FakeTarget(world=4)
    a = make(clock, target)
    a._on_alert({"rule": "dispatcher_backlog_per_worker", "value": 100.0,
                 "threshold": 64.0})
    a._on_alert({"rule": "fleet_data_wait_dominant", "value": 0.8,
                 "threshold": 0.5})
    assert a.evaluate() is None
    assert target.calls == []
    assert a.snapshot()["last_decision"]["suppress_reason"] \
        == "conflicting_signals"


def test_unbound_target_suppresses_no_target():
    a = make(Clock())
    a._on_straggler(straggler_info())
    assert a.evaluate() is None
    assert a.snapshot()["last_decision"]["suppress_reason"] == "no_target"


def test_grow_and_shrink_signals_drive_their_actions():
    clock = Clock()
    target = FakeTarget(world=4)
    a = make(clock, target)
    a._on_alert({"rule": "dispatcher_backlog_per_worker", "value": 100.0,
                 "threshold": 64.0})
    d = a.evaluate()
    assert d["kind"] == "grow" and target.calls[-1][0] == "grow"
    clock.advance(1000)
    a._on_alert({"rule": "fleet_data_wait_dominant", "value": 0.8,
                 "threshold": 0.5})
    d = a.evaluate()
    assert d["kind"] == "shrink" and target.calls[-1][0] == "shrink"
    # irrelevant rules never become signals
    a._on_alert({"rule": "embedding_pull_p99", "value": 900.0})
    assert a.snapshot()["pending_signals"] == 0


def test_action_failure_keeps_cooldown_and_journals_failure():
    clock = Clock()
    target = FakeTarget(world=4, ok=False)
    a = make(clock, target)
    a._on_straggler(straggler_info())
    d = a.evaluate()
    assert d is not None          # the decision stood (journaled applied)
    assert a.snapshot()["last_decision"]["suppress_reason"] \
        == "action_failed"
    assert a.snapshot()["actions_applied"] == 1


def test_failed_action_rearms_signal_and_retries_after_cooldown():
    """Review finding: hooks fire only at ONSET, so a signal consumed by
    a FAILED action must re-arm — a transient target error must not
    strand a still-flagged straggler for the rest of the job."""
    clock = Clock()
    target = FakeTarget(world=4, ok=False)
    a = make(clock, target, cooldown_s=50.0)
    a._on_straggler(straggler_info(wid=3))
    assert a.evaluate() is not None
    assert a.snapshot()["pending_signals"] == 1   # re-armed, not lost
    clock.advance(10)
    assert a.evaluate() is None                   # cooldown paces retry
    target.ok = True                              # transient error heals
    clock.advance(100)
    d = a.evaluate()
    assert d is not None and d["worker_id"] == 3
    assert target.calls.count(("evict", 3)) == 2
    assert a.snapshot()["pending_signals"] == 0


# ------------------------------------------------------------------ #
# journaled decisions + replay-inherited state


def test_decisions_journaled_and_replayed_with_cooldown_inherited(tmp_path):
    clock = Clock()
    journal = ControlPlaneJournal(str(tmp_path))
    target = FakeTarget(world=4)
    a = make(clock, target, journal=journal, cooldown_s=500.0)
    a._on_straggler(straggler_info(wid=7))
    assert a.evaluate() is not None
    # a second signal inside the cooldown: suppressed AND journaled
    clock.advance(5)
    a._on_straggler(straggler_info(wid=8))
    assert a.evaluate() is None
    # suppressed journaling is EDGE-triggered: more polls with the same
    # (kind, reason) add no records
    for _ in range(5):
        a.evaluate()
    journal.close()
    with open(journal.path, encoding="utf-8") as f:
        lines = f.readlines()
    recs = [json.loads(ln) for ln in lines]
    auto = [r for r in recs if r.get("t") == "autoscale"]
    assert [r["decision"] for r in auto] == ["applied", "suppressed"]
    assert auto[0]["kind"] == "evict" and auto[0]["worker_id"] == 7
    assert auto[0]["gain_s"] > auto[0]["cost_s"]
    assert auto[1]["suppress_reason"] == "cooldown"
    # replay identity (twice over the same lines)
    ra, rb = replay_lines(lines).autoscale, replay_lines(lines).autoscale
    assert asdict(ra) == asdict(rb)
    assert ra.actions_applied == 1
    assert ra.last_action_ts == pytest.approx(clock.t - 5, abs=1.0)
    assert ra.by_kind == {"evict": 1}

    # takeover: the successor's journal open replays + rotates; a
    # restored autoscaler inherits cooldown and does NOT re-fire
    successor = ControlPlaneJournal(str(tmp_path))
    snap = successor.autoscale_snapshot()
    assert snap is not None and snap.actions_applied == 1
    assert snap.last_action_ts == ra.last_action_ts
    target2 = FakeTarget(world=4)
    restored = make(clock, target2, journal=successor, cooldown_s=500.0)
    restored._on_straggler(straggler_info(wid=9))
    assert restored.evaluate() is None
    assert target2.calls == []
    assert restored.snapshot()["last_decision"]["suppress_reason"] \
        == "cooldown"
    # ... and past the inherited window the restored engine acts
    clock.advance(1000)
    assert restored.evaluate() is not None
    successor.close()
    # a snapshot survives another rotation round trip
    third = ControlPlaneJournal(str(tmp_path))
    assert third.autoscale_snapshot().actions_applied == 2
    third.close()


def test_autoscale_journal_record_in_group_commit_batch(tmp_path):
    """Applied decisions await their commit (durable-before-action) in
    group-commit mode too."""
    journal = ControlPlaneJournal(str(tmp_path), group_commit_ms=5.0)
    clock = Clock()
    a = make(clock, FakeTarget(world=4), journal=journal)
    a._on_straggler(straggler_info(wid=1))
    assert a.evaluate() is not None
    journal.close()
    with open(journal.path, encoding="utf-8") as f:
        ra = replay_lines(f.readlines()).autoscale
    assert ra.actions_applied == 1


# ------------------------------------------------------------------ #
# live-sensor revalidation (signals act only while still true)


class StubMembership:
    def __init__(self, records):
        self.records = records

    def health_snapshot(self):
        return self.records


def _rec(wid, p50_ms, now):
    return {"worker_id": wid, "name": f"w{wid}", "step_p50_ms": p50_ms,
            "updated_at": now}


def test_signal_cleared_before_hold_is_dropped():
    now = time.time()
    records = [_rec(0, 5.0, now), _rec(1, 5.0, now), _rec(2, 60.0, now)]
    membership = StubMembership(records)
    health = ClusterHealth(membership, min_workers=3)
    clock = Clock()
    target = FakeTarget(world=3)
    a = make(clock, target, hold_s=10.0).subscribe(health=health)
    health.update(now)
    assert a.snapshot()["pending_signals"] == 1
    # the straggler recovers before the hold elapses
    records[2]["step_p50_ms"] = 5.0
    health.update(now + 1)
    clock.advance(60)
    assert a.evaluate() is None
    assert target.calls == []
    assert a.snapshot()["pending_signals"] == 0


def test_end_to_end_straggler_onset_drives_eviction():
    """The real seam: ClusterHealth hook -> pending signal -> evaluate
    -> evict, against real health records."""
    now = time.time()
    records = [_rec(0, 5.0, now), _rec(1, 5.0, now), _rec(2, 60.0, now)]
    health = ClusterHealth(StubMembership(records), min_workers=3)
    clock = Clock()
    target = FakeTarget(world=3)
    a = make(clock, target).subscribe(health=health)
    health.update(now)
    d = a.evaluate()
    assert d is not None and d["kind"] == "evict" and d["worker_id"] == 2
    assert target.calls == [("evict", 2)]


def test_alert_engine_onset_drives_grow(tmp_path):
    """The other seam: a real AlertEngine rule onset -> grow."""
    from elasticdl_tpu.observability.alerts import AlertEngine, AlertRule
    from elasticdl_tpu.observability.timeseries import TimeSeriesStore

    store = TimeSeriesStore(interval_s=0.01)
    engine = AlertEngine(store, rules=[AlertRule(
        "dispatcher_backlog_per_worker",
        series="edl_fleet_backlog_per_worker",
        threshold=64.0, mode="value", window_s=60.0,
    )])
    clock = Clock()
    target = FakeTarget(world=2)
    a = make(clock, target).subscribe(alerts=engine)
    now = time.time()
    store.sample(extra={"edl_fleet_backlog_per_worker": 200.0}, now=now)
    engine.evaluate(now=now)
    d = a.evaluate()
    assert d is not None and d["kind"] == "grow"
    assert target.calls == [("grow", None)]


def test_alert_cleared_before_action_drops_signal():
    from elasticdl_tpu.observability.alerts import AlertEngine, AlertRule
    from elasticdl_tpu.observability.timeseries import TimeSeriesStore

    store = TimeSeriesStore(interval_s=0.01)
    engine = AlertEngine(store, rules=[AlertRule(
        "dispatcher_backlog_per_worker",
        series="edl_fleet_backlog_per_worker",
        threshold=64.0, mode="value", window_s=60.0,
    )])
    clock = Clock()
    target = FakeTarget(world=2)
    a = make(clock, target, hold_s=10.0).subscribe(alerts=engine)
    now = time.time()
    store.sample(extra={"edl_fleet_backlog_per_worker": 200.0}, now=now)
    engine.evaluate(now=now)
    assert a.snapshot()["pending_signals"] == 1
    # backlog drains before the hold elapses: alert clears, signal drops
    store.sample(extra={"edl_fleet_backlog_per_worker": 1.0}, now=now + 1)
    engine.evaluate(now=now + 1)
    clock.advance(60)
    assert a.evaluate() is None
    assert target.calls == []


# ------------------------------------------------------------------ #
# action adapters


class FakeProc:
    def poll(self):
        return None


class FakeManagerCfg:
    def __init__(self, num_processes=1, num_workers=3):
        self.num_processes = num_processes
        self.num_workers = num_workers


class FakePlainManager:
    def __init__(self):
        self.cfg = FakeManagerCfg(num_processes=1)
        self.evicted = []

    def evict_worker(self, wid):
        self.evicted.append(wid)
        return True


class FakeCohortManager:
    def __init__(self, size=4):
        self.cfg = FakeManagerCfg(num_processes=size)
        self.cohort_size = size
        self.removed = 0
        self.added = 0

    def pending_size(self):
        return None

    def remove_worker(self):
        self.removed += 1
        return self.cohort_size - self.removed

    def add_worker(self):
        self.added += 1
        return self.cohort_size + self.added


class FakeServicer:
    def __init__(self):
        self.evict_requests = []

    def request_evict(self, wid):
        self.evict_requests.append(wid)


def test_process_manager_target_plain_evict_uses_drain_handshake():
    mgr = FakePlainManager()
    servicer = FakeServicer()
    t = ProcessManagerTarget(mgr, servicer=servicer)
    assert t.evict(2, "worker-2") is True
    # drain handshake armed FIRST (the worker retires its records),
    # then the slot marked never-relaunch
    assert servicer.evict_requests == [2]
    assert mgr.evicted == [2]


class FakeMembershipAlive:
    def __init__(self, wids):
        self._wids = wids

    def alive_count(self):
        return len(self._wids)

    def alive_workers(self):
        import types

        return [types.SimpleNamespace(worker_id=w, led_by=None)
                for w in self._wids]


def test_plain_training_grow_is_unsupported_and_spends_no_budget():
    """Review finding: a structurally impossible action (growing a plain
    TRAINING fleet) must suppress BEFORE the budget/cooldown spend, not
    journal an applied decision that always raises."""
    from elasticdl_tpu.common.constants import JobType

    mgr = FakePlainManager()
    mgr.cfg.job_type = JobType.TRAINING_WITH_EVALUATION
    target = ProcessManagerTarget(mgr, membership=FakeMembershipAlive([0]))
    assert target.supports("grow") is False
    assert target.supports("evict") is True
    clock = Clock()
    a = make(clock, target)
    a._on_alert({"rule": "dispatcher_backlog_per_worker", "value": 100.0,
                 "threshold": 64.0})
    assert a.evaluate() is None
    snap = a.snapshot()
    assert snap["last_decision"]["suppress_reason"] == "unsupported"
    assert snap["actions_applied"] == 0
    assert snap["budget_remaining"] == a.action_budget
    # eval/prediction plain fleets CAN grow
    mgr.cfg.job_type = JobType.EVALUATION_ONLY
    assert target.supports("grow") is True


def test_plain_shrink_routes_through_the_evict_drain_path():
    """Review finding: ProcessManager.remove_worker is cohort-only —
    plain-mode shrink must evict the newest capacity via the drain
    handshake instead of raising after the decision was journaled."""
    mgr = FakePlainManager()
    servicer = FakeServicer()
    target = ProcessManagerTarget(
        mgr, servicer=servicer, membership=FakeMembershipAlive([0, 1, 2]))
    assert target.supports("shrink") is True
    assert target.shrink() is True
    assert servicer.evict_requests == [2]   # newest capacity drains
    assert mgr.evicted == [2]


def test_process_manager_target_cohort_evict_is_drain_first_shrink():
    mgr = FakeCohortManager(size=4)
    t = ProcessManagerTarget(mgr, servicer=FakeServicer())
    assert t.world_size() == 4
    assert t.evict(0, "cohort#p2") is True
    assert mgr.removed == 1       # the quiesce-checkpoint resize path
    assert t.grow() and mgr.added == 1


def test_all_failed_ignores_policy_evicted_slots():
    """Review finding: a DELETED (policy-evicted) slot must not pin
    all_failed() False while the rest of the fleet dies — and a
    deliberate eviction alone must never read as an all-failed abort."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.common.constants import PodStatus
    from elasticdl_tpu.master.process_manager import (
        ProcessManager,
        _WorkerProc,
    )

    class DeadProc:
        def poll(self):
            return 75

    cfg = JobConfig(model_def="m.f", num_workers=2)
    mgr = ProcessManager(cfg, membership_signal_path="")
    mgr._procs[0] = _WorkerProc(
        worker_id=0, proc=DeadProc(), status=PodStatus.DELETED,
        evicted=True)
    mgr._procs[1] = _WorkerProc(
        worker_id=1, proc=DeadProc(), status=PodStatus.FAILED)
    # the evicted slot is excluded; the remaining fleet IS all failed
    assert mgr.all_failed() is True
    # only retirements left: not a failure state
    mgr._procs[1].status = PodStatus.SUCCEEDED
    assert mgr.all_failed() is False
    # a live worker beside a failed one: not all failed

    class LiveProc:
        def poll(self):
            return None

    mgr._procs[1].status = PodStatus.FAILED
    mgr._procs[2] = _WorkerProc(
        worker_id=2, proc=LiveProc(), status=PodStatus.RUNNING)
    assert mgr.all_failed() is False


# ------------------------------------------------------------------ #
# the drain-handshake wire bit (servicer + pb)


def test_servicer_evict_bit_rides_heartbeat_and_clears_on_death():
    from elasticdl_tpu.master.membership import Membership
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    dispatcher = TaskDispatcher(
        training_shards=[("s", 0, 64)], records_per_task=64, shuffle=False)
    membership = Membership(heartbeat_timeout_s=60.0)
    servicer = MasterServicer(dispatcher, membership)
    membership.add_death_callback(servicer.clear_evict)
    wid = membership.register("w0").worker_id
    resp = servicer.Heartbeat(pb.HeartbeatRequest(worker_id=wid), None)
    assert resp.evict is False
    servicer.request_evict(wid)
    resp = servicer.Heartbeat(pb.HeartbeatRequest(worker_id=wid), None)
    assert resp.evict is True
    # STICKY until the worker leaves (a dropped response must not lose
    # the eviction) ...
    resp = servicer.Heartbeat(pb.HeartbeatRequest(worker_id=wid), None)
    assert resp.evict is True
    # ... and pruned when it does (a revived id must not inherit it)
    membership.mark_dead(wid, reason="evicted")
    assert servicer.evict_pending(wid) is False


def test_heartbeat_response_evict_field_survives_wire_roundtrip():
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    msg = pb.HeartbeatResponse(evict=True, num_workers=3)
    decoded = pb.HeartbeatResponse.FromString(msg.SerializeToString())
    assert decoded.evict is True and decoded.num_workers == 3
    # proto3 default: an old master's response reads evict=False
    assert pb.HeartbeatResponse().evict is False


# ------------------------------------------------------------------ #
# satellite: hook failures are counted, not dark


def test_cluster_health_hook_errors_counted():
    # importing the shared helper registers the counter (the seams load
    # it lazily, only at the first failure)
    from elasticdl_tpu.observability import hooks  # noqa: F401

    counter = default_registry().get("edl_hook_errors_total")
    before = counter.value(source="cluster_health")
    now = time.time()
    records = [_rec(0, 5.0, now), _rec(1, 5.0, now), _rec(2, 60.0, now)]
    health = ClusterHealth(StubMembership(records), min_workers=3)

    def bad_hook(info):
        raise RuntimeError("policy bug")

    health.add_hook(bad_hook)
    snap = health.update(now)
    assert snap["straggler_count"] == 1   # scoring survived the hook
    assert counter.value(source="cluster_health") == before + 1


def test_alert_engine_hook_errors_counted():
    from elasticdl_tpu.observability import hooks  # noqa: F401
    from elasticdl_tpu.observability.alerts import AlertEngine, AlertRule
    from elasticdl_tpu.observability.timeseries import TimeSeriesStore

    counter = default_registry().get("edl_hook_errors_total")
    before = counter.value(source="alert_engine")
    store = TimeSeriesStore(interval_s=0.01)
    engine = AlertEngine(store, rules=[AlertRule(
        "r", series="s", threshold=1.0, mode="value")])

    def bad_hook(info):
        raise RuntimeError("policy bug")

    engine.add_hook(bad_hook)
    now = time.time()
    store.sample(extra={"s": 5.0}, now=now)
    snap = engine.evaluate(now=now)
    assert [a["rule"] for a in snap["active"]] == ["r"]
    assert counter.value(source="alert_engine") == before + 1


# ------------------------------------------------------------------ #
# satellite: configurable straggler quorum (floor 2)


def test_two_worker_fleet_flags_straggler_with_quorum_2():
    now = time.time()
    records = [_rec(0, 5.0, now), _rec(1, 60.0, now)]
    health = ClusterHealth(StubMembership(records), min_workers=2)
    snap = health.update(now)
    assert snap["scorable"] is True
    assert [s["worker_id"] for s in snap["stragglers"]] == [1]
    # the ratio gate still protects a HEALTHY pair (60/5 = 12x flags;
    # 6/5 = 1.2x must not)
    health2 = ClusterHealth(
        StubMembership([_rec(0, 5.0, now), _rec(1, 6.0, now)]),
        min_workers=2)
    assert health2.update(now)["straggler_count"] == 0


def test_quorum_floor_and_default_unchanged():
    health = ClusterHealth(StubMembership([]), min_workers=1)
    assert health.min_workers == 2    # floor
    now = time.time()
    # default quorum 3: a 2-reporter fleet stays unscorable
    records = [_rec(0, 5.0, now), _rec(1, 60.0, now)]
    health3 = ClusterHealth(StubMembership(records))
    snap = health3.update(now)
    assert snap["scorable"] is False and snap["straggler_count"] == 0


def test_straggler_quorum_config_validation():
    from elasticdl_tpu.common.config import JobConfig

    cfg = JobConfig(model_def="m.f", straggler_quorum=1)
    with pytest.raises(ValueError, match="straggler_quorum"):
        cfg.validate()
    JobConfig(model_def="m.f", straggler_quorum=2).validate()


def test_autoscale_config_validation():
    from elasticdl_tpu.common.config import JobConfig

    ok = JobConfig(model_def="m.f", autoscale=True, checkpoint_dir="/tmp/c")
    ok.validate()
    with pytest.raises(ValueError, match="checkpoint_dir"):
        JobConfig(model_def="m.f", autoscale=True).validate()
    with pytest.raises(ValueError, match="autoscale_actions_max"):
        JobConfig(model_def="m.f", autoscale=True, checkpoint_dir="/t",
                  autoscale_actions_max=0).validate()
    with pytest.raises(ValueError, match="autoscale_max_workers"):
        JobConfig(model_def="m.f", autoscale=True, checkpoint_dir="/t",
                  autoscale_min_workers=4,
                  autoscale_max_workers=2).validate()
    with pytest.raises(ValueError, match="rescale_cost"):
        JobConfig(model_def="m.f", autoscale=True, checkpoint_dir="/t",
                  autoscale_rescale_cost_s=0).validate()
    # off = no autoscale validation at all (the disable path)
    JobConfig(model_def="m.f", autoscale_actions_max=0).validate()


# ------------------------------------------------------------------ #
# satellite: fleet series no-data semantics under reporter churn


def test_fleet_series_no_data_not_fake_zeros():
    from elasticdl_tpu.observability.timeseries import fleet_series

    now = time.time()
    # all workers churned away mid-poll: NO reporters, NO alive workers
    series = fleet_series([], todo_tasks=500, alive_workers=0, now=now)
    # backlog per worker is UNDEFINED, not todo/1: a fake 500-task
    # "backlog" would fire the grow rule exactly when nothing can grow
    assert "edl_fleet_backlog_per_worker" not in series
    assert "edl_fleet_data_wait_frac" not in series
    assert "edl_fleet_step_p50_ms_median" not in series
    assert series["edl_fleet_workers_reporting"] == 0.0
    # partial churn: stale records (beyond the window) count as absent
    stale = [_rec(0, 5.0, now - 120)]
    series = fleet_series(stale, todo_tasks=500, alive_workers=2, now=now)
    assert series["edl_fleet_workers_reporting"] == 0.0
    assert "edl_fleet_data_wait_frac" not in series
    # backlog IS emitted when alive workers exist (the signal is real)
    assert series["edl_fleet_backlog_per_worker"] == 250.0


def test_goodput_series_absent_without_reporters():
    from elasticdl_tpu.observability.goodput import FleetGoodput

    fg = FleetGoodput(StubMembership([]), dispatcher=None)
    fg.update()
    assert fg.series() == {}   # absence IS the no-data signal


def test_autoscaler_holds_position_on_no_data():
    """Zero reporters -> no straggler onsets, no alert onsets -> the
    engine makes NO decision (and journals nothing)."""
    now = time.time()
    membership = StubMembership([])
    health = ClusterHealth(membership, min_workers=2)
    clock = Clock()
    target = FakeTarget(world=3)
    a = make(clock, target).subscribe(health=health)
    health.update(now)
    assert a.evaluate() is None
    assert target.calls == []
    assert a.snapshot()["decision_records"] == 0
