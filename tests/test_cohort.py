"""Multi-process SPMD cohort (worker/cohort.py): real subprocesses forming
one jax.distributed world over local CPU devices, driven by the in-process
master — the rebuild of the reference's elastic-AllReduce integration tests
(SURVEY §3.4/§4), including the kill-a-member fault injection.
"""

import glob
import os
import time

import pytest

from elasticdl_tpu.client.local import free_port
from tests.conftest import requires_multiprocess_backend
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.master.main import Master
from elasticdl_tpu.master.process_manager import ProcessManager

HERMETIC_ENV = {
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    "EDL_LOG_LEVEL": "INFO",
}


def job_config(tmp_path, **overrides):
    base = dict(
        job_name="cohort-e2e",
        model_zoo=os.path.abspath("model_zoo"),
        model_def="deepfm.deepfm.custom_model",
        model_params={"field_vocab": 64, "hidden": "16,16"},
        training_data="synthetic://criteo?n=2048&shards=4",
        records_per_task=512,
        minibatch_size=64,
        num_epochs=1,
        evaluation_steps=0,
        num_workers=1,
        num_processes=2,
        master_addr=f"localhost:{free_port()}",
        worker_heartbeat_s=1.0,
        task_timeout_s=300.0,
        shuffle=False,
    )
    base.update(overrides)
    return JobConfig(**base)


def run_job(cfg, tmp_path, mid_job=None, timeout_s=420, return_all=False,
            resize_ckpt_timeout_s=30.0, observer=None, extra_env=None):
    master = Master(cfg)
    manager = ProcessManager(
        cfg,
        membership=master.membership,
        extra_env={**HERMETIC_ENV, **(extra_env or {})},
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.dispatcher.finished,
        # production wiring (client/local.py): planned resizes quiesce via
        # the heartbeat should_checkpoint bit
        checkpoint_request_fn=lambda: master.servicer.request_checkpoint(0),
        resize_checkpoint_timeout_s=resize_ckpt_timeout_s,
    )
    master.start()
    manager.start_workers()
    try:
        deadline = time.time() + timeout_s
        fired = False
        while not master.dispatcher.finished() and time.time() < deadline:
            master.membership.reap()
            master.dispatcher.poke()
            if mid_job is not None and not fired:
                fired = mid_job(master, manager)
            if observer is not None:
                observer(master, manager)
            time.sleep(0.2)
        assert master.dispatcher.finished(), (
            master.dispatcher.counts(), all_logs(tmp_path)[-3000:],
        )
        counts = master.dispatcher.counts()
        return (master, manager, counts) if return_all else counts
    finally:
        master.shutdown()
        manager.stop()


def all_logs(tmp_path) -> str:
    out = []
    for f in sorted(glob.glob(str(tmp_path / "logs" / "*.log"))):
        out.append(open(f, errors="replace").read())
    return "\n".join(out)


def test_cohort_job_end_to_end(tmp_path):
    cfg = job_config(tmp_path, output=str(tmp_path / "export"))
    counts = run_job(cfg, tmp_path)
    assert counts["finished_training"] == 4
    assert counts["failed_permanently"] == 0
    log = all_logs(tmp_path)
    assert "distributed world v0 up: process 0/2" in log
    assert "distributed world v0 up: process 1/2" in log
    assert os.path.exists(tmp_path / "export" / "params.msgpack")


def test_cohort_grouped_dispatch_end_to_end(tmp_path):
    """--steps_per_dispatch=2 in COHORT mode: both processes run the same
    train_many scan over the stacked global batch (one collective dispatch
    per 2 minibatches); a 512-record task at minibatch 64 = 8 batches = 4
    full groups; task accounting and loss reporting unchanged."""
    cfg = job_config(tmp_path, steps_per_dispatch=2, wire_dtype="bfloat16")
    counts = run_job(cfg, tmp_path)
    assert counts["finished_training"] == 4
    assert counts["failed_permanently"] == 0
    log = all_logs(tmp_path)
    assert "distributed world v0 up: process 0/2" in log
    assert "distributed world v0 up: process 1/2" in log


@pytest.mark.parametrize("num_processes", [
    1, pytest.param(2, marks=requires_multiprocess_backend),
])
def test_master_lr_push_applies(tmp_path, num_processes):
    """ReduceLROnPlateau's transport, end-to-end in both worker flavors:
    the master sets an LR override; a heartbeat carries it to the worker
    (plain mode, applied at the next task boundary) or to the cohort
    leader, then the ctrl broadcast (float64 bits in int32 halves) to
    every process, which all apply it at the same boundary."""
    cfg = job_config(tmp_path, num_processes=num_processes)
    fired = {"done": False}

    def push_lr(master, manager):
        # once the job is visibly underway, push the override
        if not fired["done"] and master.dispatcher.counts()["doing"] > 0:
            master.servicer.set_learning_rate(5e-4)
            fired["done"] = True

    counts = run_job(cfg, tmp_path, observer=push_lr)
    assert counts["failed_permanently"] == 0
    assert fired["done"]
    log = all_logs(tmp_path)
    if num_processes == 2:
        # both cohort processes applied it (one log line per process)
        assert log.count("applied master-pushed LR 0.0005") == 2, log[-2000:]
    else:
        assert "runtime LR set to 0.0005" in log, log[-2000:]


@pytest.mark.parametrize("steps_per_dispatch", [1, 2])
def test_cohort_evaluation_only_job(tmp_path, steps_per_dispatch):
    """evaluation_only in cohort mode: eval tasks stream through every
    process's eval path (per-batch eval_step, or the grouped eval_many
    collective scan with --steps_per_dispatch), metric states merge
    master-side, AUC comes back."""
    cfg = job_config(
        tmp_path,
        job_type="evaluation_only",
        validation_data="synthetic://criteo?n=512&shards=2",
        records_per_task=256,
        steps_per_dispatch=steps_per_dispatch,
    )
    master, manager, counts = run_job(cfg, tmp_path, return_all=True)
    assert counts["failed_permanently"] == 0
    results = master.evaluation.latest_results()
    assert "auc" in results and "loss" in results, results


@pytest.mark.parametrize("num_processes,steps_per_dispatch",
                         [(1, 1), (1, 2), (2, 1), (2, 2)])
def test_cohort_prediction_job(tmp_path, num_processes, steps_per_dispatch):
    """Prediction jobs end-to-end in BOTH worker flavors. Cohort mode was a
    round-3 gap (_data_service only knew train/eval, so prediction-only
    with num_processes>1 crashed): every process runs predict_step on the
    global batch, outputs allgather to the leader, and the zoo's
    prediction_outputs_processor writes them — exactly once across the
    job. num_processes=1 drives the plain worker's prediction path through
    the same harness; (1, 2) covers its grouped predict_many dispatch."""
    import numpy as np

    out_dir = tmp_path / "preds"
    cfg = job_config(
        tmp_path,
        job_type="prediction_only",
        prediction_data="synthetic://criteo?n=512&shards=2",
        records_per_task=256,
        num_processes=num_processes,
        steps_per_dispatch=steps_per_dispatch,
    )
    counts = run_job(
        cfg, tmp_path, extra_env={"EDL_PREDICT_OUT": str(out_dir)})
    assert counts["failed_permanently"] == 0
    files = sorted(glob.glob(str(out_dir / "*.npy")))
    assert files, all_logs(tmp_path)[-2000:]
    total = sum(np.load(f).shape[0] for f in files)
    assert total == 512  # every record predicted exactly once, none padded


@requires_multiprocess_backend
def test_cohort_member_kill_relaunches_and_resumes(tmp_path):
    cfg = job_config(
        tmp_path,
        training_data="synthetic://criteo?n=8192&shards=8",
        records_per_task=1024,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=8,
    )

    def kill_follower_after_checkpoint(master, manager):
        # wait until a checkpoint generation exists, then SIGKILL process 1
        if master.dispatcher.counts()["finished_training"] < 2:
            return False
        wp = manager._procs.get(1)
        if wp is None or wp.proc.poll() is not None:
            return False
        wp.proc.kill()
        return True

    counts = run_job(cfg, tmp_path, mid_job=kill_follower_after_checkpoint)
    assert counts["finished_training"] == 8
    assert counts["failed_permanently"] == 0
    log = all_logs(tmp_path)
    assert "cohort resumed from checkpoint at step" in log, log[-3000:]


def test_cohort_leader_sigterm_drains_via_checkpoint(tmp_path):
    """Planned preemption (SIGTERM to the LEADER): instead of dying with
    work since the last interval checkpoint lost, the leader broadcasts
    OP_ABORT|FLAG_CHECKPOINT — a collective save every process joins — and
    the relaunched cohort resumes at exactly the pre-kill step. Interval
    checkpoints are disabled (checkpoint_steps=0) so the ONLY checkpoint on
    disk is the drain's: resuming from it proves the drain worked."""
    import re

    cfg = job_config(
        tmp_path,
        training_data="synthetic://criteo?n=8192&shards=8",
        records_per_task=1024,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=0,   # no interval saves: drain is the only source
    )

    lat = {}  # drain-latency instrumentation (BASELINE.md round log)

    def sigterm_leader(master, manager):
        if master.dispatcher.counts()["finished_training"] < 2:
            return False
        wp = manager._procs.get(0)
        if wp is None or wp.proc.poll() is not None:
            return False
        wp.proc.terminate()   # SIGTERM: the k8s-preemption shape
        lat["sigterm_t"] = time.time()
        return True

    def observe(master, manager):
        if "sigterm_t" in lat and "reform_t" not in lat and \
                manager.reformation_log:
            lat["reform_t"] = manager.reformation_log[0][0]

    counts = run_job(cfg, tmp_path, mid_job=sigterm_leader, observer=observe)
    assert counts["finished_training"] == 8
    assert counts["failed_permanently"] == 0
    log = all_logs(tmp_path)
    assert "leader preempted: draining cohort via collective checkpoint" in log
    saved = re.search(r"preemption checkpoint saved at step (\d+)", log)
    resumed = re.search(r"cohort resumed from checkpoint at step (\d+)", log)
    assert saved and resumed, log[-3000:]
    # the restored step IS the pre-kill step: nothing trained was redone
    assert resumed.group(1) == saved.group(1), (saved.group(), resumed.group())
    drain_s = lat.get("reform_t", time.time()) - lat["sigterm_t"]
    print(f"\n[preemption-drain] SIGTERM -> drained+torn-down {drain_s:.2f}s "
          f"(bounded by the in-flight task + collective save)")


def test_cohort_lease_aborts_when_master_lost(tmp_path):
    """Leader unit test for orphan cleanup: once no master RPC has
    succeeded for master_unreachable_timeout_s, the next lease becomes
    OP_ABORT (taking the whole cohort down EX_TEMPFAIL) instead of NOOP
    retries forever — a cohort whose master's process tree died must not
    survive it indefinitely."""
    from elasticdl_tpu.parallel.elastic import CohortContext
    from elasticdl_tpu.worker.cohort import (
        FLAG_CHECKPOINT,
        OP_ABORT,
        OP_NOOP,
        CohortWorker,
    )

    cfg = job_config(tmp_path, master_unreachable_timeout_s=5.0)

    class DeadStub:
        def GetTask(self, *a, **k):
            raise ConnectionError("connection refused")

    w = CohortWorker(cfg, ctx=CohortContext("localhost:1", 2, 0))
    w._stub = DeadStub()
    # master answered recently: failures are still transient -> NOOP
    w._last_master_ok = time.monotonic()
    assert w._lease_control()[0] == OP_NOOP
    assert not w._shutdown.is_set()
    # silent past the limit -> ABORT with a final collective checkpoint
    # (clean task boundary, the save needs no master), shutdown latched
    w._last_master_ok = time.monotonic() - 6.0
    ctrl = w._lease_control()
    assert ctrl[0] == OP_ABORT and ctrl[6] & FLAG_CHECKPOINT
    assert w._shutdown.is_set() and w._master_lost
    # the heartbeat thread can be the one that crosses the limit (mid-task);
    # the ensuing shutdown-branch lease must carry the same checkpoint flag
    ctrl = w._lease_control()
    assert ctrl[0] == OP_ABORT and ctrl[6] & FLAG_CHECKPOINT


def test_cohort_aborts_itself_when_master_vanishes(tmp_path):
    """Orphan cleanup end-to-end: the master's gRPC server cold-stops (no
    shutdown flag ever reaches the leader); after
    master_unreachable_timeout_s the leader must broadcast the abort and
    BOTH real subprocesses must exit on their own — no cohort may outlive
    its master indefinitely (observed pre-fix: orphans surviving hours)."""
    cfg = job_config(
        tmp_path,
        training_data="synthetic://criteo?n=8192&shards=8",
        records_per_task=1024,
        master_unreachable_timeout_s=6.0,
        relaunch_max=0,
    )
    master = Master(cfg)
    manager = ProcessManager(
        cfg,
        membership=master.membership,
        extra_env=HERMETIC_ENV,
        log_dir=str(tmp_path / "logs"),
        job_finished_fn=master.dispatcher.finished,
    )
    master.start()
    manager.start_workers()
    try:
        deadline = time.time() + 180
        while (
            time.time() < deadline
            and master.dispatcher.counts()["finished_training"] < 1
        ):
            master.membership.reap()
            master.dispatcher.poke()
            time.sleep(0.2)
        assert master.dispatcher.counts()["finished_training"] >= 1
        master.server.stop(grace=0)   # cold stop: master vanishes

        deadline = time.time() + 120
        while time.time() < deadline:
            procs = list(manager._procs.values())
            if procs and all(wp.proc.poll() is not None for wp in procs):
                break
            time.sleep(0.5)
        else:
            raise AssertionError(
                "cohort outlived its vanished master: "
                + all_logs(tmp_path)[-3000:]
            )
        log = all_logs(tmp_path)
        assert "master presumed gone, aborting cohort" in log, log[-3000:]
    finally:
        master.server.stop(grace=0)
        manager.stop()


@requires_multiprocess_backend
def test_cohort_resizes_down_at_exhausted_budget(tmp_path):
    """Dynamic world resizing, scale-in: a member dies with the relaunch
    budget already spent — instead of stalling/failing, the cohort re-forms
    at N-1 and finishes the job with exactly-once task accounting
    (SURVEY §2.1 rendezvous re-formation at a new world size)."""
    cfg = job_config(
        tmp_path,
        training_data="synthetic://criteo?n=8192&shards=8",
        records_per_task=1024,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=8,
        relaunch_max=0,  # budget spent from the start: loss must resize
    )
    lat = {}  # re-formation latency instrumentation (BASELINE.md round log)

    def kill_follower(master, manager):
        if master.dispatcher.counts()["finished_training"] < 2:
            return False
        wp = manager._procs.get(1)
        if wp is None or wp.proc.poll() is not None:
            return False
        wp.proc.kill()
        lat["kill_t"] = time.time()
        lat["tasks_at_kill"] = master.dispatcher.counts()["finished_training"]
        return True

    def observe(master, manager):
        if "kill_t" not in lat or "first_task_t" in lat:
            return
        if not manager.reformation_log:
            return
        lat.setdefault("reform_t", manager.reformation_log[0][0])
        if (
            master.dispatcher.counts()["finished_training"]
            > lat["tasks_at_kill"]
        ):
            lat["first_task_t"] = time.time()

    master, manager, counts = run_job(
        cfg, tmp_path, mid_job=kill_follower, return_all=True,
        observer=observe,
    )
    assert counts["finished_training"] == 8
    assert counts["failed_permanently"] == 0
    assert manager.cohort_size == 1
    # one re-formation, from 2 to 1 processes
    assert [(o, n) for _, o, n in manager.reformation_log] == [(2, 1)]
    log = all_logs(tmp_path)
    assert "up: process 0/1" in log  # the new one-process world formed
    assert "cohort resumed from checkpoint at step" in log
    # kill -> teardown decision, and kill -> first task completed at the new
    # size (world re-form + checkpoint restore + one task's work); printed so
    # runs feed BASELINE.md's re-formation latency row
    detect_s = lat["reform_t"] - lat["kill_t"]
    recover_s = lat["first_task_t"] - lat["kill_t"]
    assert 0 <= detect_s < 60 and 0 < recover_s < 300
    print(
        f"\n[reformation-latency] kill->teardown {detect_s:.2f}s, "
        f"kill->first-task-at-new-size {recover_s:.2f}s"
    )


@requires_multiprocess_backend
def test_cohort_scales_up_on_add_worker(tmp_path):
    """Dynamic world resizing, scale-out: add_worker mid-job re-forms the
    cohort at N+1 (fresh coordinator, new world version, checkpoint restore)
    and the job completes with all tasks accounted for."""
    cfg = job_config(
        tmp_path,
        # long enough that the quiesce + re-formation land MID-job (the
        # pre-teardown checkpoint wait added in round 3 means a planned
        # resize takes a few extra seconds; an 8-task job could finish first)
        training_data="synthetic://criteo?n=24576&shards=24",
        records_per_task=1024,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=8,
    )

    def scale_up(master, manager):
        if master.dispatcher.counts()["finished_training"] < 2:
            return False
        assert manager.add_worker() == 3
        return True

    master, manager, counts = run_job(
        cfg, tmp_path, mid_job=scale_up, return_all=True
    )
    assert counts["finished_training"] == 24
    assert counts["failed_permanently"] == 0
    assert manager.cohort_size == 3
    assert [(o, n) for _, o, n in manager.reformation_log] == [(2, 3)]
    log = all_logs(tmp_path)
    assert "up: process 2/3" in log  # the third member joined the new world


@requires_multiprocess_backend
def test_cohort_remove_worker_quiesces_then_resizes(tmp_path):
    """Operator scale-in (round-3, VERDICT #7): remove_worker triggers a
    PRE-TEARDOWN checkpoint (via the heartbeat should_checkpoint bit +
    FLAG_CHECKPOINT control broadcast) before re-forming at N-1, so a
    planned resize redoes at most sub-task progress. checkpoint_steps is set
    beyond the job so the ONLY possible checkpoint is the quiesce one —
    'resumed from checkpoint' in the logs proves it landed."""
    cfg = job_config(
        tmp_path,
        # long enough that the quiesce + re-formation happen MID-job (a
        # 2-process CPU world finishes ~1024 records/s-ish; 8 tasks was over
        # before the resize landed)
        training_data="synthetic://criteo?n=24576&shards=24",
        records_per_task=1024,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=100000,   # interval checkpointing never fires
    )

    def scale_down(master, manager):
        if master.dispatcher.counts()["finished_training"] < 2:
            return False
        assert manager.remove_worker() == 1
        return True

    master, manager, counts = run_job(
        cfg, tmp_path, mid_job=scale_down, return_all=True
    )
    assert counts["finished_training"] == 24
    assert counts["failed_permanently"] == 0
    assert manager.cohort_size == 1
    assert [(o, n) for _, o, n in manager.reformation_log] == [(2, 1)]
    log = all_logs(tmp_path)
    assert "up: process 0/1" in log
    # the quiesce checkpoint was written BEFORE teardown and restored after
    assert "cohort resumed from checkpoint at step" in log, log[-3000:]
