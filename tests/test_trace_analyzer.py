"""Trace critical-path analyzer (ISSUE 7): multi-role merge over the
checked-in golden fixtures (tests/fixtures/traces/), torn/interleaved
lines tolerated (and distinguished from mid-file garbage in --strict),
deterministic critical path with per-phase/per-role attribution, and the
CLI surface CI drives over chaos/bench artifacts."""

import json
import os

from elasticdl_tpu.observability import analyzer
from elasticdl_tpu.observability.analyze import main as analyze_main

FIXTURES = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "traces"
)
RESIZE_TID = "feedface00000001"


# ---------------------------------------------------------------------- #
# loading: torn tails and garbage lines


def test_load_traces_counts_bad_lines_and_classifies_torn_tail():
    loaded = analyzer.load_traces([FIXTURES])
    assert len(loaded.files) == 2
    # master has one mid-file garbage line, worker-0 one torn tail
    assert len(loaded.bad_lines) == 2
    # only the MID-FILE garbage is a strict violation; the torn tail is
    # the documented crash shape (a writer killed mid-record)
    assert len(loaded.strict_violations) == 1
    path, line, _ = loaded.strict_violations[0]
    assert path.endswith(os.path.join("master", "trace.jsonl"))
    assert line == 2
    # every parseable record made it through the garbage
    assert len(loaded.records) == 10


def test_load_traces_missing_file_is_usage_not_writer_bug(tmp_path):
    loaded = analyzer.load_traces([str(tmp_path / "nope.jsonl")])
    assert loaded.records == []
    # a file that never existed is NOT a strict "writer bug" violation
    # (review find: a skipped best-effort trace write must not read as
    # trace corruption) — it surfaces as unreadable, CLI exit 2
    assert loaded.strict_violations == []
    assert loaded.unreadable_files == [str(tmp_path / "nope.jsonl")]


# ---------------------------------------------------------------------- #
# the golden resize timeline: master reform -> worker rescale


def test_multi_role_merge_produces_one_resize_timeline():
    report = analyzer.analyze_paths([FIXTURES])
    assert report["resize_traces"] == 1
    t = analyzer.resize_timeline(report, RESIZE_TID)
    assert t is not None
    assert t["is_resize"]
    assert t["roles"] == ["master", "worker-0"]
    assert t["spans"] == 8 and t["events"] == 1
    # two per-process roots, chained under the synthetic timeline root
    assert [r["name"] for r in t["roots"]] == ["reform", "rescale"]


def test_critical_path_deterministic_and_fully_attributed():
    report = analyzer.analyze_paths([FIXTURES])
    tl = analyzer.resize_timeline(report, RESIZE_TID)["timeline"]
    assert tl["wall_s"] == 8.5
    names = [s["name"] for s in tl["critical_path"]]
    # the exact chain: master's quiesce/teardown/spawn, the settle gap
    # between spawn-done and the worker's rescale start, then the worker's
    # mesh/compile/handoff — children emit in start order
    assert names == [
        "reform.quiesce", "reform.teardown", "reform.spawn",
        "timeline (self)", "rescale.mesh", "rescale.compile",
        "rescale.handoff",
    ]
    durs = [s["dur_s"] for s in tl["critical_path"]]
    assert durs == [2.0, 1.0, 2.0, 0.5, 0.5, 2.0, 0.5]
    # every instant attributed exactly once: segment sum == wall clock
    assert sum(durs) == tl["wall_s"]
    # phase attribution: quiesce+teardown+spawn+mesh -> settle,
    # the cross-process gap -> other
    assert tl["phases"] == {
        "compile": 2.0, "handoff": 0.5, "other": 0.5, "settle": 5.5,
    }
    assert tl["by_role"] == {"": 0.5, "master": 5.0, "worker-0": 3.0}
    # deterministic: a second run renders byte-identical JSON
    again = analyzer.analyze_paths([FIXTURES])
    assert json.dumps(report, sort_keys=True) == json.dumps(
        again, sort_keys=True
    )


def test_single_root_trace_uses_real_root_not_synthetic():
    recs = [
        {"kind": "span", "name": "rescale", "trace_id": "t", "span_id": "a",
         "parent_id": None, "role": "w", "ts": 10.0, "dur_ms": 1000.0},
        {"kind": "span", "name": "phase.compile", "trace_id": "t",
         "span_id": "b", "parent_id": "a", "role": "w", "ts": 10.2,
         "dur_ms": 800.0},
    ]
    t = analyzer.analyze_records(recs)["traces"][0]
    assert t["timeline"]["name"] == "rescale"
    assert t["timeline"]["phases"] == {"compile": 0.8, "other": 0.2}


def test_parallel_children_stay_off_the_critical_path():
    # two children overlap; only the latest-ending chain is attributed —
    # shortening the off-path child would not move the end time
    recs = [
        {"kind": "span", "name": "root", "trace_id": "t", "span_id": "r",
         "parent_id": None, "role": "m", "ts": 0.0, "dur_ms": 1000.0},
        {"kind": "span", "name": "slow.compile", "trace_id": "t",
         "span_id": "s", "parent_id": "r", "role": "m", "ts": 0.0,
         "dur_ms": 1000.0},
        {"kind": "span", "name": "parallel.handoff", "trace_id": "t",
         "span_id": "p", "parent_id": "r", "role": "m", "ts": 0.0,
         "dur_ms": 400.0},
    ]
    tl = analyzer.analyze_records(recs)["traces"][0]["timeline"]
    assert [s["name"] for s in tl["critical_path"]] == ["slow.compile"]
    assert tl["phases"] == {"compile": 1.0}


def test_straggler_events_surface_in_trace_summary():
    report = analyzer.analyze_paths([FIXTURES])
    t = analyzer.resize_timeline(report, "feedface00000002")
    assert t is not None and not t["is_resize"]
    assert t["straggler_events"] == [
        {"worker_id": 3, "score": 6.2, "step_time_p50_s": 0.09,
         "ts": 120.0}
    ]


def test_phase_classification():
    for name, phase in (
        ("phase.settle", "settle"), ("rescale.mesh", "settle"),
        ("reform.quiesce", "settle"), ("cohort.world_form", "settle"),
        ("phase.handoff", "handoff"), ("ckpt.save", "handoff"),
        ("prefetch.drain", "handoff"), ("handoff.stage_to_host", "handoff"),
        ("phase.compile", "compile"), ("compile.speculative", "compile"),
        ("rescale.compile", "compile"),
        ("rescale", "other"), ("task.lease", "other"),
    ):
        assert analyzer.classify_phase(name) == phase, name


# ---------------------------------------------------------------------- #
# CLI (python -m elasticdl_tpu.observability.analyze)


def test_cli_json_report_parses(capsys):
    rc = analyze_main([FIXTURES, "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["resize_traces"] == 1
    assert any(
        t["trace_id"] == RESIZE_TID for t in report["traces"]
    )


def test_cli_text_report_shows_critical_path(capsys):
    rc = analyze_main([FIXTURES])
    assert rc == 0
    out = capsys.readouterr().out
    assert "RESIZE" in out
    assert "reform.quiesce" in out and "rescale.compile" in out
    assert "phases:" in out and "by role:" in out


def test_cli_strict_fails_on_midfile_garbage(capsys):
    rc = analyze_main([FIXTURES, "--strict", "--json"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "strict: unparseable line" in err


def test_cli_strict_tolerates_torn_tail_alone(capsys):
    # the worker file alone: its only bad line IS the torn tail
    worker = os.path.join(FIXTURES, "worker-0", "trace.jsonl")
    rc = analyze_main([worker, "--strict", "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert len(report["unparseable_lines"]) == 1
    assert report["strict_violations"] == []


def test_cli_no_input_is_exit_2(tmp_path, capsys):
    rc = analyze_main([str(tmp_path)])
    assert rc == 2
    capsys.readouterr()


def test_cli_missing_named_file_is_exit_2_even_with_strict(tmp_path, capsys):
    rc = analyze_main([str(tmp_path / "never-written.jsonl"), "--strict"])
    assert rc == 2
    assert "unreadable input file" in capsys.readouterr().err
