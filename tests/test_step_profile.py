"""Step profiler (observability/profile.py): phase accumulation and
windows, gauge export, prefetcher data_wait/h2d attribution, the cohort's
follower-stats exchange codec, and the health scorer surfacing the WHY
(phase breakdown) on straggler infos."""

import time

import numpy as np
import pytest

from elasticdl_tpu.observability import profile
from elasticdl_tpu.observability.profile import StepProfiler, timed_iter
from elasticdl_tpu.observability.registry import default_registry


@pytest.fixture(autouse=True)
def _fresh_profiler():
    profile.reset_for_tests()
    yield
    profile.reset_for_tests()


def test_phases_accumulate_and_normalize_per_step():
    prof = StepProfiler(window=8)
    prof.add("data_wait", 0.010)
    prof.add("compute", 0.030)
    prof.add("compute", 0.010)   # same step, accumulates
    prof.step_done()
    snap = prof.snapshot(update_memory=False)
    assert snap["phase_data_wait_ms"] == 10.0
    assert snap["phase_compute_ms"] == 40.0
    assert snap["profiled_steps"] == 1
    # a grouped dispatch normalizes to per-step values
    prof.add("compute", 0.080)
    prof.step_done(steps=4)      # 20ms per step
    snap = prof.snapshot(update_memory=False)
    assert snap["profiled_steps"] == 5
    assert snap["phase_compute_ms"] == pytest.approx(30.0)  # (40+20)/2


def test_window_is_bounded_with_maintained_sums():
    prof = StepProfiler(window=4)
    for i in range(10):
        prof.add("compute", 0.001 * (i + 1))
        prof.step_done()
    snap = prof.snapshot(update_memory=False)
    # only the last 4 steps (7,8,9,10 ms) contribute
    assert snap["phase_compute_ms"] == pytest.approx(8.5)


def test_phase_context_manager_and_unknown_phase_dropped():
    prof = StepProfiler(window=4)
    with prof.phase("data_wait"):
        time.sleep(0.005)
    prof.add("weird_phase", 1.0)
    prof.step_done()
    snap = prof.snapshot(update_memory=False)
    assert snap["phase_data_wait_ms"] >= 4.0
    assert not any("weird" in k for k in snap)


def test_gauges_exported_per_phase():
    prof = StepProfiler(window=4)
    prof.add("compute", 0.020)
    prof.step_done()
    g = default_registry().get("edl_step_phase_seconds")
    assert g is not None
    assert g.value(phase="compute") == pytest.approx(0.020)


def test_memory_watermarks_best_effort():
    prof = StepProfiler()
    prof.update_memory()
    snap = prof.snapshot()
    # host RSS exists on linux; device side is 0 without a jax backend
    assert snap.get("mem_host_mb", 0) > 0
    g = default_registry().get("edl_mem_host_rss_mb")
    assert g is not None and g.value() > 0


def test_timed_iter_attributes_pulls():
    prof = StepProfiler(window=4)

    def slow_source():
        for i in range(3):
            time.sleep(0.004)
            yield i

    assert list(timed_iter(slow_source(), prof)) == [0, 1, 2]
    prof.step_done()
    snap = prof.snapshot(update_memory=False)
    assert snap["phase_data_wait_ms"] >= 10.0


def test_prefetcher_attributes_data_wait_and_h2d(mesh8):
    from elasticdl_tpu.data.prefetch import prefetch_to_device

    def batches():
        for i in range(4):
            time.sleep(0.003)
            yield {
                "features": np.full((8, 3), i, np.float32),
                "mask": np.ones((8,), np.float32),
            }

    out = list(prefetch_to_device(mesh8, batches(), depth=2))
    assert len(out) == 4
    prof = profile.get_profiler()
    prof.step_done()
    snap = prof.snapshot(update_memory=False)
    # four source pulls at >=3ms each
    assert snap["phase_data_wait_ms"] >= 10.0
    # the device_put dispatch is nonzero too
    assert snap.get("phase_h2d_ms", 0) > 0


# ---------------------------------------------------------------------- #
# cohort follower-stats exchange (satellite: the follower->leader channel)


def test_allgather_ints_single_process_shape():
    from elasticdl_tpu.parallel.elastic import CohortContext

    ctx = CohortContext("localhost:1", num_processes=1, process_id=0)
    out = ctx.allgather_ints([1, 2, 3, 2**40])
    assert out.shape == (1, 4)
    assert out[0].tolist() == [1, 2, 3, 2**40]   # full 64-bit fidelity


def _cohort(num_processes=3):
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.parallel.elastic import CohortContext
    from elasticdl_tpu.worker.cohort import CohortWorker

    cfg = JobConfig(model_def="mnist.mnist_cnn.custom_model",
                    num_processes=num_processes)
    ctx = CohortContext("localhost:1", num_processes=num_processes,
                        process_id=0)
    return CohortWorker(cfg, ctx=ctx)


def test_exchange_row_roundtrip():
    w = _cohort()
    w._step_stats.observe_step(0.025, 64)
    w._step_stats.observe_step(0.035, 64)
    profile.get_profiler().add("data_wait", 0.012)
    profile.get_profiler().add("compute", 0.030)
    profile.get_profiler().step_done()
    row = w._exchange_row()
    decoded = w._decode_exchange_row(row)
    assert decoded["steps"] == 2
    assert decoded["step_p50_ms"] == pytest.approx(30.0, abs=0.01)
    assert decoded["phase_data_wait_ms"] == pytest.approx(12.0, abs=0.01)
    assert decoded["phase_compute_ms"] == pytest.approx(30.0, abs=0.01)


def test_member_beats_prefer_follower_local_rows():
    from elasticdl_tpu.observability.health import decode_stats

    w = _cohort()
    w._member_ids = [7, 8]
    w._phase = "train"
    w._step_stats.observe_step(0.010, 64)   # the leader's own cadence
    # follower p1 exchanged a row; p2 has not yet (just re-formed)
    w._member_stats = {1: {"steps": 5, "step_p50_ms": 42.0,
                           "phase_data_wait_ms": 33.0}}
    beats = w._member_beats()
    assert [b.worker_id for b in beats] == [7, 8]
    s1 = decode_stats(beats[0].stats_json)
    s2 = decode_stats(beats[1].stats_json)
    assert s1["source"] == "follower-local"
    assert s1["step_p50_ms"] == 42.0 and s1["phase_data_wait_ms"] == 33.0
    assert s1["process_index"] == 1 and s1["phase"] == "train"
    assert s2["source"] == "leader-coalesced"
    assert s2["step_p50_ms"] == 10.0   # falls back to the leader's window


def test_exchange_member_stats_single_process_noop():
    w = _cohort(num_processes=1)
    w._exchange_member_stats()         # must not touch collectives
    assert w._member_stats == {}


# ---------------------------------------------------------------------- #
# the scorer surfaces WHY (straggler info carries the phase breakdown)


def test_straggler_info_carries_phase_breakdown():
    from elasticdl_tpu.master.membership import Membership
    from elasticdl_tpu.observability.health import ClusterHealth

    membership = Membership(heartbeat_timeout_s=1e9)
    ids = [membership.register(f"w{i}").worker_id for i in range(4)]
    for wid in ids[:3]:
        membership.heartbeat(wid, stats={"step_p50_ms": 10.0})
    membership.heartbeat(ids[3], stats={
        "step_p50_ms": 500.0, "phase": "train",
        "phase_data_wait_ms": 480.0, "phase_compute_ms": 15.0,
        "mem_host_mb": 1234.5,
    })
    health = ClusterHealth(membership)
    snap = health.update()
    assert snap["straggler_count"] == 1
    info = snap["stragglers"][0]
    assert info["worker_id"] == ids[3]
    # the WHY: blocked on the input pipeline, not compute-bound
    assert info["phase_data_wait_ms"] == 480.0
    assert info["phase_compute_ms"] == 15.0
    assert info["mem_host_mb"] == 1234.5


def test_step_phase_gauges_appear_in_live_scrape():
    """ISSUE 9 acceptance: edl_step_phase_seconds / edl_mem_* gauges show
    up in a LIVE /metrics scrape once a step has been profiled."""
    import urllib.request

    from elasticdl_tpu.observability.http import ObservabilityServer

    prof = profile.get_profiler()
    prof.add("compute", 0.015)
    prof.add("data_wait", 0.002)
    prof.step_done()
    prof.update_memory()
    server = ObservabilityServer(role="worker-0")
    port = server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
    finally:
        server.stop()
    assert 'edl_step_phase_seconds{phase="compute"}' in text
    assert 'edl_step_phase_seconds{phase="data_wait"}' in text
    assert "edl_mem_host_rss_mb" in text
