"""Rescale fast path: executable cache keying (hit/miss counters),
speculative neighbor-world compilation, live state handoff vs the
checkpoint-restore round trip, and the worker's in-place rescale."""

import os

import numpy as np
import pytest

from elasticdl_tpu.common import membership_signal
from elasticdl_tpu.training import compile_cache as cc


def make_spec():
    from elasticdl_tpu.common.model_utils import load_module
    from elasticdl_tpu.training.model_spec import ModelSpec

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    module, _ = load_module(
        os.path.join(repo, "model_zoo"), "census.wide_deep.custom_model"
    )
    return ModelSpec(
        model=module.custom_model(),
        loss=module.loss,
        optimizer=module.optimizer(),
        dataset_fn=None,
        eval_metrics_fn=getattr(module, "eval_metrics_fn", None),
        module_name="census.wide_deep",
    )


def census_batch(n=16, seed=0):
    r = np.random.RandomState(seed)
    return {
        "features": {
            "dense": r.rand(n, 5).astype(np.float32),
            "cat": r.randint(0, 400, (n, 9)).astype(np.int32),
        },
        "labels": r.randint(0, 2, (n,)).astype(np.int32),
    }


@pytest.fixture(scope="module")
def spec():
    return make_spec()


def make_trainer(spec, mesh, cache, token="t"):
    from elasticdl_tpu.training.trainer import Trainer

    return Trainer(spec, mesh, cache_token=token, cache=cache)


# ------------------------------------------------------------------ #
# cache keying


def test_same_aval_rejit_is_cache_hit(spec, mesh8):
    """A second trainer on the same (mesh, token, knobs) finds the first
    trainer's programs: zero misses, counter-asserted."""
    cache = cc.CompileCache()
    batch = census_batch()
    t1 = make_trainer(spec, mesh8, cache)
    state = t1.init_state(batch)
    state, _ = t1.train_step(state, batch)
    first = cache.stats()
    assert first["misses"] == 2 and first["hits"] == 0  # init + train_step

    t2 = make_trainer(spec, mesh8, cache)
    state2 = t2.init_state(batch)
    state2, _ = t2.train_step(state2, batch)
    second = cache.stats()
    assert second["misses"] == 2, second   # nothing rebuilt
    assert second["hits"] == 2, second     # init + train_step both hits
    assert second["hit_rate"] == 0.5


def test_different_mesh_is_cache_miss(spec, mesh8):
    import jax

    from elasticdl_tpu.parallel.mesh import build_mesh

    cache = cc.CompileCache()
    batch = census_batch()
    t1 = make_trainer(spec, mesh8, cache)
    s1 = t1.init_state(batch)
    t1.train_step(s1, batch)
    before = cache.stats()

    mesh4 = build_mesh({"data": 4}, jax.devices()[:4])
    t2 = make_trainer(spec, mesh4, cache)
    s2 = t2.init_state(batch)
    t2.train_step(s2, batch)
    after = cache.stats()
    assert after["misses"] == before["misses"] + 2   # new mesh = new programs
    assert after["hits"] == before["hits"]


def test_instance_token_trainers_do_not_share(spec, mesh8):
    """No cache_token (ad-hoc trainers): entries are private — two
    trainers over the same spec still build their own programs."""
    cache = cc.CompileCache()
    batch = census_batch()
    from elasticdl_tpu.training.trainer import Trainer

    for _ in range(2):
        t = Trainer(spec, mesh8, cache=cache)
        s = t.init_state(batch)
        t.train_step(s, batch)
    stats = cache.stats()
    assert stats["hits"] == 0 and stats["misses"] == 4


def test_lru_eviction_bounds_entries():
    cache = cc.CompileCache(max_entries=2)
    for i in range(5):
        cache.get_or_build(("k", i), lambda i=i: i)
    assert cache.stats()["entries"] == 2
    # evicted key rebuilds (a miss), resident key hits
    assert cache.get_or_build(("k", 0), lambda: "rebuilt") == "rebuilt"
    assert cache.get_or_build(("k", 4), lambda: "wrong") == 4


# ------------------------------------------------------------------ #
# speculative neighbor compilation


def test_neighbor_world_sizes_with_simulated_cohort(monkeypatch):
    """Candidate ordering from a simulated multi-process context
    (EDL_NUM_PROCESSES): the announced pending size first, then N±1."""
    from elasticdl_tpu.parallel.elastic import (
        context_from_env, neighbor_world_sizes,
    )

    monkeypatch.setenv("EDL_NUM_PROCESSES", "4")
    monkeypatch.setenv("EDL_PROCESS_ID", "0")
    from elasticdl_tpu.common.config import JobConfig

    ctx = context_from_env(JobConfig(model_def="x"))
    assert ctx is not None and ctx.num_processes == 4
    assert neighbor_world_sizes(ctx.num_processes) == [3, 5]
    assert neighbor_world_sizes(ctx.num_processes, pending=2) == [2, 3, 5]
    assert neighbor_world_sizes(2, pending=2, min_size=1) == [1, 3]
    assert neighbor_world_sizes(1) == [2]


def test_membership_signal_roundtrip(tmp_path):
    path = str(tmp_path / "sig.json")
    assert membership_signal.pending_size(path) is None
    assert membership_signal.write_signal(path, world_size=4, pending_size=3)
    assert membership_signal.pending_size(path) == 3
    sig = membership_signal.read_signal(path)
    assert sig["world_size"] == 4 and sig["pending_size"] == 3
    # clearing the pending size (resize landed)
    membership_signal.write_signal(path, world_size=3, world_version=1)
    assert membership_signal.pending_size(path) is None


def test_speculative_compile_hits_on_actual_resize(spec, mesh8, tmp_path,
                                                   monkeypatch):
    """The tentpole flow, simulated multi-process via EDL_NUM_PROCESSES:
    steady state at world size 8 (1 device per process), master announces
    4 via the signal file, the speculative compiler precompiles the
    neighbor world EXECUTION-FREE, and the post-resize trainer's programs
    are all cache hits — counter-asserted, plus the AOT executable runs."""
    import jax

    from elasticdl_tpu.parallel.mesh import build_mesh

    monkeypatch.setenv("EDL_NUM_PROCESSES", "8")
    monkeypatch.setenv("EDL_PROCESS_ID", "0")
    cache = cc.CompileCache()
    batch = census_batch()
    devices = jax.devices()

    t_full = make_trainer(spec, mesh8, cache)
    state = t_full.init_state(batch)
    state, _ = t_full.train_step(state, batch)

    signal_path = str(tmp_path / "membership_signal.json")
    membership_signal.write_signal(signal_path, world_size=8, pending_size=4)

    compiled_meshes = {}

    def compile_for_size(size):
        if size < 1 or size > len(devices) or 16 % size:
            raise cc.SpeculativeCompiler.SkipSize(f"size {size}")
        mesh = build_mesh({"data": size}, devices[:size])
        t = make_trainer(spec, mesh, cache)
        abs_state = t.abstract_train_state(batch)
        t.aot_compile_train_step(abs_state, batch, speculative=True,
                                 abstract=True)
        compiled_meshes[size] = mesh

    speculator = cc.SpeculativeCompiler(
        compile_for_size, 8, max_size=len(devices), signal_path=signal_path
    )
    # the announced size is compiled first
    assert speculator.candidate_sizes()[0] == 4
    compiled = speculator.precompile_once()
    assert 4 in compiled
    assert cache.stats()["speculative_compiles"] >= 1

    # the resize lands: the new trainer re-traces NOTHING
    cache.reset_stats()
    from elasticdl_tpu.parallel import elastic

    new_mesh = compiled_meshes[4]
    handoff = elastic.LiveStateHandoff().capture(state)
    t_new = make_trainer(spec, new_mesh, cache)
    new_state = handoff.apply(new_mesh)
    new_state, logs = t_new.train_step(new_state, batch)
    stats = cache.stats()
    assert stats["misses"] == 0, stats
    assert stats["hits"] >= 1, stats
    assert stats["hit_rate"] == 1.0
    assert int(new_state.step) == int(jax.device_get(state.step)) + 1
    assert np.isfinite(float(logs["loss"]))


def test_speculative_compiler_skips_and_failures_are_contained():
    calls = []

    def compile_for_size(size):
        calls.append(size)
        if size == 3:
            raise cc.SpeculativeCompiler.SkipSize("not representable")
        if size == 5:
            raise RuntimeError("boom")

    speculator = cc.SpeculativeCompiler(compile_for_size, 4)
    compiled = speculator.precompile_once()
    assert compiled == []                  # 3 skipped, 5 failed
    assert sorted(calls) == [3, 5]
    # neither is retried while the candidate set is unchanged
    assert speculator.precompile_once() == []
    assert sorted(calls) == [3, 5]
    # a resize resets both sets
    speculator.notify_resize(6)
    speculator.precompile_once()
    assert 7 in calls


def test_process_manager_announces_pending_size(tmp_path):
    """add/remove_worker on a cohort manager write the pending-membership
    signal file (no spawn happens until the watch loop acts), and spawned
    workers would inherit its path via EDL_PENDING_WORLD_FILE."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.master.process_manager import ProcessManager

    cfg = JobConfig(model_def="x", num_processes=2)
    mgr = ProcessManager(cfg, log_dir=str(tmp_path / "logs"))
    path = mgr._signal_path
    assert path.endswith("membership_signal.json")

    assert mgr.add_worker() == 3
    sig = membership_signal.read_signal(path)
    assert sig["world_size"] == 2 and sig["pending_size"] == 3
    assert membership_signal.pending_size(path) == 3

    assert mgr.remove_worker() == 2
    assert mgr.remove_worker() == 1
    assert membership_signal.pending_size(path) == 1
    assert mgr.pending_size() == 1


# ------------------------------------------------------------------ #
# live state handoff


def test_live_handoff_bitexact_vs_checkpoint_restore(spec, mesh8, tmp_path):
    """The acceptance gate: skipping the restore round trip changes no
    bit of the params (or opt state)."""
    import jax

    from elasticdl_tpu.parallel import elastic
    from elasticdl_tpu.parallel.mesh import build_mesh
    from elasticdl_tpu.training.checkpoint import CheckpointManager

    cache = cc.CompileCache()
    batch = census_batch()
    t_full = make_trainer(spec, mesh8, cache)
    state = t_full.init_state(batch)
    for i in range(2):
        state, _ = t_full.train_step(state, census_batch(seed=i))

    mngr = CheckpointManager(str(tmp_path / "ckpt"))
    mngr.save(state, wait=True)

    new_mesh = build_mesh({"data": 4}, jax.devices()[:4])
    t_new = make_trainer(spec, new_mesh, cache)
    restored = mngr.restore(t_new.abstract_train_state(batch))

    handoff = elastic.LiveStateHandoff().capture(state)
    assert handoff.step == 2
    handed = handoff.apply(new_mesh)
    assert not handoff.captured            # one-shot

    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get((handed.params,
                                                  handed.opt_state))),
        jax.tree_util.tree_leaves(jax.device_get((restored.params,
                                                  restored.opt_state))),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # every handed leaf lives on the new mesh
    for leaf in jax.tree_util.tree_leaves(handed.params):
        assert set(leaf.sharding.device_set) <= set(new_mesh.devices.flat)
    mngr.close()


def test_restore_or_handoff_prefers_fresh_capture(spec, mesh8, tmp_path):
    """restore_or_handoff: a capture at least as new as the durable step
    is applied (no restore); an older capture is discarded and restore
    wins."""
    import jax

    from elasticdl_tpu.parallel import elastic
    from elasticdl_tpu.parallel.mesh import build_mesh
    from elasticdl_tpu.training.checkpoint import CheckpointManager

    cache = cc.CompileCache()
    batch = census_batch()
    t_full = make_trainer(spec, mesh8, cache)
    state = t_full.init_state(batch)
    state, _ = t_full.train_step(state, batch)       # step 1
    stale = elastic.LiveStateHandoff().capture(state)
    state, _ = t_full.train_step(state, batch)       # step 2

    mngr = CheckpointManager(str(tmp_path / "ckpt"))
    mngr.save(state, wait=True)                      # durable step 2

    new_mesh = build_mesh({"data": 4}, jax.devices()[:4])
    t_new = make_trainer(spec, new_mesh, cache)
    abstract = t_new.abstract_train_state(batch)

    # stale capture (step 1) loses to the durable step 2
    got = mngr.restore_or_handoff(abstract, stale, new_mesh)
    assert int(jax.device_get(got.step)) == 2
    assert not stale.captured

    # fresh capture (step 2 == durable step 2) wins without a restore
    fresh = elastic.LiveStateHandoff().capture(state)
    got2 = mngr.restore_or_handoff(abstract, fresh, new_mesh)
    assert int(jax.device_get(got2.step)) == 2
    assert mngr.last_restored_step == 2
    mngr.close()


def test_save_overlapped_runs_teardown_during_write(spec, mesh8, tmp_path):
    from elasticdl_tpu.training.checkpoint import CheckpointManager

    cache = cc.CompileCache()
    batch = census_batch()
    t = make_trainer(spec, mesh8, cache)
    state = t.init_state(batch)
    mngr = CheckpointManager(str(tmp_path / "ckpt"))
    ran = []
    step = mngr.save_overlapped(state, lambda: ran.append(True))
    assert ran == [True]
    assert mngr.latest_step(refresh=True) == step
    # overlap work failing must not lose the durable checkpoint
    state2, _ = t.train_step(state, batch)

    def boom():
        raise RuntimeError("teardown failed")

    step2 = mngr.save_overlapped(state2, boom)
    assert mngr.latest_step(refresh=True) == step2
    mngr.close()


def test_stage_to_host_scopes_snapshot_to_changed_owners(spec, mesh8):
    """stage_to_host pulls ONLY leaves owned (partly) outside the
    surviving device set; fully-surviving leaves stay on device."""
    import jax

    from elasticdl_tpu.parallel import elastic
    from elasticdl_tpu.parallel.mesh import build_mesh

    cache = cc.CompileCache()
    batch = census_batch()
    t = make_trainer(spec, mesh8, cache)
    state = t.init_state(batch)

    surviving = [d.id for d in jax.devices()[:4]]
    handoff = elastic.LiveStateHandoff().capture(state)
    staged = handoff.stage_to_host(surviving)
    # replicated/sharded leaves over all 8 devices all have owners outside
    # the surviving half, so something must stage; the applied result is
    # still bit-exact on the new mesh
    assert staged > 0
    new_mesh = build_mesh({"data": 4}, jax.devices()[:4])
    handed = handoff.apply(new_mesh)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(handed.params)),
        jax.tree_util.tree_leaves(jax.device_get(state.params)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------------ #
# worker in-place rescale (prefetch drain + live handoff + cache reuse)


def test_worker_inplace_rescale_preserves_state_and_hits_cache(monkeypatch):
    import jax

    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.worker.worker import Worker

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = JobConfig(
        model_zoo=os.path.join(repo, "model_zoo"),
        model_def="census.wide_deep.custom_model",
        minibatch_size=16,
    )
    worker = Worker(cfg)
    worker._build_trainer()
    batch = census_batch()
    worker._ensure_state(batch)
    state_before = jax.device_get(worker._state.params)
    worker._state, _ = worker._trainer.train_step(worker._state, batch)
    step_before = int(jax.device_get(worker._state.step))

    worker.request_rescale({"data": 4}, jax.devices()[:4])
    worker._rescale_in_place()
    assert worker.last_recovery_s is not None
    assert dict(zip(worker._mesh.axis_names,
                    worker._mesh.devices.shape)) == {"data": 4}
    assert int(jax.device_get(worker._state.step)) == step_before
    # training continues on the new mesh with the handed-over state
    worker._state, logs = worker._trainer.train_step(worker._state, batch)
    assert np.isfinite(float(logs["loss"]))
    assert int(jax.device_get(worker._state.step)) == step_before + 1
    del state_before
