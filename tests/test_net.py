"""common/net.py: free-port picking and the bind-retry TOCTOU closure."""

import socket

import pytest

from elasticdl_tpu.common.net import PortBindError, bind_with_retry, free_port


def test_free_port_is_bindable():
    port = free_port()
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", port))


def test_bind_with_retry_returns_first_success():
    seen = []
    port, result = bind_with_retry(lambda p: seen.append(p) or f"server:{p}")
    assert result == f"server:{port}"
    assert seen == [port]


def test_bind_with_retry_retries_lost_races_with_fresh_ports():
    attempts = []

    def build(port):
        attempts.append(port)
        if len(attempts) < 3:
            raise PortBindError(f"port {port} taken")
        return "server"

    port, result = bind_with_retry(build, attempts=5)
    assert result == "server" and port == attempts[-1]
    assert len(attempts) == 3
    # (no distinct-port assertion: the OS may legally hand the same
    # ephemeral port back since the fake build() never actually binds it)


def test_bind_with_retry_gives_up_after_attempts():
    def build(port):
        raise PortBindError("always taken")

    with pytest.raises(PortBindError):
        bind_with_retry(build, attempts=3)


def test_master_raises_port_bind_error_on_taken_port():
    """Master's bind failure is the typed error bind_with_retry keys on."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.master.main import Master

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        s.listen(1)
        taken = s.getsockname()[1]
        cfg = JobConfig(
            model_def="mnist.mnist_cnn.custom_model",
            job_type="training_only",
            training_data="synthetic://mnist?n=32&shards=1",
            master_addr=f"localhost:{taken}",
        )
        with pytest.raises(PortBindError):
            Master(cfg)
