"""Hybrid ICI x DCN mesh (parallel/mesh.build_hybrid_mesh): layout invariants
on the virtual 8-device mesh, and a real sharded train step over it."""

import jax
import numpy as np
import pytest

from elasticdl_tpu.parallel.mesh import (
    build_hybrid_mesh,
    build_mesh,
    shard_batch,
)


def test_hybrid_layout_dcn_slowest():
    """dcn {"data": 2} x ici {"data": 2, "model": 2}: the data axis is 4
    with slice blocks slowest-varying — devices of one slice (contiguous
    ids) stay adjacent along every axis, so intra-slice collectives never
    hop the slow tier."""
    devs = jax.devices()[:8]
    mesh = build_hybrid_mesh(
        {"data": 2, "model": 2}, {"data": 2}, devices=devs)
    assert mesh.axis_names == ("data", "model")
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    grid = np.vectorize(lambda d: d.id)(mesh.devices)
    # slice 0 = devices 0-3 occupies data rows 0-1; slice 1 rows 2-3
    np.testing.assert_array_equal(grid, [[0, 1], [2, 3], [4, 5], [6, 7]])


def test_hybrid_pure_dp_across_slices():
    devs = jax.devices()[:8]
    mesh = build_hybrid_mesh({"data": 4}, {"data": 2}, devices=devs)
    assert dict(mesh.shape) == {"data": 8}
    grid = np.vectorize(lambda d: d.id)(mesh.devices)
    np.testing.assert_array_equal(grid, list(range(8)))


def test_hybrid_size_mismatch_raises():
    with pytest.raises(ValueError, match="needs 16 devices"):
        build_hybrid_mesh({"data": 4, "model": 2}, {"data": 2},
                          devices=jax.devices()[:8])


def test_build_job_mesh_from_config():
    """--dcn_mesh_shape data=2 --mesh_shape data=2,model=2 resolves to the
    hybrid mesh; unset dcn gives the flat path; bad divisors fail loudly."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.parallel.mesh import build_job_mesh

    devs = jax.devices()[:8]
    cfg = JobConfig(
        model_zoo="model_zoo", model_def="m.m.f",
        mesh_shape="data=2,model=2", dcn_mesh_shape="data=2",
    )
    mesh = build_job_mesh(cfg, devs)
    assert dict(mesh.shape) == {"data": 4, "model": 2}

    flat = build_job_mesh(JobConfig(model_zoo="z", model_def="m.m.f"), devs)
    assert dict(flat.shape) == {"data": 8}

    with pytest.raises(ValueError, match="does not divide"):
        build_job_mesh(
            JobConfig(model_zoo="z", model_def="m.m.f", dcn_mesh_shape="data=3"),
            devs)
    with pytest.raises(ValueError, match="named form"):
        JobConfig(model_zoo="z", model_def="m.m.f",
                  dcn_mesh_shape="2").dcn_axes_sizes()


def test_train_step_on_hybrid_mesh():
    """DeepFM trains on the 2-slice hybrid mesh: gradient psum spans the
    full data axis (both tiers), embedding rows shard over data x model."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.training.model_spec import ModelSpec
    from elasticdl_tpu.training.trainer import Trainer

    mesh = build_hybrid_mesh(
        {"data": 2, "model": 2}, {"data": 2}, devices=jax.devices()[:8])
    cfg = JobConfig(
        model_zoo="model_zoo",
        model_def="deepfm.deepfm.custom_model",
        model_params={"field_vocab": 64, "hidden": "16,16"},
    )
    trainer = Trainer(ModelSpec.from_config(cfg), mesh)
    r = np.random.RandomState(0)
    batch = {
        "features": {
            "dense": r.rand(8, 13).astype(np.float32),
            "cat": r.randint(0, 1 << 20, (8, 26)).astype(np.int32),
        },
        "labels": r.randint(0, 2, (8,)).astype(np.int32),
        "mask": np.ones((8,), np.float32),
    }
    state = trainer.init_state(batch)
    state, logs = trainer.train_step(state, batch)
    assert np.isfinite(float(logs["loss"]))
    assert state.model_version == 1

    # the batch really is split over all 8 devices (4 data shards x 2
    # model-replicated), matching the plain-mesh sharding semantics
    sharded = shard_batch(mesh, batch)
    assert len(sharded["labels"].sharding.device_set) == 8


def test_hybrid_equals_flat_mesh_numerics():
    """A hybrid (2-slice) data axis must give the same training math as the
    flat 8-device mesh — hierarchy changes the collective ROUTE, not the
    result."""
    from elasticdl_tpu.common.config import JobConfig
    from elasticdl_tpu.training.model_spec import ModelSpec
    from elasticdl_tpu.training.trainer import Trainer

    cfg = JobConfig(
        model_zoo="model_zoo",
        model_def="mnist.mnist_cnn.custom_model",
        model_params={"learning_rate": 0.01},
    )
    r = np.random.RandomState(1)
    batch = {
        "features": r.rand(16, 28, 28, 1).astype(np.float32),
        "labels": r.randint(0, 10, (16,)).astype(np.int32),
        "mask": np.ones((16,), np.float32),
    }
    losses = []
    for mesh in (
        build_mesh({"data": 8}, jax.devices()[:8]),
        build_hybrid_mesh({"data": 4}, {"data": 2}, devices=jax.devices()[:8]),
    ):
        tr = Trainer(ModelSpec.from_config(cfg), mesh, seed=0)
        st = tr.init_state(batch)
        st, logs = tr.train_step(st, batch)
        losses.append(float(logs["loss"]))
    assert losses[0] == pytest.approx(losses[1], rel=1e-5)
