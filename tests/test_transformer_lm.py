"""Transformer LM zoo config: trains sequence-parallel on a (data x seq)
mesh, input partitioning honored end to end, loss falls on the synthetic
bigram stream."""

import numpy as np

from tests.conftest import (
    requires_spmd_partitioning,
    requires_tp_exact_backend,
)
import pytest

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.data.reader import SyntheticDataReader, create_data_reader
from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.training.model_spec import ModelSpec
from elasticdl_tpu.training.trainer import Trainer

MODEL_PARAMS = {
    "vocab": 64, "num_layers": 2, "dim": 64, "heads": 4,
    "max_len": 64, "seq_parallel": "ring",
}


def make_spec(**over):
    cfg = JobConfig(
        model_zoo="model_zoo",
        model_def="transformer.transformer_lm.custom_model",
        model_params={**MODEL_PARAMS, **over},
    )
    return ModelSpec.from_config(cfg)


@pytest.fixture(scope="module")
def reader():
    return SyntheticDataReader(kind="lm", num_records=512, vocab=64, seq_len=32)


def make_batch(spec, reader, i, n=8):
    parse = spec.dataset_fn("training", reader.metadata)
    feats, labs = zip(*(parse(r) for r in reader.read_records("s", i * n, (i + 1) * n)))
    return {
        "features": np.stack(feats), "labels": np.stack(labs),
        "mask": np.ones((n,), np.float32),
    }


def test_synthetic_lm_reader_via_url():
    r = create_data_reader("synthetic://lm?n=100&shards=2&vocab=32&seq_len=16")
    recs = list(r.read_records(*r.create_shards()[0]))
    toks = np.frombuffer(recs[0], np.uint16)
    assert toks.shape == (17,) and toks.max() < 32
    assert r.metadata["vocab"] == 32 and r.metadata["seq_len"] == 16


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_lm_trains_on_seq_mesh(reader, mode):
    spec = make_spec(seq_parallel=mode)
    mesh = build_mesh({"data": 2, "seq": 4})
    trainer = Trainer(spec, mesh, seed=0)
    state = trainer.init_state(make_batch(spec, reader, 0))
    losses = []
    for i in range(12):
        state, logs = trainer.train_step(state, make_batch(spec, reader, i % 8))
        losses.append(float(logs["loss"]))
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses
    assert state.model_version == 12

    ms = trainer.new_metric_states()
    ms = trainer.eval_step(state, make_batch(spec, reader, 9), ms)
    res = trainer.metric_results(ms)
    assert "token_accuracy" in res and 0.0 <= res["token_accuracy"] <= 1.0


def test_batch_partition_applied(reader):
    from jax.sharding import PartitionSpec as P

    spec = make_spec()
    assert spec.batch_partition["features"] == P("data", "seq")
    mesh = build_mesh({"data": 2, "seq": 4})
    trainer = Trainer(spec, mesh, seed=0)
    state = trainer.init_state(make_batch(spec, reader, 0))
    state, _ = trainer.train_step(state, make_batch(spec, reader, 1))

    from elasticdl_tpu.parallel.mesh import shard_batch

    b = shard_batch(mesh, make_batch(spec, reader, 2), spec.batch_partition)
    # compare shardings, not raw specs: older jax normalizes spec entries
    # to tuples (('data',) vs 'data'), so spec == spec is version-fragile
    from jax.sharding import NamedSharding

    f = b["features"]
    assert f.sharding.is_equivalent_to(
        NamedSharding(mesh, P("data", "seq")), f.ndim)
    m = b["mask"]
    assert m.sharding.is_equivalent_to(NamedSharding(mesh, P("data")), m.ndim)


def test_lm_single_axis_mesh_fallback(reader):
    """Without a seq axis the model runs plain full attention (single-chip
    deployments of the same zoo config)."""
    spec = make_spec()
    mesh = build_mesh({"data": 8})
    trainer = Trainer(spec, mesh, seed=0)
    state = trainer.init_state(make_batch(spec, reader, 0))
    state, logs = trainer.train_step(state, make_batch(spec, reader, 1))
    assert np.isfinite(float(logs["loss"]))


def test_remat_accum_with_flash_kernel(reader, monkeypatch):
    """The HBM knobs must compose with the Pallas flash kernel: a train
    step with remat_policy='dots' + grad_accum=2 and the flash path forced
    on (EDL_FLASH=1 + interpret mode, the production-TPU path emulated)
    must match the plain step's first loss — remat recompute re-runs the
    kernel in the backward, which nothing else covers."""
    from elasticdl_tpu.ops.pallas_attention import interpret_mode

    spec = make_spec(seq_parallel="ring")
    mesh = build_mesh({"data": 2, "seq": 4})
    batch = make_batch(spec, reader, 0)

    def first_loss(**kw):
        t = Trainer(spec, mesh, seed=0, **kw)
        _, logs = t.train_step(t.init_state(batch), batch)
        return float(logs["loss"])

    monkeypatch.setenv("EDL_FLASH", "1")
    with interpret_mode():
        plain = first_loss()
        knobs = first_loss(remat_policy="dots", grad_accum=2)
    assert knobs == pytest.approx(plain, rel=1e-4), (plain, knobs)


@requires_tp_exact_backend
def test_tensor_parallel_matches_replicated(reader):
    """Megatron-style TP (tp_axis=model): same seed, same batch, one train
    step — loss and (gathered) params must match the replicated run, with
    kernels actually sharded over the model axis. GSPMD inserts the
    row-split partial-sum all-reduce the hand-written Megatron psum would
    do."""
    base = dict(seq_parallel="none", compute_dtype="float32")
    spec_rep = make_spec(**base)
    spec_tp = make_spec(**base, tp_axis="model")
    mesh = build_mesh({"data": 2, "model": 4})

    def one_step(spec):
        trainer = Trainer(spec, mesh, seed=0)
        batch = make_batch(spec, reader, 0)
        state = trainer.init_state(batch)
        state, logs = trainer.train_step(state, batch)
        return state, float(logs["loss"])

    state_rep, loss_rep = one_step(spec_rep)
    state_tp, loss_tp = one_step(spec_tp)
    assert loss_tp == pytest.approx(loss_rep, rel=1e-4)

    # kernels are genuinely split over the model axis: col-split q and
    # row-split mlp_out, each device holding 1/4 of the split dim
    q = state_tp.params["block_0"]["q"]["kernel"]
    assert "model" in tuple(q.sharding.spec), q.sharding.spec
    assert q.sharding.shard_shape(q.shape)[1] == q.shape[1] // 4
    mlp_out = state_tp.params["block_0"]["mlp_out"]["kernel"]
    assert "model" in tuple(mlp_out.sharding.spec), mlp_out.sharding.spec

    # params agree after one step (gather the tp shards)
    for name in ("q", "k", "v", "mlp_in", "mlp_out", "proj"):
        a = np.asarray(state_rep.params["block_0"][name]["kernel"])
        b = np.asarray(state_tp.params["block_0"][name]["kernel"])
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def _compiled_step_collectives(spec, mesh, reader):
    import jax

    from elasticdl_tpu.parallel import mesh as mesh_lib
    from tests.test_comm_structure import collective_sizes

    trainer = Trainer(spec, mesh, seed=0)
    batch = make_batch(spec, reader, 0)
    state = trainer.init_state(batch)
    state, _ = trainer.train_step(state, batch)   # builds the jitted step
    sharded = mesh_lib.shard_batch(mesh, batch, spec.batch_partition)
    with jax.set_mesh(mesh):
        hlo = trainer._train_step.lower(state, sharded).compile().as_text()
    return collective_sizes(hlo)


def test_tensor_parallel_inserts_model_axis_collectives(reader):
    """TP must actually distribute the matmuls: the compiled TP step
    carries MORE reduction collectives than the replicated baseline (the
    row-split partial-sum all-reduces over `model`, on top of the DP
    gradient sync both versions share). A bare "has an all-reduce" check
    would be vacuous — DP grad sync alone satisfies it."""
    mesh = build_mesh({"data": 2, "model": 4})
    base = dict(seq_parallel="none", compute_dtype="float32")
    n_base = sum(
        1 for op, _ in _compiled_step_collectives(make_spec(**base), mesh, reader)
        if "all-reduce" in op or "reduce-scatter" in op
    )
    n_tp = sum(
        1 for op, _ in _compiled_step_collectives(
            make_spec(**base, tp_axis="model"), mesh, reader)
        if "all-reduce" in op or "reduce-scatter" in op
    )
    assert n_tp > n_base, (n_tp, n_base)


@requires_spmd_partitioning
def test_pipeline_parallel_lm_matches_no_pp_mesh(reader):
    """pp_axis=pp: the SAME module + params run pipelined on a data x pp
    mesh and sequentially on a data-only mesh (gpipe's fallback) — one
    train step must produce the same loss, proving the schedule computes
    the same function. Then it trains."""
    import jax

    spec = make_spec(num_layers=4, pp_axis="pp", seq_parallel="none",
                     compute_dtype="float32")
    mesh_pp = build_mesh({"data": 2, "pp": 4})
    mesh_seq = build_mesh({"data": 2}, jax.devices()[:2])

    def one_step(mesh):
        trainer = Trainer(spec, mesh, seed=0)
        batch = make_batch(spec, reader, 0)
        state = trainer.init_state(batch)
        state, logs = trainer.train_step(state, batch)
        return state, float(logs["loss"])

    state_pp, loss_pp = one_step(mesh_pp)
    _, loss_seq = one_step(mesh_seq)
    assert loss_pp == pytest.approx(loss_seq, rel=1e-4)

    # stacked layer params genuinely shard over pp
    wq = state_pp.params["pipeline"]["wq"]
    assert "pp" in tuple(wq.sharding.spec), wq.sharding.spec
    assert wq.sharding.shard_shape(wq.shape)[0] == 1   # one layer per shard

    # and the pipelined model LEARNS
    trainer = Trainer(spec, mesh_pp, seed=0)
    state = trainer.init_state(make_batch(spec, reader, 0))
    losses = []
    for i in range(10):
        state, logs = trainer.train_step(state, make_batch(spec, reader, i % 8))
        losses.append(float(logs["loss"]))
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses


def test_pipeline_and_tensor_parallel_mutually_exclusive(reader):
    spec = make_spec(num_layers=4, pp_axis="pp", tp_axis="model",
                     seq_parallel="none")
    mesh = build_mesh({"data": 2, "pp": 4})
    trainer = Trainer(spec, mesh, seed=0)
    with pytest.raises(ValueError, match="mutually exclusive"):
        trainer.init_state(make_batch(spec, reader, 0))


def test_pipeline_rejects_dropout_and_seq_parallel(reader):
    mesh = build_mesh({"data": 2, "pp": 4})
    for params, msg in [
        (dict(pp_axis="pp", dropout=0.1, seq_parallel="none"), "dropout"),
        (dict(pp_axis="pp", seq_parallel="ring"), "seq_parallel"),
    ]:
        spec = make_spec(num_layers=4, **params)
        trainer = Trainer(spec, mesh, seed=0)
        with pytest.raises(ValueError, match=msg):
            trainer.init_state(make_batch(spec, reader, 0))
