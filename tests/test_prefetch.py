"""Device prefetcher: value fidelity, lookahead, wire casting, early
abandonment, error propagation, passthrough mode."""

import numpy as np
import pytest

from elasticdl_tpu.data.prefetch import prefetch_to_device


def host_batches(n, size=8):
    for i in range(n):
        yield {
            "features": np.full((size, 3), i, np.float32),
            "labels": np.arange(size, dtype=np.int32) + i,
            "mask": np.ones((size,), np.float32),
        }


def test_yields_all_batches_in_order_on_device(mesh8):
    import jax

    out = list(prefetch_to_device(mesh8, host_batches(5), depth=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["features"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["features"]), i)
        np.testing.assert_array_equal(np.asarray(b["labels"]), np.arange(8) + i)


def test_short_streams_and_empty(mesh8):
    assert len(list(prefetch_to_device(mesh8, host_batches(1), depth=4))) == 1
    assert list(prefetch_to_device(mesh8, host_batches(0), depth=2)) == []


def test_early_break_is_clean(mesh8):
    it = prefetch_to_device(mesh8, host_batches(1000), depth=2)
    for i, _ in enumerate(it):
        if i == 2:
            break
    it.close()


def test_error_propagates(mesh8):
    def bad():
        yield from host_batches(5)
        raise RuntimeError("reader exploded")

    got = []
    with pytest.raises(RuntimeError, match="reader exploded"):
        for b in prefetch_to_device(mesh8, bad(), depth=2):
            got.append(b)
    # lookahead surfaces the source error up to `depth` batches early, but
    # every batch before the lookahead window was delivered intact
    assert len(got) >= 3


def test_depth_zero_passthrough(mesh8):
    import jax

    out = list(prefetch_to_device(mesh8, host_batches(3), depth=0))
    assert len(out) == 3
    assert isinstance(out[0]["features"], jax.Array)


def test_drain_returns_pending_host_batches(mesh8):
    """Reform hook: drain() hands back the lookahead window's HOST batches
    (device copies die with the old mesh) and ends iteration; the
    un-consumed source survives for requeueing."""
    from elasticdl_tpu.data.prefetch import DevicePrefetcher

    pf = DevicePrefetcher(mesh8, host_batches(6), depth=3)
    first = next(pf)                       # fills the window to 3
    np.testing.assert_array_equal(np.asarray(first["features"]), 0)
    pending = pf.drain()
    assert [int(b["features"][0, 0]) for b in pending] == [1, 2]
    assert all(isinstance(b["features"], np.ndarray) for b in pending)
    with pytest.raises(StopIteration):
        next(pf)
    # batches never pulled into the window remain on the source
    rest = [int(b["features"][0, 0]) for b in pf.source]
    assert rest == [3, 4, 5]


def test_drain_then_requeue_covers_every_batch(mesh8):
    """The worker's rescale flow: drained + remaining batches re-enter a
    new prefetcher — every batch is delivered exactly once."""
    import itertools

    from elasticdl_tpu.data.prefetch import DevicePrefetcher

    pf = DevicePrefetcher(mesh8, host_batches(8), depth=2)
    seen = [int(np.asarray(next(pf)["features"])[0, 0]) for _ in range(2)]
    leftover, source = pf.drain(), pf.source
    pf2 = DevicePrefetcher(mesh8, itertools.chain(iter(leftover), source),
                           depth=2)
    seen += [int(np.asarray(b["features"])[0, 0]) for b in pf2]
    assert seen == list(range(8))


def test_depth_and_cast_resolve_from_env(mesh8, monkeypatch):
    from elasticdl_tpu.data import prefetch

    monkeypatch.setenv("EDL_PREFETCH_DEPTH", "5")
    monkeypatch.setenv("EDL_PREFETCH_CAST", "bfloat16")
    pf = prefetch.prefetch_to_device(mesh8, host_batches(1))
    assert pf.depth == 5 and pf.cast == "bfloat16"
    # explicit arguments win over the environment
    pf2 = prefetch.prefetch_to_device(mesh8, host_batches(1), 1, cast="")
    assert pf2.depth == 1 and pf2.cast == ""
    # garbage depth falls back to the default
    monkeypatch.setenv("EDL_PREFETCH_DEPTH", "nope")
    assert prefetch.resolve_depth(None) == prefetch.DEFAULT_DEPTH


def test_wire_cast_bfloat16(mesh8):
    import jax.numpy as jnp

    out = list(prefetch_to_device(mesh8, host_batches(2), depth=2, cast="bfloat16"))
    # float leaves travel as bf16; int leaves untouched
    assert out[0]["features"].dtype == jnp.bfloat16
    # mask must stay f32: its sum drives exactly-once record accounting
    assert out[0]["mask"].dtype == jnp.float32
    assert out[0]["labels"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out[1]["features"], np.float32), 1.0)
