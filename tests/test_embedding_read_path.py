"""Serving-grade embedding reads (ISSUE 13): the staleness-bounded
hot-row cache (watermark fencing, write-through, full invalidation),
read replicas (delta sync, primary-only writes, stale rejection,
owner-death promotion), the pull/compute overlap pipeline (ordering,
drain/re-issue), the journal-replayed replica map, and the
pull-vs-read latency split in tier_stats()."""

import numpy as np
import pytest

from elasticdl_tpu.embedding import sharding, tier, transport
from elasticdl_tpu.embedding.cache import HotRowCache
from elasticdl_tpu.embedding.store import (
    EmbeddingShardStore,
    StaleShardMapError,
    load_shard_file,
)
from elasticdl_tpu.embedding.transport import LocalTransport

SPEC = sharding.TableSpec("users", vocab=4096, dim=8, seed=3)


def make_read_tier(num_shards=4, owners=(0, 1), replicas_per_shard=0,
                   cache_rows=0, staleness=1, read_replicas=False,
                   client_id="rp", sync=True):
    assignment = sharding.assign_round_robin(num_shards, list(owners))
    rep_map = sharding.assign_replicas(
        assignment, list(owners), replicas_per_shard)
    view = sharding.ShardMapView(
        version=1, num_shards=num_shards, owners=tuple(assignment),
        tables=(SPEC,), replicas=tuple(tuple(r) for r in rep_map),
    )
    tr = LocalTransport()
    stores = {}
    for o in owners:
        st = EmbeddingShardStore(o, device=False)
        st.attach(view)
        tr.register(st)
        stores[o] = st
    if sync and replicas_per_shard:
        for s in range(num_shards):
            for rep in view.replicas_of(s):
                stores[rep].sync_replica_from(
                    tr, view.owner_of(s), "users", s)
    client = tier.EmbeddingTierClient(
        lambda: view, tr, client_id=client_id, retry_backoff_s=0.001,
        cache_rows=cache_rows, cache_staleness=staleness,
        read_replicas=read_replicas,
    )
    return view, tr, stores, client


def oracle_pull(tr, view, ids):
    c = tier.EmbeddingTierClient(
        lambda: view, tr, client_id="oracle", retry_backoff_s=0.001)
    return c.pull("users", ids)


# ------------------------------------------------------------------ #
# store watermarks + delta log


def test_push_watermark_counts_applied_pushes_and_travels():
    view, tr, stores, client = make_read_tier(num_shards=2, owners=(0,))
    st = stores[0]
    assert st.shard_watermark("users", 0) == 0
    for i in range(3):
        client.push("users", np.array([0, 2, 4]),
                    np.ones((3, 8), np.float32), scale=0.1)
    assert st.shard_watermark("users", 0) == 3
    # a duplicate (re-sent seq) does NOT bump the watermark
    ok, wm = st.push("users", 0, np.array([0], np.int32),
                     np.ones((1, 8), np.float32),
                     client_id=client.client_id, seq=1,
                     with_watermark=True)
    assert ok is False and wm == 3
    # the watermark rides extract/install payloads
    payload = st.extract_shard("users", 0)
    assert payload["wm"] == 3
    other = EmbeddingShardStore(9, device=False)
    other.install_shard("users", 0, payload)
    assert other.shard_watermark("users", 0) == 3


def test_watermark_rides_checkpoint_files(tmp_path):
    view, tr, stores, client = make_read_tier(num_shards=2, owners=(0,))
    client.push("users", np.array([1, 3]), np.ones((2, 8), np.float32))
    stores[0].save(str(tmp_path))
    payload = load_shard_file(str(tmp_path), "users", 1)
    assert payload is not None and payload["wm"] == 1


def test_delta_log_disabled_without_replicas_in_map():
    """A map with no replica assignments must not buffer gradient
    history per push — the log is pure memory/copy cost until something
    consumes it."""
    view, tr, stores, client = make_read_tier(num_shards=1, owners=(0,))
    client.push("users", np.arange(4), np.ones((4, 8), np.float32))
    sh = stores[0]._get_shard("users", 0, None)
    assert len(sh.deltas) == 0
    assert tr.fetch_delta(0, "users", 0, 0) is None  # full-copy path


def test_delta_log_sync_and_gap_fallback():
    view, tr, stores, client = make_read_tier(num_shards=1, owners=(0,))
    primary = stores[0]
    primary.set_delta_logging(True)
    replica = EmbeddingShardStore(7, device=False)
    replica.install_replica("users", 0, primary.extract_shard("users", 0))
    tr.register(replica)
    for i in range(4):
        client.push("users", np.arange(6) * 1 + i,
                    np.full((6, 8), 0.5, np.float32), scale=0.1)
    # delta sync lands the replica exactly on the primary
    wm = replica.sync_replica_from(tr, 0, "users", 0)
    assert wm == 4
    np.testing.assert_array_equal(
        replica.extract_shard("users", 0, replica=True)["rows"],
        primary.extract_shard("users", 0)["rows"])
    # exactly-once seq fence traveled via the delta entries: promoting
    # this replica dedupes a re-sent pre-sync push
    assert replica.extract_shard("users", 0, replica=True)["applied"] \
        == primary.extract_shard("users", 0)["applied"]
    # a replica further behind than the bounded log triggers the full
    # resync path (fetch_delta returns None)
    from elasticdl_tpu.embedding import store as store_lib

    stale = EmbeddingShardStore(8, device=False)
    stale.install_replica(
        "users", 0, {"rows": primary.extract_shard("users", 0)["rows"],
                     "applied": {}, "wm": 0})
    log_depth = store_lib.DELTA_LOG
    for i in range(log_depth + 2):
        client.push("users", np.array([2]),
                    np.ones((1, 8), np.float32), scale=0.01)
    assert tr.fetch_delta(0, "users", 0, 0) is None
    wm2 = stale.sync_replica_from(tr, 0, "users", 0)
    assert wm2 == primary.shard_watermark("users", 0)
    np.testing.assert_array_equal(
        stale.extract_shard("users", 0, replica=True)["rows"],
        primary.extract_shard("users", 0)["rows"])


def test_replica_rejects_pushes():
    view, tr, stores, client = make_read_tier(num_shards=1, owners=(0,))
    replica = EmbeddingShardStore(7, device=False)
    replica.install_replica("users", 0,
                            stores[0].extract_shard("users", 0))
    with pytest.raises(StaleShardMapError, match="READ replica"):
        replica.push("users", 0, np.array([0], np.int32),
                     np.ones((1, 8), np.float32), client_id="x", seq=1)


# ------------------------------------------------------------------ #
# hot-row cache: staleness fencing, write-through, invalidation


def test_cache_staleness_bound_honored_under_concurrent_pushes():
    """The watermark fencing contract: once the client OBSERVES the
    owner watermark past `entry_wm + bound`, the cached row is a miss —
    a foreign writer's pushes can never be hidden past the bound."""
    view, tr, stores, client = make_read_tier(
        num_shards=2, owners=(0, 1), cache_rows=256, staleness=1)
    ids = np.arange(32)
    client.pull("users", ids)                      # cache at wm 0
    writer = tier.EmbeddingTierClient(
        lambda: view, tr, client_id="writer", retry_backoff_s=0.001)
    for _ in range(3):                             # foreign pushes
        writer.push("users", np.array([2, 4, 6]),
                    np.ones((3, 8), np.float32), scale=0.5)
    # the client's own push ack carries the advanced watermark: every
    # cached row of that shard now exceeds the bound -> refetch
    client.push("users", np.array([8]),
                np.zeros((1, 8), np.float32), scale=1.0)
    got = client.pull("users", ids)
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))
    assert client.cache.stale_evictions > 0


def test_cache_watermark_probe_bounds_read_only_staleness():
    """A fully-cache-served client never touches a shard, so its
    watermark knowledge would freeze — the probe cadence refreshes it
    and the fence then fires."""
    view, tr, stores, client = make_read_tier(
        num_shards=2, owners=(0, 1), cache_rows=256, staleness=1)
    client.wm_probe_every = 2
    ids = np.arange(24)
    client.pull("users", ids)
    writer = tier.EmbeddingTierClient(
        lambda: view, tr, client_id="w2", retry_backoff_s=0.001)
    for _ in range(3):
        writer.push("users", np.array([1, 2, 3]),
                    np.ones((3, 8), np.float32), scale=0.5)
    for _ in range(4):                 # full-hit pulls tick the probe
        client.pull("users", ids)
    got = client.pull("users", ids)    # post-probe: fence fires
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))


def test_cache_write_through_keeps_own_pushes_warm():
    view, tr, stores, client = make_read_tier(
        num_shards=2, owners=(0, 1), cache_rows=256, staleness=0)
    ids = np.arange(16)
    client.pull("users", ids)
    h0 = client.cache.hits
    # single writer: our own push write-through re-tags the rows fresh
    # even at staleness 0 — the next pull is all hits and CORRECT
    client.push("users", ids, np.ones((16, 8), np.float32), scale=-0.5)
    got = client.pull("users", ids)
    assert client.cache.hits > h0
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))


def test_cache_interleaved_foreign_push_drops_instead_of_patching():
    """Write-through is only sound when OUR push was the shard's sole
    advance; an interleaved foreign push must drop the entry, not patch
    it fresh-but-wrong."""
    view, tr, stores, client = make_read_tier(
        num_shards=1, owners=(0,), cache_rows=256, staleness=0)
    ids = np.arange(8)
    client.pull("users", ids)
    writer = tier.EmbeddingTierClient(
        lambda: view, tr, client_id="w3", retry_backoff_s=0.001)
    writer.push("users", np.array([3]),
                np.full((1, 8), 7.0, np.float32), scale=1.0)
    client.push("users", ids, np.ones((16 // 2, 8), np.float32),
                scale=-0.25)
    got = client.pull("users", ids)
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))


def test_cache_invalidated_on_map_epoch_change_and_reshard_commit():
    views = {}

    def fetch():
        return views["v"]

    assignment = sharding.assign_round_robin(4, [0, 1])
    v1 = sharding.ShardMapView(
        version=1, num_shards=4, owners=tuple(assignment), tables=(SPEC,))
    tr = LocalTransport()
    for o in (0, 1):
        st = EmbeddingShardStore(o, device=False)
        st.attach(v1)
        tr.register(st)
    views["v"] = v1
    client = tier.EmbeddingTierClient(
        fetch, tr, client_id="inv", retry_backoff_s=0.001,
        cache_rows=256, cache_staleness=4)
    ids = np.arange(40)
    client.pull("users", ids)
    assert client.cache.stats()["resident_rows"] > 0
    # shard-map epoch change (reshard commit bumps version the same
    # way): refresh drops the WHOLE cache + watermark state
    views["v"] = sharding.ShardMapView(
        version=2, num_shards=4, owners=tuple(assignment), tables=(SPEC,))
    for o in (0, 1):
        tr.store_of(o).adopt_version(2)
    client.refresh()
    assert client.cache.stats()["resident_rows"] == 0
    got = client.pull("users", ids)
    np.testing.assert_allclose(got, oracle_pull(tr, views["v"], ids))


# ------------------------------------------------------------------ #
# replica reads


def test_replica_reads_fan_out_and_stay_consistent():
    """Least-loaded routing: once the primary carries more read load
    than its replica, reads go to the replica — and serve identical
    rows (single-shard tier makes the decision deterministic)."""
    view, tr, stores, client = make_read_tier(
        num_shards=1, owners=(0, 1), replicas_per_shard=1,
        read_replicas=True)
    assert view.replicas_of(0) == (1,)
    counter = tier._REPLICA_READS
    tot0 = counter.value(shard="0")
    ids = np.arange(64)
    got = client.pull("users", ids)      # tie -> primary, loads it
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))
    assert counter.value(shard="0") == tot0
    got = client.pull("users", ids)      # primary loaded -> replica
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))
    assert counter.value(shard="0") > tot0


def test_stale_replica_rejected_primary_serves():
    view, tr, stores, client = make_read_tier(
        num_shards=1, owners=(0, 1), replicas_per_shard=1,
        read_replicas=True, staleness=1)
    ids = np.arange(32)
    client.pull("users", ids)
    # advance the primary WITHOUT syncing the replica: the client's
    # own push acks tell it the owner moved on, so a lagging replica
    # answer must be discarded and the primary re-serve
    for _ in range(3):
        client.push("users", ids, np.ones((32, 8), np.float32),
                    scale=0.25)
    rejects0 = tier._REPLICA_STALE.value()
    # load the primary's rolling read count so routing picks the replica
    with client._lock:
        client._target_loads[view.owner_of(0)] = 10_000
    got = client.pull("users", ids)
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))
    assert tier._REPLICA_STALE.value() > rejects0
    # once the replica catches up by delta sync, it serves again
    stores[1].sync_replica_from(tr, 0, "users", 0)
    reads0 = tier._REPLICA_READS.value(shard="0")
    got = client.pull("users", ids)
    np.testing.assert_allclose(got, oracle_pull(tr, view, ids))
    assert tier._REPLICA_READS.value(shard="0") > reads0


def test_replica_promoted_on_owner_death_bit_exact():
    """The ISSUE acceptance: kill the primary after a delta sync; the
    replica holder — preferred by the re-plan — promotes its copy and
    serves BIT-EXACT rows, seq fence included."""
    from elasticdl_tpu.master.journal import replay_lines

    owner = sharding.ShardMapOwner(4, replica_count=1)
    owner.register_table(SPEC)
    view = owner.bootstrap([0, 1])
    tr = LocalTransport()
    stores = {}
    for o in (0, 1):
        st = EmbeddingShardStore(o, device=False)
        st.attach(view)
        tr.register(st)
        stores[o] = st
    for s in range(4):
        for rep in view.replicas_of(s):
            stores[rep].sync_replica_from(tr, view.owner_of(s), "users", s)
    client = tier.EmbeddingTierClient(
        owner.view, tr, client_id="promo", retry_backoff_s=0.001)
    ids = np.arange(0, 128, 3)
    client.push("users", ids, np.full((ids.size, 8), 0.3, np.float32),
                scale=-1.0)
    # keep replicas synced to the last push, then kill worker 0
    for s in range(4):
        for rep in view.replicas_of(s):
            stores[rep].sync_replica_from(tr, view.owner_of(s), "users", s)
    victim = 0
    victim_shards = view.shards_owned_by(victim)
    expect = {
        s: stores[victim].extract_shard("users", s)["rows"]
        for s in victim_shards
    }
    tr.deregister(victim)
    new_view, moves = owner.begin_resharding([1], dead=[victim])
    # promotion preference: every stranded shard lands on the surviving
    # replica holder
    assert all(m.dst == 1 for m in moves)
    for s in victim_shards:
        assert new_view.owner_of(s) == 1
        wm = stores[1].promote_replica("users", s)
        assert wm == 1
    owner.confirm_moves(new_view.version, [m.shard for m in moves])
    for s in victim_shards:
        np.testing.assert_array_equal(
            stores[1].extract_shard("users", s)["rows"], expect[s])
    # a pre-kill push re-sent across the promotion still dedupes (the
    # seq fence traveled with the replica copy)
    stores[1].adopt_version(owner.view().version)
    assert stores[1].push(
        "users", victim_shards[0],
        np.array([0], np.int32), np.ones((1, 8), np.float32),
        client_id=client.client_id, seq=1) is False


def test_runtime_promotes_replica_and_installs_assignments(tmp_path):
    """WorkerTierRuntime half of promotion: on_world_change prefers the
    freshest copy (replica vs drained checkpoint by watermark) and
    adopts new replica assignments."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench as bench_mod
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.proto.service import MasterStub, make_channel

    m = bench_mod._et_master(str(tmp_path), 4, replicas=1)
    try:
        m["owner"].register_table(SPEC)
        channel = make_channel(f"localhost:{m['port']}")
        stub = MasterStub(channel)
        wids = [
            stub.RegisterWorker(
                pb.RegisterWorkerRequest(worker_name=f"rp-{i}")).worker_id
            for i in range(2)
        ]
        shared = LocalTransport()
        runtimes = {
            w: tier.WorkerTierRuntime(
                stub, w, checkpoint_dir=str(tmp_path), transport=shared,
                read_replicas=True)
            for w in wids
        }
        view = runtimes[wids[0]].client.view
        # replica assignments came over the WIRE (flat stride fields)
        assert any(view.replicas_of(s) for s in range(4))
        for rt in runtimes.values():
            # the first runtime's install ran before the second store
            # existed — the sync round picks up the deferred install
            rt.sync_replicas()
            assert set(rt.store.resident_replicas()) == {
                ("users", s) for s in view.shards_replicated_on(rt.worker_id)
            }
        client = runtimes[wids[0]].client
        ids = np.arange(64)
        client.push("users", ids, np.full((64, 8), 0.2, np.float32),
                    scale=-1.0)
        sync_count = runtimes[wids[1]].sync_replicas()
        assert sync_count >= len(runtimes[wids[1]].store.resident_replicas())
        victim = wids[0]
        survivor = wids[1]
        expect = bench_mod._et_full_table(SPEC, view, shared)
        runtimes[victim].drain()
        shared.deregister(victim)
        m["membership"].mark_dead(victim, reason="test kill")
        promoted = runtimes[survivor].on_world_change()
        assert promoted >= 1
        final = m["owner"].view()
        assert all(final.owner_of(s) == survivor for s in range(4))
        np.testing.assert_array_equal(
            bench_mod._et_full_table(SPEC, final, shared), expect)
    finally:
        m["server"].stop(None)
        if m["journal"]._fh is not None:
            m["journal"].close()


# ------------------------------------------------------------------ #
# journal: the replica map replays identically


def test_journal_replays_replica_map_and_rollback(tmp_path):
    from elasticdl_tpu.master.journal import (
        ControlPlaneJournal,
        replay_lines,
    )

    j = ControlPlaneJournal(str(tmp_path))
    owner = sharding.ShardMapOwner(4, journal=j, replica_count=1)
    owner.register_table(SPEC)
    owner.bootstrap([0, 1])
    v1 = owner.view()
    assert any(v1.replicas_of(s) for s in range(4))
    j.close()
    with open(j.path) as f:
        replay = replay_lines(f.readlines())
    assert replay.embedding is not None
    assert [list(r) for r in v1.replicas] == replay.embedding.replicas
    # begin WITHOUT commit: the pending replica map rolls back with the
    # owners (the successor re-plans; clients requeue)
    j2 = ControlPlaneJournal(str(tmp_path))
    owner2 = sharding.ShardMapOwner(4, journal=j2, replica_count=1)
    owner2.restore_from_replay(j2.embedding_snapshot())
    assert [list(r) for r in owner2.view().replicas] \
        == replay.embedding.replicas
    owner2.begin_resharding([1], dead=[0])
    j2.close()
    with open(j2.path) as f:
        replay2 = replay_lines(f.readlines())
    assert replay2.embedding.reshard_interrupted is True
    assert replay2.embedding.replicas == replay.embedding.replicas
    assert replay2.embedding.owners == [int(o) for o in v1.owners]


# ------------------------------------------------------------------ #
# pull pipeline


def test_pipeline_orders_overlaps_and_drains():
    view, tr, stores, client = make_read_tier(num_shards=2, owners=(0, 1))
    pipe = tier.EmbeddingPullPipeline(client, "users", depth=2)
    a, b = np.arange(16), np.arange(16, 48)
    pipe.submit(a)
    pipe.submit(b)
    rows_a, inv_a, _ = pipe.get()
    rows_b, inv_b, _ = pipe.get()
    np.testing.assert_allclose(
        rows_a[inv_a.reshape(-1)], oracle_pull(tr, view, a))
    np.testing.assert_allclose(
        rows_b[inv_b.reshape(-1)], oracle_pull(tr, view, b))
    with pytest.raises(RuntimeError, match="empty"):
        pipe.get()
    pipe.submit(a)
    pipe.submit(b)
    with pytest.raises(RuntimeError, match="depth"):
        pipe.submit(a)
    drained = pipe.drain()
    assert [d.tolist() for d in drained] == [a.tolist(), b.tolist()]
    pipe.submit(a)                      # resubmission after drain works
    rows, inv, _ = pipe.get()
    np.testing.assert_allclose(
        rows[inv.reshape(-1)], oracle_pull(tr, view, a))
    pipe.close()
    with pytest.raises(RuntimeError, match="closed"):
        pipe.submit(a)


def test_pipeline_reissues_when_map_changed_between_pull_and_get():
    """A completed background pull from an ABANDONED map version is
    never served: get() re-pulls under the fresh map."""
    views = {}
    assignment = sharding.assign_round_robin(2, [0, 1])
    v1 = sharding.ShardMapView(
        version=1, num_shards=2, owners=tuple(assignment), tables=(SPEC,))
    tr = LocalTransport()
    for o in (0, 1):
        st = EmbeddingShardStore(o, device=False)
        st.attach(v1)
        tr.register(st)
    views["v"] = v1
    client = tier.EmbeddingTierClient(
        lambda: views["v"], tr, client_id="pr", retry_backoff_s=0.001)
    pipe = tier.EmbeddingPullPipeline(client, "users", depth=1)
    ids = np.arange(24)
    pipe.submit(ids)
    _ = pipe._q[0][1].result()          # background pull completed at v1
    views["v"] = sharding.ShardMapView(
        version=2, num_shards=2, owners=tuple(assignment), tables=(SPEC,))
    for o in (0, 1):
        tr.store_of(o).adopt_version(2)
    client.refresh()
    rows, inv, _ = pipe.get()           # re-issued under v2
    np.testing.assert_allclose(
        rows[inv.reshape(-1)], oracle_pull(tr, views["v"], ids))
    pipe.close()


def test_session_run_pipelined_matches_blocking_steps():
    """EmbeddingTierSession.run with a pipeline produces the same
    losses and the same final table as the blocking step-by-step path.
    Batches use DISJOINT id ranges: a pipelined pull is by design up to
    `pipeline_depth` pushes stale (the convergence tradeoff
    docs/performance.md documents), so only non-overlapping batches are
    bitwise-comparable across the two schedules."""
    batches = [{"cat": np.arange(i * 64, i * 64 + 32)} for i in range(6)]

    def loss_fn(vectors, inverses, batch):
        import jax.numpy as jnp

        emb = vectors["users"][inverses["users"]]
        return jnp.mean(emb * emb)

    def run(depth):
        view, tr, stores, client = make_read_tier(
            num_shards=2, owners=(0, 1), client_id=f"sess{depth}")
        sess = tier.EmbeddingTierSession(
            client, {"users": "cat"}, pipeline_depth=depth)
        losses = [loss for loss, _ in sess.run(loss_fn, batches, lr=0.1)]
        sess.close()
        table = np.zeros((SPEC.vocab, SPEC.dim), np.float32)
        for s in range(view.num_shards):
            rows = tr.store_of(view.owners[s]).extract_shard(
                "users", s)["rows"]
            idx = np.arange(s, SPEC.vocab, view.num_shards)
            table[idx] = rows[: len(idx)]
        return losses, table

    losses_blocking, table_blocking = run(0)
    losses_piped, table_piped = run(2)
    np.testing.assert_allclose(losses_blocking, losses_piped, rtol=1e-6)
    np.testing.assert_allclose(table_blocking, table_piped, atol=1e-6)


# ------------------------------------------------------------------ #
# tier_stats latency split (the ISSUE 13 bugfix)


def test_tier_stats_splits_owner_pull_from_effective_read():
    view, tr, stores, client = make_read_tier(
        num_shards=2, owners=(0, 1), cache_rows=512, staleness=4)
    ids = np.arange(64)
    client.pull("users", ids)           # cold: owner round recorded
    owner_rounds = len(client._pull_times)
    for _ in range(3):                  # warm: cache-served, NO owner RPC
        client.pull("users", ids)
    stats = client.tier_stats()
    assert "emb_pull_p99_ms" in stats and "emb_read_p99_ms" in stats
    # cache-served pulls must not add owner-RPC samples (the alert's
    # series is undiluted) but DO land in the effective-read window
    assert len(client._pull_times) == owner_rounds
    assert len(client._read_times) == 4
    assert stats["emb_cache_hit_rate"] > 0
    # a pipeline advertises its lookahead through the same payload
    pipe = tier.EmbeddingPullPipeline(client, "users", depth=3)
    assert client.tier_stats()["emb_pipeline_depth"] == 3.0
    pipe.close()
    assert "emb_pipeline_depth" not in client.tier_stats()


def test_fleet_series_carries_cache_hit_rate_min():
    from elasticdl_tpu.observability.timeseries import fleet_series

    now = 100.0
    records = [
        {"updated_at": now, "emb_cache_hit_rate": 0.9,
         "emb_read_p99_ms": 2.0},
        {"updated_at": now, "emb_cache_hit_rate": 0.1,
         "emb_read_p99_ms": 9.0},
    ]
    out = fleet_series(records, now=now)
    # worst reporter: MIN for hit rate (collapse sensor), MAX for p99
    assert out["edl_fleet_emb_cache_hit_rate"] == 0.1
    assert out["edl_fleet_emb_read_p99_ms"] == 9.0
    # absent when nobody runs a cache — the alert rule sees no-data
    out2 = fleet_series([{"updated_at": now}], now=now)
    assert "edl_fleet_emb_cache_hit_rate" not in out2


def test_config_read_path_flags_validate():
    from elasticdl_tpu.common.config import JobConfig

    MD = "mnist.mnist_cnn.custom_model"
    cfg = JobConfig(model_def=MD, embedding_shards=4,
                    embedding_cache_rows=1024,
                    embedding_cache_staleness=4,
                    embedding_read_replicas=1,
                    embedding_pull_pipeline=2)
    cfg.validate()
    with pytest.raises(ValueError, match="cache_rows"):
        JobConfig(model_def=MD, embedding_cache_rows=-1).validate()
    with pytest.raises(ValueError, match="staleness"):
        JobConfig(model_def=MD, embedding_shards=4,
                  embedding_cache_staleness=-1).validate()
    with pytest.raises(ValueError, match="requires the tier"):
        JobConfig(model_def=MD, embedding_read_replicas=1).validate()
    with pytest.raises(ValueError, match="pull_pipeline"):
        JobConfig(model_def=MD, embedding_shards=2,
                  embedding_pull_pipeline=-1).validate()
    with pytest.raises(ValueError, match="capacity_rows"):
        HotRowCache(0)
    # the cache requires the deduping client (write-through and the
    # slot store assume sorted-unique streams)
    view, tr, _stores, _c = make_read_tier()
    with pytest.raises(ValueError, match="dedupe"):
        tier.EmbeddingTierClient(
            lambda: view, tr, client_id="nd", dedupe=False, cache_rows=16)


def test_cache_lru_eviction_at_capacity():
    cache = HotRowCache(capacity_rows=8, staleness_bound=4)
    wm = np.zeros(1, np.int64)
    ids1 = np.arange(8)
    cache.insert("t", 64, 4, ids1, np.ones((8, 4), np.float32),
                 np.zeros(8, np.int64))
    cache.lookup("t", 64, 4, ids1[:4], wm, 1)      # touch 0-3
    ids2 = np.arange(8, 12)
    cache.insert("t", 64, 4, ids2, np.ones((4, 4), np.float32),
                 np.zeros(4, np.int64))
    hit, _ = cache.lookup("t", 64, 4, ids1[:4], wm, 1)
    assert hit.all()                    # recently-touched survived
    hit2, _ = cache.lookup("t", 64, 4, ids2, wm, 1)
    assert hit2.all()                   # new entries resident
    assert cache.stats()["resident_rows"] == 8
