"""Rescale fast path, part 1: the executable cache + speculative compiler.

BENCH_r05 measured `compile_and_first_group_s = 88.78s` against
`seconds_to_auc = 30.98s` — compilation is ~3x the useful work, and because
elasticity is re-formation (parallel/elastic.py: "XLA's world is static per
initialize()"), every membership change pays that bill again. This module
makes the recompile avoidable at three layers:

1. `CompileCache`: a process-global, thread-safe store of jitted callables
   and AOT-compiled executables, keyed by (program token, program kind,
   mesh fingerprint, trainer knobs). The token identifies the PROGRAM the
   job's config lowers to — deliberately world-version-independent, so a
   Trainer rebuilt after a re-formation (same job, same mesh shape) gets
   the previous generation's callable back instead of re-tracing. Counters
   (hits/misses/speculative) feed the bench's `recompile_hit_rate`.

2. The persistent on-disk XLA cache (common/runtime.configure_jax_runtime,
   `--compilation_cache_dir` / `EDL_COMPILATION_CACHE_DIR`): covers the
   case the in-memory cache cannot — a re-formed PROCESS. The relaunched
   generation re-traces but deserializes executables instead of compiling.

3. `SpeculativeCompiler`: once a job reaches steady state, a background
   thread precompiles the step programs for the NEIGHBOR world sizes
   (N-1, N+1, plus any size announced through the master's pending-
   membership signal file — common/membership_signal.py), so when the
   resize actually lands the executable is already in both caches and
   recovery is bounded by state movement, not XLA.

Keying note: the default token is unique per Trainer instance (safe: no
cross-trainer sharing for ad-hoc trainers whose loss/optimizer closures
cannot be fingerprinted). Job entrypoints pass `job_cache_token(cfg)` —
derived from the config that fully determines the program — which is what
makes pre/post-resize trainers, and the speculative compiler's throwaway
neighbor trainers, share entries.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from elasticdl_tpu.common import membership_signal
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_instance_tokens = itertools.count()

#: default LRU capacity; an evicted entry just recompiles on next use
DEFAULT_MAX_ENTRIES = 128


def job_cache_token(cfg) -> str:
    """Program-identity token from a JobConfig: every field that changes
    the traced program is included; nothing world/membership-scoped is.
    Two processes (or two generations) with the same job config produce
    the same token — that is the whole point."""
    return "|".join(
        str(part)
        for part in (
            cfg.model_zoo,
            cfg.model_def,
            sorted(cfg.model_params.items()),
            cfg.loss,
            cfg.optimizer,
            cfg.eval_metrics_fn,
            cfg.param_dtype,
            cfg.compute_dtype,
        )
    )


def instance_token() -> str:
    """Fallback token for trainers built outside a job config: unique per
    call, so entries are private to that trainer (identical semantics to
    the pre-cache lazy build — no false sharing between ad-hoc specs)."""
    return f"~instance-{next(_instance_tokens)}"


def mesh_fingerprint(mesh) -> Tuple:
    """World-version-independent mesh identity: axis layout plus the flat
    device ids. Two Mesh objects over the same devices in the same layout
    fingerprint equal (same-size re-formation reuses executables); a
    resized mesh differs (no stale-shape reuse)."""
    return (
        tuple(str(a) for a in mesh.axis_names),
        tuple(int(s) for s in mesh.devices.shape),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def aval_signature(tree: Any) -> Tuple:
    """Hashable (shape, dtype) signature of a pytree's array leaves —
    identifies the XLA program a (state, batch) pair lowers to."""
    import jax

    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape")
    )


class CompileCache:
    """Thread-safe LRU of compiled program artifacts.

    Two entry classes share the store:
    - jitted callables (`get_or_build`): counted — a hit here is a resize
      that did NOT re-trace; `stats()["hit_rate"]` is the bench's
      `recompile_hit_rate`.
    - AOT executables (`store_aot` / `peek`): uncounted lookups (they sit
      in front of a callable that was already counted once), tallied only
      as `speculative_compiles` when marked so.
    """

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Any]" = OrderedDict()  # guarded_by: _lock
        self._hits = 0          # guarded_by: _lock
        self._misses = 0        # guarded_by: _lock
        self._speculative = 0   # guarded_by: _lock
        # bumped on every store_aot: dispatchers pin a negative AOT lookup
        # and re-check only when this moves (zero per-step tree walks in
        # the no-AOT common case) — see Trainer._dispatch
        self._aot_generation = 0  # guarded_by: _lock

    # ------------------------------------------------------------------ #

    def get_or_build(
        self, key: Tuple, build: Callable[[], Any], *, speculative: bool = False
    ) -> Any:
        """Return the cached value for `key`, building (OUTSIDE the lock —
        builds are multi-second compiles) on a miss. A lost build race keeps
        the first value. `speculative=True` marks a background precompile:
        a resulting insert counts as speculative, not as a (real) miss."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                if not speculative:
                    self._hits += 1
                return self._entries[key]
        value = build()
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return self._entries[key]
            self._entries[key] = value
            if speculative:
                self._speculative += 1
            else:
                self._misses += 1
            while len(self._entries) > self.max_entries:
                evicted, _ = self._entries.popitem(last=False)
                logger.info("compile cache evicted %r (LRU)", evicted[:2])
            return value

    def peek(self, key: Tuple) -> Optional[Any]:
        """Uncounted lookup (AOT executables in front of a counted
        callable); refreshes LRU position on a find."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return self._entries[key]
            return None

    def store_aot(self, key: Tuple, value: Any, *, speculative: bool = False) -> Any:
        """Insert an AOT-compiled executable; first writer wins."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return self._entries[key]
            self._entries[key] = value
            self._aot_generation += 1
            if speculative:
                self._speculative += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
            return value

    @property
    def aot_generation(self) -> int:
        with self._lock:
            return self._aot_generation

    def contains(self, key: Tuple) -> bool:
        with self._lock:
            return key in self._entries

    # ------------------------------------------------------------------ #

    def stats(self) -> Dict[str, float]:
        with self._lock:
            lookups = self._hits + self._misses
            return {
                "hits": self._hits,
                "misses": self._misses,
                "speculative_compiles": self._speculative,
                "entries": len(self._entries),
                "hit_rate": (self._hits / lookups) if lookups else 0.0,
            }

    def reset_stats(self) -> None:
        with self._lock:
            self._hits = self._misses = self._speculative = 0

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._hits = self._misses = self._speculative = 0


_GLOBAL_CACHE = CompileCache()


def global_cache() -> CompileCache:
    """The process-wide cache every job-entrypoint Trainer shares."""
    return _GLOBAL_CACHE


# Scrape surface for the PROCESS-GLOBAL cache (the one job entrypoints and
# the speculative compiler share); ad-hoc trainers' private caches are
# deliberately not aggregated — their stats describe nothing cross-resize.
_reg = default_registry()
for _stat, _help in (
    ("hits", "executable-cache hits (a resize that did NOT re-trace)"),
    ("misses", "executable-cache misses (real re-traces)"),
    ("speculative_compiles", "background neighbor-size precompiles"),
    ("entries", "live cache entries"),
    ("hit_rate", "hits / (hits + misses) — the bench's recompile_hit_rate"),
):
    _reg.gauge(
        f"edl_compile_cache_{_stat}", _help
    ).set_fn(lambda s=_stat: _GLOBAL_CACHE.stats()[s])


# ---------------------------------------------------------------------- #
# speculative neighbor-world compilation


class SpeculativeCompiler:
    """Background precompilation of the step programs for neighbor world
    sizes, so a resize lands on a warm cache.

    `compile_for_size(size)` does the actual work — the caller supplies it
    (typically: build a throwaway Trainer on the neighbor-size mesh against
    the SHARED CompileCache/token and AOT-compile its steps). It may raise
    `SkipSize` for sizes this process cannot represent (e.g. scale-up
    beyond the visible devices: on real multi-host TPU the devices of a
    larger world do not exist yet, and the persistent on-disk cache is the
    warmth mechanism there instead). Failures are logged, never raised into
    the training thread; a size is compiled at most once until the
    candidate set changes.

    Candidates: current±1 plus `extra_sizes` plus whatever the master's
    pending-membership signal file currently announces. The announced size
    is compiled FIRST — it is the one that is actually about to happen.
    """

    def __init__(
        self,
        compile_for_size: Callable[[int], Any],
        current_size: int,
        *,
        min_size: int = 1,
        max_size: Optional[int] = None,
        signal_path: str = "",
        extra_sizes: Sequence[int] = (),
        poll_s: float = 2.0,
    ):
        self._compile_for_size = compile_for_size
        self.current_size = int(current_size)
        self.min_size = int(min_size)
        self.max_size = max_size
        self.signal_path = signal_path
        self.extra_sizes = tuple(int(s) for s in extra_sizes)
        self.poll_s = poll_s
        self._done: set = set()        # guarded_by: _lock
        self._failed: set = set()      # guarded_by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    class SkipSize(Exception):
        """compile_for_size: this size is not representable here (not an
        error — e.g. scale-up past the visible device set)."""

    def candidate_sizes(self) -> List[int]:
        # ONE implementation of the candidate policy (announced size
        # first, then nearest neighbors): parallel/elastic.py owns it;
        # imported lazily so this module stays importable without jax
        from elasticdl_tpu.parallel.elastic import neighbor_world_sizes

        pending = membership_signal.pending_size(self.signal_path or None)
        sizes = set(
            neighbor_world_sizes(
                self.current_size, pending=pending,
                min_size=self.min_size, max_size=self.max_size,
            )
        )
        sizes.update(
            s for s in self.extra_sizes
            if s >= self.min_size
            and (self.max_size is None or s <= self.max_size)
            and s != self.current_size
        )
        return sorted(
            sizes, key=lambda s: (s != pending, abs(s - self.current_size), s)
        )

    def precompile_once(self) -> List[int]:
        """One pass over the current candidates; returns sizes compiled
        this pass. Synchronous — tests and the bench call this directly;
        `start()` loops it on a daemon thread."""
        compiled = []
        for size in self.candidate_sizes():
            with self._lock:
                if size in self._done or size in self._failed:
                    continue
            if self._stop.is_set():
                break
            try:
                with tracing.span(
                    "compile.speculative", size=size,
                    current_size=self.current_size,
                ) as sp:
                    try:
                        self._compile_for_size(size)
                    except SpeculativeCompiler.SkipSize:
                        sp.set(outcome="skipped")
                        raise
                    sp.set(outcome="compiled")
            except SpeculativeCompiler.SkipSize as e:
                logger.info("speculative compile skipped size %d: %s", size, e)
                with self._lock:
                    self._failed.add(size)
            except Exception:
                logger.exception("speculative compile failed for size %d", size)
                with self._lock:
                    self._failed.add(size)
            else:
                logger.info("speculative compile ready for world size %d", size)
                with self._lock:
                    self._done.add(size)
                compiled.append(size)
        return compiled

    def notify_resize(self, new_size: int) -> None:
        """The world actually resized: neighbors move with it (previously
        failed sizes may become representable, so both sets reset)."""
        with self._lock:
            self.current_size = int(new_size)
            self._done.clear()
            self._failed.clear()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="edl-speculative-compile", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.precompile_once()
            except Exception:
                logger.exception("speculative compile pass failed")
            self._stop.wait(self.poll_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
