"""Streaming evaluation metrics, jit-friendly.

Reference parity: the reference aggregates evaluation by shipping model outputs
and labels (or Keras metric states) from workers to the master, which merges
them into job metrics (reference: elasticdl/python/master/evaluation_service.py).

Rebuilt: each metric is a pure (init, update, result) triple over a small
fixed-shape state array, so `update` runs *inside* the jitted eval step, states
sum across batches on the worker, and the master merges per-worker states by
plain addition — no raw outputs/labels ever leave the device. All built-in
metric states are additive, which is what makes cross-worker merge = sum.

`mask` is a (N,) 0/1 weight vector marking real vs padded rows (the framework
pads the last partial batch to keep XLA shapes static).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np


def _as_mask(mask, n) -> jnp.ndarray:
    if mask is None:
        return jnp.ones((n,), jnp.float32)
    return jnp.asarray(mask, jnp.float32).reshape(-1)


class Metric:
    """Base streaming metric. State is a flat float32 vector, additive across
    batches and across workers."""

    name = "metric"

    def init_state(self) -> np.ndarray:
        raise NotImplementedError

    def update(
        self,
        state: jnp.ndarray,
        labels: jnp.ndarray,
        outputs: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        """Return state + this batch's contribution. Runs under jit."""
        raise NotImplementedError

    def result(self, state: np.ndarray) -> float:
        raise NotImplementedError


class Mean(Metric):
    """Weighted mean of a per-example value function (default: the output)."""

    name = "mean"

    def __init__(self, fn: Optional[Callable] = None):
        self._fn = fn

    def init_state(self) -> np.ndarray:
        return np.zeros((2,), np.float32)  # [sum, count]

    def update(self, state, labels, outputs, mask=None):
        v = self._fn(labels, outputs) if self._fn else outputs
        v = jnp.asarray(v, jnp.float32).reshape(-1)
        m = _as_mask(mask, v.shape[0])
        return state + jnp.stack([jnp.sum(v * m), jnp.sum(m)])

    def result(self, state) -> float:
        return float(state[0] / max(float(state[1]), 1.0))


class Accuracy(Metric):
    """Classification accuracy. Outputs: logits (N, C), or binary scores (N,).

    `from_logits` (default True, matching AUC) thresholds 1-D binary outputs
    at 0.0 (logit space); set False for probabilities (threshold 0.5).
    """

    name = "accuracy"

    def __init__(self, from_logits: bool = True):
        self.from_logits = from_logits

    def init_state(self) -> np.ndarray:
        return np.zeros((2,), np.float32)  # [correct, count]

    def update(self, state, labels, outputs, mask=None):
        labels = jnp.asarray(labels).reshape(-1)
        outputs = jnp.asarray(outputs)
        if outputs.ndim > 1 and outputs.shape[-1] > 1:
            pred = jnp.argmax(outputs, axis=-1).reshape(-1)
        else:
            threshold = 0.0 if self.from_logits else 0.5
            pred = (outputs.reshape(-1) > threshold).astype(labels.dtype)
        m = _as_mask(mask, labels.shape[0])
        correct = jnp.sum((pred == labels).astype(jnp.float32) * m)
        return state + jnp.stack([correct, jnp.sum(m)])

    def result(self, state) -> float:
        return float(state[0] / max(float(state[1]), 1.0))


class AUC(Metric):
    """Streaming binary AUC via fixed-threshold confusion-matrix bins.

    Same approach as tf.keras.metrics.AUC (which the reference's model zoo uses
    for DeepFM/Census): bucket scores at `num_thresholds` thresholds,
    accumulate (tp, fp, tn, fn) per threshold, integrate ROC by trapezoid at
    result time. State: (4 * num_thresholds,), additive across workers.
    """

    name = "auc"

    def __init__(self, num_thresholds: int = 200, from_logits: bool = True):
        self.num_thresholds = num_thresholds
        self.from_logits = from_logits

    def init_state(self) -> np.ndarray:
        return np.zeros((4 * self.num_thresholds,), np.float32)

    def update(self, state, labels, outputs, mask=None):
        scores = jnp.asarray(outputs, jnp.float32).reshape(-1)
        if self.from_logits:
            scores = 1.0 / (1.0 + jnp.exp(-scores))
        labels = jnp.asarray(labels, jnp.float32).reshape(-1)
        m = _as_mask(mask, labels.shape[0])
        t = jnp.linspace(0.0, 1.0, self.num_thresholds)
        pred_pos = (scores[None, :] >= t[:, None]).astype(jnp.float32)   # (T, N)
        lab_pos = (labels[None, :] > 0.5).astype(jnp.float32)            # (1, N)
        w = m[None, :]
        tp = jnp.sum(pred_pos * lab_pos * w, axis=1)
        fp = jnp.sum(pred_pos * (1 - lab_pos) * w, axis=1)
        fn = jnp.sum((1 - pred_pos) * lab_pos * w, axis=1)
        tn = jnp.sum((1 - pred_pos) * (1 - lab_pos) * w, axis=1)
        return state + jnp.concatenate([tp, fp, tn, fn])

    def result(self, state) -> float:
        s = np.asarray(state, np.float64).reshape(4, self.num_thresholds)
        tp, fp, tn, fn = s
        tpr = tp / np.maximum(tp + fn, 1e-9)
        fpr = fp / np.maximum(fp + tn, 1e-9)
        # thresholds ascend => fpr/tpr descend; integrate |trapezoid|
        trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2 compat
        return float(abs(trapezoid(tpr, fpr)))


class PrecisionRecall(Metric):
    """Streaming binary precision/recall/F1 at one decision threshold.
    State: [tp, fp, fn], additive across batches and workers. `result`
    returns F1 by default; `kind` selects 'precision'/'recall'/'f1' so one
    class serves all three (register it three times under different names,
    e.g. {"precision": PrecisionRecall("precision"), ...})."""

    def __init__(self, kind: str = "f1", threshold: float = 0.5,
                 from_logits: bool = True):
        if kind not in ("precision", "recall", "f1"):
            raise ValueError(f"unknown kind {kind!r}")
        self.kind = kind
        self.name = kind
        self.threshold = threshold
        self.from_logits = from_logits

    def init_state(self) -> np.ndarray:
        return np.zeros((3,), np.float32)  # [tp, fp, fn]

    def update(self, state, labels, outputs, mask=None):
        scores = jnp.asarray(outputs, jnp.float32).reshape(-1)
        if self.from_logits:
            scores = 1.0 / (1.0 + jnp.exp(-scores))
        labels = jnp.asarray(labels, jnp.float32).reshape(-1)
        m = _as_mask(mask, labels.shape[0])
        pred = (scores >= self.threshold).astype(jnp.float32)
        lab = (labels > 0.5).astype(jnp.float32)
        tp = jnp.sum(pred * lab * m)
        fp = jnp.sum(pred * (1 - lab) * m)
        fn = jnp.sum((1 - pred) * lab * m)
        return state + jnp.stack([tp, fp, fn])

    def result(self, state) -> float:
        tp, fp, fn = (float(x) for x in np.asarray(state, np.float64))
        precision = tp / max(tp + fp, 1e-9)
        recall = tp / max(tp + fn, 1e-9)
        if self.kind == "precision":
            return precision
        if self.kind == "recall":
            return recall
        return 2 * precision * recall / max(precision + recall, 1e-9)


def init_states(metrics: Dict[str, Metric]) -> Dict[str, np.ndarray]:
    return {k: m.init_state() for k, m in metrics.items()}


def merge_states(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Cross-batch / cross-worker merge: plain elementwise sum."""
    return {k: np.asarray(a[k]) + np.asarray(b[k]) for k in a}


def results(metrics: Dict[str, Metric], states: Dict[str, Any]) -> Dict[str, float]:
    return {k: metrics[k].result(np.asarray(states[k])) for k in metrics}
