"""The jitted training engine.

This replaces the reference's entire hot path — TF2-eager forward/backward on
the worker plus server-side optimizer application on the parameter server
(reference: elasticdl/python/worker/worker.py `training_process_eagerly`,
elasticdl/pkg/ps/optimizer.go) — with ONE `jax.jit`-compiled XLA program:
forward, loss, backward, `optax` update, all fused on-device.

Parallelism comes from the mesh, not from RPCs:
- the batch is sharded over the `data` axis, so the mean-loss gradient is a
  `psum` XLA inserts over ICI (this *is* the reference's allreduce mode),
- params carry flax partitioning metadata; anything unannotated is replicated,
  annotated tensors (embedding tables) are sharded — this *is* the reference's
  parameter-server placement, minus the per-step gRPC round-trips.

Model state is donated each step, so params update in place in HBM.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)
import numpy as np
import optax
from flax import struct
from jax.sharding import Mesh

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.parallel import mesh as mesh_lib
from elasticdl_tpu.training import compile_cache as cc
from elasticdl_tpu.training.model_spec import ModelSpec
from elasticdl_tpu.training import metrics as metrics_lib

logger = default_logger(__name__)


class TrainState(struct.PyTreeNode):
    """Functional training state: a pytree living (sharded) in device HBM.

    The reference kept `step` as the PS "model version" used for staleness
    control (reference: elasticdl/pkg/ps/parameter.go); here there is no
    staleness — `step` is just the global step counter, and doubles as the
    model version reported to the master.
    """

    step: jnp.ndarray
    params: Any
    opt_state: Any
    extra_vars: Any          # non-param collections, e.g. batch_stats
    rng: jax.Array

    @property
    def model_version(self) -> int:
        return int(jax.device_get(self.step))


def _split_batch(batch: Dict[str, Any]):
    features = batch["features"]
    labels = batch.get("labels")
    mask = batch.get("mask")
    return features, labels, mask


def _masked_scalar_loss(loss_fn, labels, outputs, mask):
    """Apply the user loss; accept per-example vectors (masked mean) or
    scalars (used as-is)."""
    value = loss_fn(labels, outputs)
    value = jnp.asarray(value)
    if value.ndim == 0:
        return value
    value = value.reshape(-1).astype(jnp.float32)
    if mask is None:
        return jnp.mean(value)
    m = jnp.asarray(mask, jnp.float32).reshape(-1)
    return jnp.sum(value * m) / jnp.maximum(jnp.sum(m), 1.0)


def _aux_loss(new_vars, weight: float):
    """weight * sum of everything sown into the "losses" collection (e.g.
    api.layers.MoE's Switch load-balance penalty). Added INSIDE the
    differentiated loss so auxiliaries regularize training; 0-weight jobs
    pay nothing (static branch)."""
    if not weight:
        return jnp.float32(0.0)
    leaves = jax.tree_util.tree_leaves(new_vars.get("losses", {}))
    if not leaves:
        return jnp.float32(0.0)
    return jnp.float32(weight) * sum(
        jnp.sum(jnp.asarray(l, jnp.float32)) for l in leaves)


_warned_scalar_accum = False


def _warn_scalar_loss_with_accum() -> None:
    """ADVICE r4: a user loss returning a pre-reduced SCALAR under
    grad_accum weighs micro-batches equally, which diverges from the
    full-batch masked mean when padding is uneven across micro-batches.
    Every zoo loss is per-example so this never fires in-tree; warn once
    so a user scalar loss over masked data isn't silently different."""
    global _warned_scalar_accum
    if not _warned_scalar_accum:
        _warned_scalar_accum = True
        logger.warning(
            "grad_accum_steps > 1 with a loss that returns a pre-reduced "
            "scalar: micro-batches are weighed equally, which differs from "
            "the unaccumulated step when padding/mask density varies across "
            "micro-batches. Return a per-example loss vector for exact "
            "full-batch-equivalent gradients."
        )


def _accumulated_grads(forward, loss_fn, state, features, labels, mask,
                       step_rng, accum, aux_weight: float = 0.0):
    """Gradient accumulation: split the batch into `accum` micro-batches
    along the leading dim, `lax.scan` forward+backward over them holding
    ONE micro-batch of activations live at a time, and return grads exactly
    equal to the full-batch step's (so K is a pure HBM knob, not a
    semantics change).

    Exactness: per-example (vector) losses accumulate masked SUM and count,
    dividing once at the end — identical to the full batch's weighted mean
    even with padded rows concentrated in one micro-batch. A user loss that
    returns a SCALAR is assumed to be a mean over its micro-batch (true of
    every zoo loss); micro-batches then weigh equally. The exactness claim
    is scoped to aux_weight=0: sown auxiliary losses (MoE balance) are
    batch-DEPENDENT statistics, so per-micro aux (micro-sized capacity,
    per-micro frac/mean_prob) legitimately differs from the full-batch
    aux — the example-count weighting below is the accumulation-consistent
    choice, not an equality guarantee. BatchNorm-style
    extra_vars thread through the scan (last micro-batch wins, matching K
    sequential steps); dropout draws per-micro-batch folds of the step
    rng."""

    def to_micro(x):
        b = x.shape[0]
        if b % accum:
            raise ValueError(
                f"grad_accum={accum} must divide the batch size {b}")
        # STRIDED split (row j*K+k -> micro k, slot j), NOT a contiguous
        # reshape: the batch dim arrives sharded P('data') with each device
        # holding a contiguous row block, and a contiguous split would put
        # each micro-batch on only N/K devices — GSPMD then reshards the
        # whole batch (all-to-all) every step. The strided mapping keeps
        # every device's rows local in every micro-batch, and grads are
        # masked-sum/divide-once weighted so the grouping is semantically
        # irrelevant.
        return x.reshape((b // accum, accum) + x.shape[1:]).swapaxes(0, 1)

    # mask may be None: pytrees treat None as structure, so the 3-tuple
    # shape survives the scan with m arriving as None
    micro = jax.tree_util.tree_map(to_micro, (features, labels, mask))

    def body(carry, mb):
        g_acc, loss_acc, cnt_acc, vars_c, i = carry
        f, l, m = mb
        rng = jax.random.fold_in(step_rng, i)

        def sum_loss(params):
            variables = {"params": params, **vars_c}
            outputs, new_vars = forward(variables, f, rng)
            value = jnp.asarray(loss_fn(l, outputs))
            if value.ndim == 0:
                # pre-reduced scalar: weigh micro-batches equally (ndim is
                # static, so this warning fires once at trace time)
                _warn_scalar_loss_with_accum()
                return value + _aux_loss(new_vars, aux_weight), (
                    jnp.float32(1.0), new_vars)
            v = value.reshape(-1).astype(jnp.float32)
            mm = (jnp.asarray(m, jnp.float32).reshape(-1) if m is not None
                  else jnp.ones_like(v))
            cnt = jnp.sum(mm)
            # aux scaled by this micro-batch's example count so the final
            # divide-once yields the example-weighted mean of the PER-MICRO
            # aux (see the exactness scoping in the docstring: batch-
            # dependent aux statistics cannot equal the full-batch value)
            return jnp.sum(v * mm) + _aux_loss(new_vars, aux_weight) * cnt, (
                cnt, new_vars)

        (s, (cnt, new_vars)), g = jax.value_and_grad(
            sum_loss, has_aux=True)(state.params)
        g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
        return (g_acc, loss_acc + s, cnt_acc + cnt, new_vars, i + 1), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
    (g_sum, loss_sum, cnt, new_vars, _), _ = jax.lax.scan(
        body,
        (zeros, jnp.float32(0.0), jnp.float32(0.0), state.extra_vars,
         jnp.int32(0)),
        micro,
    )
    denom = jnp.maximum(cnt, 1.0)
    grads = jax.tree_util.tree_map(lambda g: g / denom, g_sum)
    return loss_sum / denom, new_vars, grads


# identifies the XLA program a (state, batch) pair lowers to; shared with
# the executable cache so AOT keys and cost-cache keys agree
_aval_signature = cc.aval_signature


def resolve_remat_policy(name: str):
    """Map a config-level policy name to a jax.checkpoint policy. "" (full
    remat: save nothing the policy engine controls) returns None. The menu
    is the standard HBM/FLOPs trade for long-context training on TPU:
    `dots` keeps MXU outputs and recomputes the (cheap, VPU) elementwise
    chain — the usual best trade; `dots_no_batch` additionally drops
    batch-dim matmul outputs (attention scores) — bigger savings, more
    recompute; `nothing` recomputes everything — minimum HBM."""
    if not name:
        return None
    policies = {
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "nothing": jax.checkpoint_policies.nothing_saveable,
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat policy {name!r}; choose from "
            f"{sorted(policies)} or '' for full remat"
        )
    return policies[name]


class Trainer:
    """Builds and runs the jitted train/eval/predict steps for one ModelSpec
    on one Mesh."""

    def __init__(
        self,
        spec: ModelSpec,
        mesh: Mesh,
        remat: bool = False,
        remat_policy: str = "",
        grad_accum: int = 1,
        seed: int = 0,
        cache_token: str = "",
        cache: "cc.CompileCache" = None,
    ):
        self.spec = spec
        self.mesh = mesh
        # Executable-cache identity (rescale fast path): job entrypoints
        # pass a config-derived token so pre/post-resize trainers (and the
        # speculative compiler's neighbor trainers) share programs through
        # the process-global cache. Ad-hoc trainers (no token) get a
        # PRIVATE cache instead: entries — and the compiled executables
        # plus closed-over models they pin — die with the trainer, exactly
        # the pre-cache lifetime (a global insert would pin every
        # short-lived trainer's programs until LRU pressure evicts them).
        self.cache_token = cache_token or cc.instance_token()
        if cache is not None:
            self._cache = cache
        elif cache_token:
            self._cache = cc.global_cache()
        else:
            self._cache = cc.CompileCache()
        # AOT executables pinned per kind: (aval signature, executable or
        # None, cache AOT generation); resolved lazily per call kind
        self._pinned_exe: Dict[str, Tuple[Any, Any, int]] = {}
        # a named policy implies remat on; "" + remat=True is full remat.
        # Resolved HERE so a bad name fails at construction, not at the
        # first train-step build after the job is already running.
        self.remat = remat or bool(remat_policy)
        self.remat_policy = remat_policy
        self._resolved_remat_policy = resolve_remat_policy(remat_policy)
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = int(grad_accum)
        self.seed = seed
        self.metrics: Dict[str, metrics_lib.Metric] = (
            dict(spec.eval_metrics_fn()) if spec.eval_metrics_fn else {}
        )
        self._train_step = None
        # AOT cost-analysis results keyed by the (state, batch) aval
        # signature — a second train_step_cost call with a different batch
        # shape is a different XLA program and must not reuse the first
        # result (round-5 advisor)
        self._cost_cache: Dict[Any, Dict[str, float]] = {}
        self._train_many = None
        self._eval_step = None
        self._eval_many = None
        self._predict_step = None
        self._predict_many = None

    # ------------------------------------------------------------------ #
    # Executable cache plumbing (rescale fast path)

    def _program_key(self, kind: str) -> Tuple:
        """Identity of one step PROGRAM: config-derived token + mesh
        fingerprint + every trainer knob that changes the trace. No world
        version, no process identity — which is exactly what makes a
        re-formed world at the same shape a cache HIT."""
        return (
            self.cache_token,
            kind,
            cc.mesh_fingerprint(self.mesh),
            self.remat,
            self.remat_policy,
            self.grad_accum,
            float(self.spec.aux_loss_weight or 0.0),
        )

    def _ensure(self, attr: str, kind: str, build,
                speculative: bool = False) -> Any:
        """Resolve the jitted callable for `kind` through the shared
        executable cache, pinning it on the instance (one counted cache
        lookup per trainer per kind — a post-resize trainer that finds the
        previous generation's callable is the `recompile_hit_rate` hit;
        speculative resolutions count as speculative, not misses)."""
        fn = getattr(self, attr)
        if fn is None:
            fn = self._cache.get_or_build(
                self._program_key(kind), build, speculative=speculative)
            setattr(self, attr, fn)
        return fn

    def compile_stats(self) -> Dict[str, float]:
        """Hit/miss/speculative counters of the shared executable cache."""
        return self._cache.stats()

    def _dispatch(self, kind: str, jitted, *args):
        """Prefer a cache-resident AOT executable for these exact avals
        (the speculative compiler's output); fall back to the jitted
        callable. The common case — no AOT entry exists for this kind —
        pays ZERO per-step overhead: once a negative lookup is pinned, the
        cache's AOT generation counter (bumped on every store_aot) is the
        only thing checked until a new executable could actually match.
        Known trade: an AOT entry stored for a shape OTHER than the first
        one dispatched, before any store bumps the generation again, can
        be shadowed by the negative pin — it then just runs the (correct)
        jitted path."""
        gen = self._cache.aot_generation
        pinned = self._pinned_exe.get(kind)
        if pinned is not None and pinned[2] == gen and pinned[1] is None:
            return jitted(*args)
        sig = cc.aval_signature(args)
        if pinned is None or pinned[0] != sig or pinned[2] != gen:
            exe = self._cache.peek(self._program_key(kind) + ("aot", sig))
            pinned = (sig, exe, gen)
            self._pinned_exe[kind] = pinned
        exe = pinned[1]
        if exe is not None:
            try:
                return exe(*args)
            except Exception:
                # input sharding/layout drifted from what the executable
                # was lowered with: drop to the jitted path (which
                # reshards) for good on this shape
                logger.warning(
                    "AOT executable for %s rejected its inputs; falling "
                    "back to the jitted path", kind, exc_info=True,
                )
                self._pinned_exe[kind] = (sig, None, gen)
        return jitted(*args)

    def _aot_compile(self, attr: str, kind: str, build, args,
                     speculative: bool = False):
        """`.lower().compile()` the program for these exact (sharded) args
        and park the executable in the shared cache — which also feeds the
        persistent on-disk XLA cache when one is configured. Idempotent per
        aval signature."""
        fn = self._ensure(attr, kind, build, speculative=speculative)
        key = self._program_key(kind) + ("aot", cc.aval_signature(args))
        exe = self._cache.peek(key)
        if exe is not None:
            return exe
        with jax.set_mesh(self.mesh):
            exe = fn.lower(*args).compile()
        return self._cache.store_aot(key, exe, speculative=speculative)

    def _aot_batch(self, batch, abstract: bool):
        """Concrete callers get the real sharded batch; abstract callers
        (speculative compiles for worlds this process cannot execute on)
        get the ShapeDtypeStruct mirror — identical avals and shardings,
        zero data movement."""
        if abstract:
            return mesh_lib.abstract_batch(
                self.mesh, batch, self.spec.batch_partition)
        return mesh_lib.shard_batch(self.mesh, batch, self.spec.batch_partition)

    def aot_compile_train_step(self, state, batch, speculative: bool = False,
                               abstract: bool = False):
        return self._aot_compile(
            "_train_step", "train_step", self._build_train_step,
            (state, self._aot_batch(batch, abstract)), speculative=speculative,
        )

    def aot_compile_eval_step(self, state, batch, speculative: bool = False,
                              abstract: bool = False):
        return self._aot_compile(
            "_eval_step", "eval_step", self._build_eval_step,
            (state, self._aot_batch(batch, abstract), self.new_metric_states()),
            speculative=speculative,
        )

    def aot_compile_predict_step(self, state, batch, speculative: bool = False,
                                 abstract: bool = False):
        return self._aot_compile(
            "_predict_step", "predict_step", self._build_predict_step,
            (state, self._aot_batch(batch, abstract)), speculative=speculative,
        )

    def aot_compile_train_many(self, state, stacked_batch,
                               speculative: bool = False):
        """AOT twin for the scan-of-steps program (callers on the grouped
        dispatch path — steps_per_dispatch > 1 — hand a stacked batch built
        with shard_batch_stack / make_global_batch_stack)."""
        return self._aot_compile(
            "_train_many", "train_many", self._build_train_many,
            (state, stacked_batch), speculative=speculative,
        )

    # ------------------------------------------------------------------ #
    # State creation

    def init_state(self, example_batch: Dict[str, Any]) -> TrainState:
        """Initialize sharded TrainState from an example batch.

        Params annotated with flax partitioning metadata (nn.with_partitioning,
        as used by the sharded Embedding layer) get their annotated
        NamedSharding; everything else is replicated. The whole init runs under
        jit so large sharded tables are initialized shard-wise on their own
        devices, never materialized on one host — the analog of the reference
        PS initializing embedding rows server-side
        (reference: elasticdl/pkg/ps/embedding.go lazy init).
        """
        model, tx = self.spec.model, self.spec.optimizer
        features, _, _ = _split_batch(example_batch)
        root_key = jax.random.PRNGKey(self.seed)

        def _variables(rng, feats):
            return model.init({"params": rng, "dropout": rng}, feats, training=False)

        def build_create():
            # Derive shardings from flax partitioning metadata. Optimizer
            # slots (Adam mu/nu, …) must shard exactly like their params —
            # the PS slot tables of the reference (elasticdl/pkg/ps/
            # embedding.go Adam slot tables) sharded with the rows. optax
            # tree ops preserve nn.Partitioned boxes, so running tx.init on
            # the *boxed* abstract params yields boxed slots whose specs we
            # can read; GSPMD propagation alone leaves them replicated.
            def _abstract(rng, feats):
                variables = _variables(rng, feats)
                return variables, tx.init(variables["params"])

            abstract, abstract_opt = jax.eval_shape(_abstract, root_key, features)
            param_shardings = nn.get_sharding(abstract, self.mesh)
            opt_shardings = nn.get_sharding(abstract_opt, self.mesh)

            def _create(rng, feats):
                variables = nn.meta.unbox(_variables(rng, feats))
                variables = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, variables, param_shardings
                )
                params = variables.pop("params")
                opt_state = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint,
                    tx.init(params),
                    opt_shardings,
                )
                return TrainState(
                    step=jnp.zeros((), jnp.int32),
                    params=params,
                    opt_state=opt_state,
                    extra_vars=variables,
                    rng=rng,
                )

            return jax.jit(_create)

        with jax.set_mesh(self.mesh):
            # Cache-keyed like the step programs (a re-formed world at an
            # unchanged shape must not re-trace model init). The key carries
            # the example-feature avals because the derived shardings bake
            # the parameter shapes in; features are an ARGUMENT of the
            # jitted program (not a closure constant), so a cached program
            # re-run with a different example batch stays value-correct
            # even for data-dependent initializers.
            create = self._cache.get_or_build(
                self._program_key("init") + (cc.aval_signature(features),),
                build_create,
            )
            state = create(root_key, features)
        n = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
        logger.info("Initialized model %s: %.3fM params", self.spec.module_name, n / 1e6)
        return state

    def abstract_train_state(self, example_batch: Dict[str, Any]) -> TrainState:
        """Execution-free twin of `init_state`: the same TrainState pytree
        as ShapeDtypeStructs carrying their NamedShardings. Consumed by
        checkpoint-restore targets and by AOT lowering for worlds this
        process cannot execute on (speculative neighbor compilation: on a
        real multi-process mesh, running init from one process would hang
        on collectives its peers never joined — lowering does not)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        model, tx = self.spec.model, self.spec.optimizer
        features, _, _ = _split_batch(example_batch)
        root_key = jax.random.PRNGKey(self.seed)

        with jax.set_mesh(self.mesh):
            def _abstract(rng, feats):
                variables = model.init(
                    {"params": rng, "dropout": rng}, feats, training=False)
                return variables, tx.init(variables["params"])

            abstract, abstract_opt = jax.eval_shape(_abstract, root_key, features)
            param_shardings = nn.get_sharding(abstract, self.mesh)
            opt_shardings = nn.get_sharding(abstract_opt, self.mesh)
            repl = NamedSharding(self.mesh, P())

            def strip_boxes(tree):
                # nn.meta.unbox applies a sharding constraint (trace-only);
                # here we just want the boxed avals out of their metadata
                is_box = lambda x: isinstance(x, nn.meta.AxisMetadata)  # noqa: E731
                return jax.tree_util.tree_map(
                    lambda x: x.value if is_box(x) else x, tree, is_leaf=is_box
                )

            def sds(leaf, sharding):
                return jax.ShapeDtypeStruct(
                    tuple(leaf.shape), leaf.dtype, sharding=sharding)

            variables = jax.tree_util.tree_map(
                sds, strip_boxes(abstract), param_shardings)
            params = variables.pop("params")
            opt_state = jax.tree_util.tree_map(
                sds, strip_boxes(abstract_opt), opt_shardings)
            return TrainState(
                step=jax.ShapeDtypeStruct((), jnp.int32, sharding=repl),
                params=params,
                opt_state=opt_state,
                extra_vars=variables,
                rng=jax.ShapeDtypeStruct(
                    tuple(root_key.shape), root_key.dtype, sharding=repl),
            )

    # ------------------------------------------------------------------ #
    # Steps

    def _build_train_step(self):
        return jax.jit(self._raw_train_step(), donate_argnums=(0,))

    def _raw_train_step(self):
        model, tx, loss_fn = self.spec.model, self.spec.optimizer, self.spec.loss
        remat = self.remat
        remat_policy = self._resolved_remat_policy
        accum = self.grad_accum
        aux_weight = float(self.spec.aux_loss_weight or 0.0)

        def step_fn(state: TrainState, batch):
            features, labels, mask = _split_batch(batch)
            step_rng = jax.random.fold_in(state.rng, state.step)
            mutable = list(state.extra_vars.keys())

            def forward(variables, feats, rng):
                if mutable:
                    return model.apply(
                        variables, feats, training=True,
                        rngs={"dropout": rng}, mutable=mutable,
                    )
                return (
                    model.apply(variables, feats, training=True, rngs={"dropout": rng}),
                    {},
                )

            if remat:
                forward = jax.checkpoint(forward, policy=remat_policy)

            def compute_loss(params):
                variables = {"params": params, **state.extra_vars}
                outputs, new_vars = forward(variables, features, step_rng)
                loss = _masked_scalar_loss(loss_fn, labels, outputs, mask)
                return loss + _aux_loss(new_vars, aux_weight), new_vars

            if accum > 1:
                loss_value, new_vars, grads = _accumulated_grads(
                    forward, loss_fn, state, features, labels, mask,
                    step_rng, accum, aux_weight=aux_weight,
                )
            else:
                (loss_value, new_vars), grads = jax.value_and_grad(
                    compute_loss, has_aux=True
                )(state.params)
            updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                extra_vars=new_vars,
            )
            return new_state, {"loss": loss_value.astype(jnp.float32)}

        return step_fn

    def _build_eval_step(self):
        return jax.jit(self._raw_eval_step())

    def _raw_eval_step(self):
        model, loss_fn = self.spec.model, self.spec.loss
        metric_items = tuple(self.metrics.items())

        def step_fn(state: TrainState, batch, metric_states):
            features, labels, mask = _split_batch(batch)
            variables = {"params": state.params, **state.extra_vars}
            outputs = model.apply(variables, features, training=False)
            new_states = dict(metric_states)
            for name, metric in metric_items:
                new_states[name] = metric.update(
                    metric_states[name], labels, outputs, mask
                )
            loss_value = _masked_scalar_loss(loss_fn, labels, outputs, mask)
            count = (
                jnp.sum(jnp.asarray(mask, jnp.float32))
                if mask is not None
                else jnp.float32(jnp.reshape(jnp.asarray(labels), (-1,)).shape[0])
            )
            new_states["_loss"] = metric_states["_loss"] + jnp.stack(
                [loss_value * count, count]
            )
            return new_states

        return step_fn

    def _build_predict_step(self):
        return jax.jit(self._raw_predict_step())

    def _raw_predict_step(self):
        model = self.spec.model

        def step_fn(state: TrainState, batch):
            features, _, _ = _split_batch(batch)
            variables = {"params": state.params, **state.extra_vars}
            return model.apply(variables, features, training=False)

        return step_fn

    # ------------------------------------------------------------------ #
    # Public API

    def train_step(self, state: TrainState, batch: Dict[str, Any]):
        fn = self._ensure("_train_step", "train_step", self._build_train_step)
        batch = mesh_lib.shard_batch(self.mesh, batch, self.spec.batch_partition)
        with jax.set_mesh(self.mesh):
            return self._dispatch("train_step", fn, state, batch)

    def train_many(self, state: TrainState, stacked_batch):
        """K train steps in ONE XLA dispatch: `lax.scan` of the step over a
        stacked batch pytree (leaves (K, B, ...) — build with
        `mesh.shard_batch_stack`). TPU-idiomatic dispatch amortization: the
        per-step host round-trip disappears (the reference pays a gRPC
        round-trip per minibatch — SURVEY §3.3; through this sandbox's TPU
        tunnel one dispatch costs ~10-70 ms, dwarfing small steps). Returns
        (new_state, metrics stacked over the K steps)."""
        fn = self._ensure("_train_many", "train_many", self._build_train_many)
        with jax.set_mesh(self.mesh):
            return self._dispatch("train_many", fn, state, stacked_batch)

    def _build_train_many(self):
        """The scan-of-step program."""
        raw = self._raw_train_step()
        return jax.jit(
            lambda s, stacked: jax.lax.scan(raw, s, stacked),
            donate_argnums=(0,),
        )

    def train_step_cost(self, state: TrainState, batch) -> Dict[str, float]:
        """XLA cost analysis of ONE train step (the scan body `train_many`
        runs K times per dispatch): {'flops', 'bytes accessed'} from the
        lowered (pre-optimization) HLO — milliseconds on backends whose
        client-side analysis works; on PJRT-plugin backends (the axon TPU)
        it falls back to compiling the step AOT to ask the backend, which
        can take the full first-compile time (~20-40 s on the chip) — keep
        this off latency-sensitive paths. The SINGLE step is costed
        deliberately: XLA's
        cost analysis counts a `lax.scan` (while-loop) body ONCE regardless
        of trip count, so costing the train_many program would be ambiguous
        per-step. Matmul/conv FLOPs are exact (fusion never changes them);
        'bytes accessed' counts every pre-fusion intermediate and so
        upper-bounds real HBM traffic. This is the analytic numerator for
        the MFU the bench reports."""
        fn = self._ensure("_train_step", "train_step", self._build_train_step)
        batch = mesh_lib.shard_batch(self.mesh, batch, self.spec.batch_partition)
        with jax.set_mesh(self.mesh):
            lowered = fn.lower(state, batch)
            ca = lowered.cost_analysis()
            d = ca if isinstance(ca, dict) else (ca[0] if ca else {})
            if not d.get("flops"):
                # PJRT-plugin backends (the axon TPU here) return None
                # from the client-side lowered analysis; the compiled
                # executable's analysis is computed by the backend and
                # does work there. This is a FRESH AOT compile of the
                # single-step program (train_many's scan is a different
                # program, so nothing is cached) — memoized per (state,
                # batch) aval signature, so repeat callers pay it once per
                # distinct step shape and a different batch shape gets its
                # own analysis instead of the stale first result.
                key = _aval_signature((state, batch))
                if key in self._cost_cache:
                    d = self._cost_cache[key]
                else:
                    try:
                        d = lowered.compile().cost_analysis() or {}
                    except Exception:
                        d = {}
                    self._cost_cache[key] = d
        return {
            "flops": float(d.get("flops", 0.0)),
            "bytes accessed": float(d.get("bytes accessed", 0.0)),
        }

    def set_learning_rate(self, state: TrainState, lr: float) -> TrainState:
        """Runtime LR change with no retrace — requires the zoo optimizer to
        be built via lr_modulation.modulated (injected hyperparams)."""
        from elasticdl_tpu.training import lr_modulation

        return state.replace(
            opt_state=lr_modulation.set_learning_rate(state.opt_state, lr)
        )

    def new_metric_states(self) -> Dict[str, np.ndarray]:
        states = metrics_lib.init_states(self.metrics)
        states["_loss"] = np.zeros((2,), np.float32)
        return states

    def eval_step(self, state: TrainState, batch, metric_states):
        fn = self._ensure("_eval_step", "eval_step", self._build_eval_step)
        batch = mesh_lib.shard_batch(self.mesh, batch, self.spec.batch_partition)
        with jax.set_mesh(self.mesh):
            return self._dispatch("eval_step", fn, state, batch, metric_states)

    def eval_many(self, state: TrainState, stacked_batch, metric_states):
        """K eval steps in ONE XLA dispatch: `lax.scan` of the eval step
        over a stacked batch pytree (build with `mesh.shard_batch_stack`) —
        the eval-stream twin of `train_many`'s dispatch amortization (the
        per-dispatch host round trip dominates small eval batches on a slow
        link). Streaming metric states are the scan carry, so the result is
        numerically equivalent to K sequential `eval_step` calls (the scan
        body compiles separately — XLA fusion may round the last bit
        differently)."""
        fn = self._ensure("_eval_many", "eval_many", self._build_eval_many)
        with jax.set_mesh(self.mesh):
            return fn(state, stacked_batch, metric_states)

    def _build_eval_many(self):
        raw = self._raw_eval_step()
        return jax.jit(
            lambda s, stacked, ms: jax.lax.scan(
                lambda carry, b: (raw(s, b, carry), None), ms, stacked
            )[0]
        )

    def predict_step(self, state: TrainState, batch):
        fn = self._ensure(
            "_predict_step", "predict_step", self._build_predict_step)
        batch = mesh_lib.shard_batch(self.mesh, batch, self.spec.batch_partition)
        with jax.set_mesh(self.mesh):
            return self._dispatch("predict_step", fn, state, batch)

    def predict_many(self, state: TrainState, stacked_batch):
        """K predict steps in ONE dispatch (`lax.map` over the stacked
        batch pytree): outputs come back stacked (K, B, ...) — the
        prediction twin of train_many/eval_many dispatch amortization."""
        fn = self._ensure(
            "_predict_many", "predict_many", self._build_predict_many)
        with jax.set_mesh(self.mesh):
            return fn(state, stacked_batch)

    def _build_predict_many(self):
        raw = self._raw_predict_step()
        return jax.jit(
            lambda s, stacked: jax.lax.map(lambda b: raw(s, b), stacked)
        )

    def metric_results(self, metric_states) -> Dict[str, float]:
        states = {k: np.asarray(jax.device_get(v)) for k, v in metric_states.items()}
        out = metrics_lib.results(self.metrics, {k: v for k, v in states.items() if k != "_loss"})
        loss_state = states.get("_loss")
        if loss_state is not None and loss_state[1] > 0:
            out["loss"] = float(loss_state[0] / loss_state[1])
        return out
