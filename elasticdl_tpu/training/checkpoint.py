"""Sharded checkpointing via orbax.

Reference parity: elasticdl/python/master/checkpoint_service.py — versioned
checkpoint directories every `--checkpoint_steps`, keep `--keep_checkpoint_max`,
restore on restart. The reference's master pulled dense params and iterated PS
embedding shards over gRPC to assemble a checkpoint; here orbax writes each
device's shard of the (mesh-sharded) TrainState directly — no gather, no
single-host bottleneck, which is what makes preemption-triggered saves cheap
enough for elasticity.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )

    def save(self, state: Any, step: Optional[int] = None, wait: bool = False) -> int:
        step = int(state.model_version if step is None else step)
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()
        logger.info("checkpoint step %d -> %s", step, self._dir)
        return step

    def latest_step(self, refresh: bool = False) -> Optional[int]:
        """refresh=True re-reads the directory — orbax caches the step list
        per manager instance, so observers polling for checkpoints written by
        OTHER processes (e.g. the resize quiesce in master/process_manager)
        must refresh or they never see them."""
        if refresh:
            try:
                self._mngr.reload()
            except Exception:
                logger.exception("checkpoint manager reload failed")
        return self._mngr.latest_step()

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Optional[Any]:
        """Restore into the sharding/structure of `abstract_state` (a pytree
        of jax.ShapeDtypeStruct with shardings, or a concrete state)."""
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            return None
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )
        logger.info("restored checkpoint step %d from %s", step, self._dir)
        return restored

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def delete(self, step: int) -> None:
        """Discard a saved step (drain checkpoints whose retirement report
        the master rejected must not be restored)."""
        try:
            self._mngr.delete(step)
            logger.info("deleted checkpoint step %d", step)
        except Exception:
            logger.exception("failed to delete checkpoint step %d", step)

    def close(self) -> None:
        self._mngr.close()
