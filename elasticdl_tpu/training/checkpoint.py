"""Sharded checkpointing via orbax.

Reference parity: elasticdl/python/master/checkpoint_service.py — versioned
checkpoint directories every `--checkpoint_steps`, keep `--keep_checkpoint_max`,
restore on restart. The reference's master pulled dense params and iterated PS
embedding shards over gRPC to assemble a checkpoint; here orbax writes each
device's shard of the (mesh-sharded) TrainState directly — no gather, no
single-host bottleneck, which is what makes preemption-triggered saves cheap
enough for elasticity.

Failure hardening on top of the plain orbax wrapper:

- `restore()` with no explicit step walks BACK from the latest step when it
  is corrupt or partially written (a crashed save, a torn copy), restoring
  the newest step that loads and warning loudly about every step skipped.
- A shape-mismatch restore failure is classified against the embedding
  geometry descriptor recorded beside the checkpoints (ops/embedding.py):
  instead of a raw orbax error, the caller gets told which vocab-padding
  rule the checkpoint was written under and what `vocab_align=` to rebuild
  the model with.
- Fault-injection sites `ckpt.save` / `ckpt.restore` (common/faults.py) sit
  in front of both operations, so chaos schedules can crash a save or fail
  a restore deterministically. Save atomicity under a crash is orbax's
  rename-commit; the chaos tests assert an injected crash-during-save never
  makes a half-written step visible to `latest_step()`.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, List, Optional

import jax
import orbax.checkpoint as ocp

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_CKPT_SAVES = _reg.counter(
    "edl_ckpt_saves_total", "checkpoint saves initiated")
_CKPT_RESTORES = _reg.counter(
    "edl_ckpt_restores_total", "successful checkpoint restores")
_CKPT_HANDOFFS = _reg.counter(
    "edl_ckpt_handoffs_total",
    "live state handoffs that skipped the restore round trip")
_CKPT_WALKBACKS = _reg.counter(
    "edl_ckpt_restore_walkbacks_total",
    "corrupt/partial steps skipped during restore")
_CKPT_SAVE_S = _reg.histogram(
    "edl_ckpt_save_seconds", "save initiation wall time (async part excl.)")

GEOMETRY_FILE = "embedding_geometry.json"


class CheckpointGeometryError(RuntimeError):
    """A checkpoint cannot restore into this model because the embedding
    vocab-padding geometry changed between write and restore."""


def _current_geometry() -> Optional[dict]:
    try:
        from elasticdl_tpu.ops import embedding as emb_ops

        return emb_ops.geometry_descriptor()
    except Exception:  # pragma: no cover - embedding ops always importable
        logger.exception("embedding geometry descriptor unavailable")
        return None


def _path_str(path) -> str:
    parts = []
    for p in path:
        for attr in ("key", "name", "idx"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def _leaf_shapes(tree: Any) -> dict:
    out = {}
    try:
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if hasattr(leaf, "shape"):
                out[_path_str(path)] = tuple(leaf.shape)
    except Exception:  # pragma: no cover — diagnostics must not break restore
        logger.exception("leaf-shape walk failed")
    return out


def _shape_mismatches(expected: Any, saved_metadata: Any) -> List[str]:
    """Shape diffs between the requested abstract state and what the
    checkpoint actually holds (its saved array metadata), matched by leaf
    path name. Orbax's StandardRestore does NOT reliably fail on
    global-shape changes — observed: restoring a (4, 2) saved array into
    an (8, 2) sharded target silently returns an (8, 2) array — so a
    geometry change (e.g. an embedding table padded under a different
    vocab alignment) could otherwise "restore" into padding garbage
    instead of erroring. Only paths present on both sides are compared, so
    container-naming differences degrade to a no-op, never a false
    positive."""
    want, have = _leaf_shapes(expected), _leaf_shapes(saved_metadata)
    return [
        f"{path}: model wants {want[path]}, checkpoint holds {have[path]}"
        for path in sorted(set(want) & set(have))
        if want[path] != have[path]
    ]


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )
        self.last_restored_step: Optional[int] = None

    # ------------------------------------------------------------------ #
    # geometry metadata

    def _geometry_path(self) -> str:
        return os.path.join(self._dir, GEOMETRY_FILE)

    def _record_geometry(self) -> None:
        """Write the embedding padding rule beside the checkpoints,
        refreshing a stale sidecar: the steps being written NOW carry the
        CURRENT build's geometry, so a descriptor left by an older build
        must not survive to misdiagnose later restore failures. Best-effort:
        a failed sidecar write must not fail the save that carries the
        actual training state. Known limitation: this records the
        module-level rule, not per-layer Embedding(vocab_align=...)
        overrides — the rule-matches-but-shapes-differ restore error spells
        out that case."""
        geo = _current_geometry()
        if geo is None:
            return
        stored = self.stored_geometry()
        if stored == geo:
            return
        if stored is not None:
            logger.warning(
                "embedding geometry sidecar is stale (%s); rewriting as %s "
                "— steps saved from here on carry the current geometry",
                stored, geo,
            )
        path = self._geometry_path()
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(geo, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            logger.exception("embedding geometry sidecar write failed")

    def stored_geometry(self) -> Optional[dict]:
        try:
            with open(self._geometry_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _raise_geometry_error(self, step: int, err: BaseException) -> None:
        """Turn a shape-mismatch restore failure into an actionable error
        naming the alignment to rebuild with (round-5 advisor: the raw
        orbax error gave users nothing to act on)."""
        stored = self.stored_geometry()
        current = _current_geometry()
        if stored is not None and stored != current:
            align = stored.get("vocab_align", 256)
            raise CheckpointGeometryError(
                f"checkpoint step {step} in {self._dir} was written under "
                f"embedding geometry {stored} but this build pads with "
                f"{current}. Rebuild the model with the checkpoint's "
                f"alignment — Embedding(..., vocab_align={align}) / "
                f"padded_vocab(v, align={align}) — or re-export the model "
                "under the new geometry."
            ) from err
        if stored is None:
            raise CheckpointGeometryError(
                f"checkpoint step {step} in {self._dir} does not match the "
                "model's parameter shapes and records no geometry metadata "
                "(written before this version). If it predates the round-5 "
                "large-vocab alignment change (256 -> 8192 for vocabs >= "
                "64k), rebuild the model with Embedding(..., "
                "vocab_align=256) / padded_vocab(v, align=256) to reproduce "
                f"the old geometry. Original error: {err}"
            ) from err
        # The recorded padding RULE matches this build, yet shapes differ.
        # The sidecar records the module default, not per-layer overrides,
        # so this is either a checkpoint from a different model entirely or
        # a vocab_align= override present on exactly one side (e.g. the
        # checkpoint was written by a model rebuilt with the old alignment
        # per the message above, then restored without it).
        raise CheckpointGeometryError(
            f"checkpoint step {step} in {self._dir} does not match the "
            f"model's parameter shapes: {err}. The recorded padding rule "
            f"({stored}) matches this build, so either this checkpoint "
            "belongs to a different model, or one side was built with an "
            "explicit Embedding(..., vocab_align=...) override — rebuild "
            "with the same override the checkpoint was written with."
        ) from err

    # ------------------------------------------------------------------ #

    def save(self, state: Any, step: Optional[int] = None, wait: bool = False) -> int:
        step = int(state.model_version if step is None else step)
        with tracing.span("ckpt.save", step=step, wait=wait) as sp:
            t0 = time.perf_counter()
            # chaos hook: ckpt.save:crash kills the process before orbax's
            # rename-commit — the step must never become visible; :drop
            # raises into the caller's save-failure path
            faults.fire("ckpt.save")
            self._record_geometry()
            self._mngr.save(step, args=ocp.args.StandardSave(state))
            # chaos hook: ckpt.save.commit:crash dies with the async write
            # in flight — orbax's rename-commit must leave no visible
            # partial step
            faults.fire("ckpt.save.commit")
            if wait:
                self._mngr.wait_until_finished()
            _CKPT_SAVES.inc()
            _CKPT_SAVE_S.observe(time.perf_counter() - t0)
            sp.set(dir=self._dir)
        logger.info("checkpoint step %d -> %s", step, self._dir)
        return step

    def save_overlapped(self, state: Any, overlap_fn, step: Optional[int] = None) -> int:
        """Rescale fast path: start the (async) save, run the caller's
        teardown work while orbax writes in the background, then block for
        durability. Used by the planned-resize/preemption drain so the
        final checkpoint write overlaps world teardown instead of
        serializing in front of it. The overlap work failing does not lose
        the checkpoint (the durability wait still runs); a failed save
        surfaces only after the overlap work completed."""
        step = self.save(state, step=step, wait=False)
        try:
            overlap_fn()
        except Exception:
            logger.exception("overlap work during final save failed")
        self._mngr.wait_until_finished()
        return step

    def restore_or_handoff(
        self, abstract_state: Any, handoff, new_mesh, step: Optional[int] = None
    ) -> Optional[Any]:
        """Prefer a live state handoff (parallel/elastic.LiveStateHandoff)
        over the checkpoint-restore round trip when the captured state is
        at least as new as the newest durable step — the planned-resize
        case, where the donor arrays are still resident and resharding
        beats deserializing. Anything older (or no capture at all) falls
        back to a plain restore; a failed apply falls back too, so the
        handoff is an optimization, never a new failure mode."""
        if handoff is not None and handoff.captured:
            latest = self.latest_step(refresh=True)
            if latest is None or (handoff.step or 0) >= latest:
                try:
                    with tracing.span("ckpt.handoff", step=handoff.step):
                        state = handoff.apply(new_mesh)
                    _CKPT_HANDOFFS.inc()
                    logger.info(
                        "live state handoff applied at step %s "
                        "(checkpoint-restore skipped)", handoff.step,
                    )
                    self.last_restored_step = handoff.step
                    return state
                except Exception:
                    logger.exception(
                        "live handoff failed; falling back to restore")
            else:
                logger.info(
                    "handoff step %s older than durable step %d; restoring",
                    handoff.step, latest,
                )
                handoff.discard()
        return self.restore(abstract_state, step=step)

    def latest_step(self, refresh: bool = False) -> Optional[int]:
        """refresh=True re-reads the directory — orbax caches the step list
        per manager instance, so observers polling for checkpoints written by
        OTHER processes (e.g. the resize quiesce in master/process_manager)
        must refresh or they never see them."""
        if refresh:
            try:
                self._mngr.reload()
            except Exception:
                logger.exception("checkpoint manager reload failed")
        return self._mngr.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._mngr.all_steps())

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Optional[Any]:
        """Restore into the sharding/structure of `abstract_state` (a pytree
        of jax.ShapeDtypeStruct with shardings, or a concrete state).

        With an explicit `step`, that step is tried alone. Otherwise steps
        are tried newest-first: a corrupt/partial newest step (crashed save,
        torn copy) is skipped with a loud warning and the previous step is
        restored — losing one checkpoint interval beats dying at relaunch.
        Shape mismatches are NOT walked past (every step shares the model's
        geometry, so older steps would fail identically): they raise a
        CheckpointGeometryError naming the alignment to rebuild with.
        """
        with tracing.span("ckpt.restore", step=step) as restore_span:
            return self._restore_traced(abstract_state, step, restore_span)

    def _restore_traced(self, abstract_state, step, restore_span):
        faults.fire("ckpt.restore")
        if step is not None:
            candidates = [step]
        else:
            candidates = sorted(self.all_steps(), reverse=True)
        if not candidates:
            restore_span.set(outcome="no_checkpoint")
            return None
        last_err: Optional[BaseException] = None
        for i, cand in enumerate(candidates):
            try:
                meta = self._mngr.item_metadata(cand)
            except Exception:
                meta = None  # unreadable metadata: let the restore attempt decide
            if meta is not None:
                mismatches = _shape_mismatches(abstract_state, meta)
                if mismatches:
                    self._raise_geometry_error(
                        cand, ValueError("; ".join(mismatches))
                    )
            try:
                restored = self._mngr.restore(
                    cand, args=ocp.args.StandardRestore(abstract_state)
                )
            except Exception as e:  # noqa: BLE001 — corrupt/partial: walk back
                # Geometry problems are detected by the metadata pre-check
                # above, BEFORE orbax restores anything; an exception here
                # is therefore treated as corruption, never classified by
                # its error text (a checksum "mismatch" must walk back, not
                # masquerade as a geometry diagnosis).
                last_err = e
                _CKPT_WALKBACKS.inc()
                remaining = len(candidates) - i - 1
                logger.warning(
                    "checkpoint step %d in %s failed to restore (%s: %s); "
                    "%s", cand, self._dir, type(e).__name__, e,
                    f"falling back to step {candidates[i + 1]}"
                    if remaining else "no older step left",
                )
                continue
            if i > 0:
                logger.warning(
                    "restored FALLBACK checkpoint step %d (skipped %d newer "
                    "corrupt/partial step(s): %s)",
                    cand, i, candidates[:i],
                )
            else:
                logger.info(
                    "restored checkpoint step %d from %s", cand, self._dir
                )
            self.last_restored_step = cand
            _CKPT_RESTORES.inc()
            restore_span.set(restored_step=cand, walked_back=i)
            return restored
        raise RuntimeError(
            f"every checkpoint step in {self._dir} failed to restore "
            f"(tried {candidates})"
        ) from last_err

    # ------------------------------------------------------------------ #
    # embedding tier shards (elasticdl_tpu/embedding/store.py)
    #
    # Tier tables are NOT TrainState leaves (they live outside the jitted
    # step, on their owning workers), so orbax never sees them; they ride
    # the same checkpoint directory as per-shard files with their
    # exactly-once sequence watermarks. The per-shard write is atomic
    # (tmp + fsync + replace), so a crash mid-save leaves every shard
    # either whole-old or whole-new — restore never sees a torn shard.

    def save_embedding_tier(self, store, tables=None) -> int:
        """Persist every tier shard resident in `store` beside the orbax
        steps; returns shards written. Called by the worker's drain path
        (a planned kill must lose no acked push) and by checkpoint-step
        cadence when the tier is live."""
        with tracing.span("ckpt.embedding_tier_save") as sp:
            n = store.save(self._dir, tables)
            sp.set(shards=n)
        return n

    def restore_embedding_tier(self, store) -> int:
        """Install any checkpointed shard the store's current map assigns
        here but that is not yet resident (kill-worker recovery); returns
        shards restored."""
        with tracing.span("ckpt.embedding_tier_restore") as sp:
            n = store.restore_missing(self._dir)
            sp.set(shards=n)
        return n

    @property
    def directory(self) -> str:
        """The root the tier's shard files live under (embedding/store
        resolves <dir>/emb/)."""
        return self._dir

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def delete(self, step: int) -> None:
        """Discard a saved step (drain checkpoints whose retirement report
        the master rejected must not be restored)."""
        try:
            self._mngr.delete(step)
            logger.info("deleted checkpoint step %d", step)
        except Exception:
            logger.exception("failed to delete checkpoint step %d", step)

    def close(self) -> None:
        self._mngr.close()
