"""Runtime learning-rate modulation without recompilation.

Reference parity: elasticdl/python/master/learning_rate_modulation.py — the
reference scaled the learning rate per gradient push (staleness-aware LR for
its async PS mode). The rebuild is synchronous, but runtime LR control is
still needed for elasticity: when the worker set grows or shrinks, the
effective global batch changes and the LR should scale with it (linear
scaling rule), without retracing the jitted train step.

Mechanism: `optax.inject_hyperparams` lifts the optimizer's hyperparameters
(learning_rate, ...) out of the traced closure and into the optimizer STATE,
which is a step input — so mutating the state between steps changes the LR
with zero recompilation. Zoo modules opt in by building their optimizer
through `modulated(...)`:

    def optimizer(**kw):
        return lr_modulation.modulated(optax.adam, learning_rate=1e-3)
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax


def modulated(tx_factory: Callable[..., optax.GradientTransformation],
              **hyperparams) -> optax.GradientTransformation:
    """Build `tx_factory(**hyperparams)` with hyperparams lifted into the
    optimizer state (mutable between steps via set_hyperparam)."""
    return optax.inject_hyperparams(tx_factory)(**hyperparams)


def _hyperparam_leaves(opt_state: Any):
    """Yield every InjectStatefulHyperparamsState-like node's hyperparams
    dict in the (possibly nested/chained) optax state tree."""
    nodes = [opt_state]
    while nodes:
        node = nodes.pop()
        hp = getattr(node, "hyperparams", None)
        if isinstance(hp, dict):
            yield node
        if isinstance(node, tuple):
            nodes.extend(node)
        else:
            inner = getattr(node, "inner_state", None)
            if inner is not None:
                nodes.append(inner)


def set_hyperparam(opt_state: Any, name: str, value) -> Any:
    """Return a copy of opt_state with hyperparam `name` set to `value` in
    every injected node that carries it. Raises if none does."""
    found = False
    nodes = list(_hyperparam_leaves(opt_state))
    for node in nodes:
        if name in node.hyperparams:
            found = True
    if not found:
        raise KeyError(
            f"no injected hyperparam {name!r}; build the optimizer with "
            f"lr_modulation.modulated(...)"
        )

    def replace(node):
        if name in node.hyperparams:
            old = node.hyperparams[name]
            new_hp = dict(node.hyperparams)
            new_hp[name] = jnp.asarray(value, jnp.asarray(old).dtype)
            return node._replace(hyperparams=new_hp)
        return node

    def walk(node):
        hp = getattr(node, "hyperparams", None)
        if isinstance(hp, dict):
            node = replace(node)
        inner = getattr(node, "inner_state", None)
        if inner is not None:
            return node._replace(inner_state=walk(inner))
        if isinstance(node, tuple) and not hasattr(node, "hyperparams"):
            return type(node)(*(walk(c) for c in node)) if hasattr(
                node, "_fields"
            ) else tuple(walk(c) for c in node)
        return node

    return walk(opt_state)


def get_hyperparam(opt_state: Any, name: str) -> Optional[float]:
    for node in _hyperparam_leaves(opt_state):
        if name in node.hyperparams:
            return float(jax.device_get(node.hyperparams[name]))
    return None


def set_learning_rate(opt_state: Any, lr: float) -> Any:
    return set_hyperparam(opt_state, "learning_rate", lr)


def get_learning_rate(opt_state: Any) -> Optional[float]:
    return get_hyperparam(opt_state, "learning_rate")


def apply_learning_rate(trainer, state, lr: float):
    """Set `lr` on `state` via the trainer, tolerating a zoo optimizer that
    was not built through `modulated(...)` — a pushed/rescaled LR reaching
    such a job is a config mismatch that must log, not kill the worker.
    Returns the (possibly unchanged) state. Shared by worker and cohort."""
    from elasticdl_tpu.common.log_utils import default_logger

    try:
        return trainer.set_learning_rate(state, lr)
    except KeyError:
        default_logger(__name__).warning(
            "ignoring LR %.6g: optimizer has no injected learning_rate "
            "(use lr_modulation.modulated)", lr,
        )
        return state


def linear_scale(base_lr: float, alive_workers: int, base_workers: int) -> float:
    """Linear-scaling rule for elastic membership changes (the sync-DP analog
    of the reference's staleness modulation): LR tracks the live worker
    count, i.e. the effective global batch size."""
    return base_lr * max(1, alive_workers) / max(1, base_workers)


def staleness_modulation(base_lr: float, staleness: int, factor: float = 1.0
                         ) -> float:
    """The reference's async-PS formula kept for parity: damp the LR for a
    gradient computed `staleness` versions behind."""
    return base_lr / (1.0 + factor * max(0, staleness))
