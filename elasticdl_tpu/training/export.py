"""Model export for serving — the reference's model handler, rebuilt.

Reference parity: elasticdl/python/common/model_handler.py — after training,
the reference rewrote `elasticdl.layers.Embedding` into `tf.keras.layers.
Embedding` by pulling every table row from the parameter-server pods, then
wrote a TF SavedModel for serving. Here the trained state already holds the
full tables as mesh-sharded `jax.Array`s in HBM, so export is a gather-free
`device_get` of the state pytree:

  <export_dir>/params.msgpack   flax.serialization of {"params", "extra_vars"}
  <export_dir>/model_info.json  model_def, model_params, step, framework info

`load_model()` rebuilds the serving pair (flax Module, variables) from an
export directory — single-device inference needs no mesh. `export_saved_model`
additionally writes a TF SavedModel via jax2tf when TensorFlow is available,
matching the reference's serving artifact format.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.version import __version__

logger = default_logger(__name__)

PARAMS_FILE = "params.msgpack"
INFO_FILE = "model_info.json"


def _host_variables(state: Any) -> Dict[str, Any]:
    """Gather the trained variables to host numpy. Single-host sharded arrays
    assemble via device_get; multi-host (jax.distributed) arrays span
    non-addressable devices, so they go through process_allgather instead."""
    import flax.linen as nn

    tree = {"params": state.params, "extra_vars": dict(state.extra_vars)}
    tree = nn.meta.unbox(tree)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        tree = multihost_utils.process_allgather(tree, tiled=True)
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


def export_model(
    state: Any,
    export_dir: str,
    model_def: str = "",
    model_params: Optional[Dict[str, Any]] = None,
    module_name: str = "",
    write_files: bool = True,
) -> str:
    """Write a serving export of a trained TrainState. Returns export_dir.

    Multi-process: the host gather inside is COLLECTIVE (process_allgather),
    so every process must call this; pass write_files=False on non-leader
    processes so only one writes the artifact.
    """
    from flax import serialization

    export_dir = os.path.abspath(export_dir)
    tree = _host_variables(state)
    if not write_files:
        return export_dir
    os.makedirs(export_dir, exist_ok=True)
    with open(os.path.join(export_dir, PARAMS_FILE), "wb") as f:
        f.write(serialization.msgpack_serialize(tree))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(tree["params"]))
    info = {
        "format": "elasticdl-tpu-export-v1",
        "model_def": model_def,
        "module_name": module_name,
        "model_params": dict(model_params or {}),
        "step": int(state.model_version),
        "num_params": int(n_params),
        "framework_version": __version__,
        "jax_version": jax.__version__,
    }
    # the info sidecar is what read_info/load_for_serving trust to decode
    # PARAMS_FILE — land it atomically so a crash mid-export can't leave a
    # torn manifest next to a complete params blob (edl-lint EDL305)
    info_path = os.path.join(export_dir, INFO_FILE)
    tmp = info_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f, indent=2, default=str)
    os.replace(tmp, info_path)
    logger.info(
        "exported model (%.3fM params, step %d) -> %s",
        n_params / 1e6, info["step"], export_dir,
    )
    return export_dir


def read_info(export_dir: str) -> Dict[str, Any]:
    with open(os.path.join(export_dir, INFO_FILE)) as f:
        return json.load(f)


def load_variables(export_dir: str) -> Dict[str, Any]:
    """Restore the exported variables dict {"params", "extra_vars"} as host
    numpy pytrees (no target structure needed)."""
    from flax import serialization

    with open(os.path.join(export_dir, PARAMS_FILE), "rb") as f:
        return serialization.msgpack_restore(f.read())


def load_model(
    export_dir: str,
    model_zoo: str,
    model_def: str = "",
    model_params: Optional[Dict[str, Any]] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """Rebuild (module, variables) for serving from an export directory.

    `model.apply(variables, features, training=False)` is the serving call;
    model_def/model_params default to the values recorded at export time.
    """
    from elasticdl_tpu.common.model_utils import load_module

    info = read_info(export_dir)
    model_def = model_def or info["model_def"]
    params = dict(info.get("model_params", {}))
    params.update(model_params or {})
    module, func_name = load_module(model_zoo, model_def)
    model = getattr(module, func_name)(**params)
    tree = load_variables(export_dir)
    variables = {"params": tree["params"], **tree.get("extra_vars", {})}
    return model, variables


def export_saved_model(
    export_dir: str,
    model_zoo: str,
    example_features: Any,
    out_dir: Optional[str] = None,
) -> Optional[str]:
    """Convert an export directory into a TF SavedModel via jax2tf.

    Returns the SavedModel path, or None when TensorFlow/jax2tf is not
    usable in this environment (the msgpack export remains authoritative).
    """
    try:
        import tensorflow as tf
        from jax.experimental import jax2tf
    except Exception as e:  # pragma: no cover - env without TF
        logger.warning("SavedModel export unavailable: %s", e)
        return None

    model, variables = load_model(export_dir, model_zoo)

    def serve(features):
        return model.apply(variables, features, training=False)

    # symbolic batch dim "b" so one SavedModel signature serves any batch size
    poly = jax.tree_util.tree_map(
        lambda x: ", ".join(["b"] + ["_"] * (np.ndim(x) - 1)), example_features
    )
    tf_fn = tf.function(
        jax2tf.convert(serve, with_gradient=False, polymorphic_shapes=[poly]),
        autograph=False,
        input_signature=[
            jax.tree_util.tree_map(
                # leading dim None: serving batch size is the client's choice
                lambda x: tf.TensorSpec(
                    (None,) + tuple(np.shape(x)[1:]), np.asarray(x).dtype
                ),
                example_features,
            )
        ],
    )
    out_dir = out_dir or os.path.join(export_dir, "saved_model")
    module = tf.Module()
    module.serve = tf_fn
    tf.saved_model.save(module, out_dir)
    logger.info("SavedModel -> %s", out_dir)
    return out_dir
