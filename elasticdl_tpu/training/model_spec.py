"""The model-zoo contract, resolved into one object.

Reference parity: the reference's model-zoo module contract — module-level
`custom_model()`, `loss()`, `optimizer()`, `dataset_fn()`, `eval_metrics_fn()`,
`callbacks()` functions addressed by `--model_def=pkg.module.custom_model`
(reference: elasticdl/python/common/model_utils.py and model_zoo/*).

Rebuilt in JAX terms:
- `custom_model(**model_params)` returns a `flax.linen.Module`,
- `loss(labels, outputs)` returns a scalar `jnp` loss (mean over batch),
- `optimizer(**model_params)` returns an `optax.GradientTransformation`,
- `dataset_fn(mode, metadata)` returns a `parse_fn(raw_record) -> (features,
  label)` of numpy values with static shapes (XLA needs static shapes; the
  framework does the batching and last-batch padding),
- `eval_metrics_fn()` returns `{name: Metric}` using
  `elasticdl_tpu.training.metrics` streaming metrics,
- `callbacks()` (optional) returns a list of callback objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import flax.linen as nn

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.model_utils import get_module_attr, load_module


@dataclass
class ModelSpec:
    model: nn.Module
    loss: Callable[..., Any]
    optimizer: Any                       # optax.GradientTransformation
    dataset_fn: Optional[Callable[..., Any]]
    eval_metrics_fn: Optional[Callable[[], Dict[str, Any]]]
    callbacks: List[Any] = field(default_factory=list)
    prediction_outputs_processor: Optional[Any] = None
    module_name: str = ""
    # The params the model was ACTUALLY built with (cfg.model_params plus
    # injected defaults like compute_dtype) — export must record these, or a
    # serving reload could rebuild the module with different defaults.
    model_params: Dict[str, Any] = field(default_factory=dict)
    # Optional per-top-level-key PartitionSpec overrides for input batches
    # (zoo module-level `batch_partition()`; sequence-parallel models shard
    # tokens over ('data', 'seq')).
    batch_partition: Optional[Dict[str, Any]] = None
    # Weight on auxiliary losses sown into the "losses" collection (e.g.
    # api.layers.MoE's Switch load-balance penalty). 0 = ignored. The
    # trainer adds weight * sum(sown leaves) INSIDE the differentiated
    # loss, so the aux regularizes training. Zoo modules export it as a
    # module-level `aux_loss_weight` float.
    aux_loss_weight: float = 0.0

    @classmethod
    def from_config(cls, cfg: JobConfig) -> "ModelSpec":
        module, func_name = load_module(cfg.model_zoo, cfg.model_def)
        model_fn = getattr(module, func_name, None)
        if model_fn is None:
            raise ValueError(f"{cfg.model_def!r}: no {func_name} in {module.__name__}")
        # Convention: the job-level compute_dtype reaches user models through
        # model_params unless the user already set one explicitly.
        model_params = dict(cfg.model_params)
        model_params.setdefault("compute_dtype", cfg.compute_dtype)
        model = model_fn(**model_params)
        if not isinstance(model, nn.Module):
            raise TypeError(
                f"{cfg.model_def} must return a flax.linen.Module, got {type(model)}"
            )

        loss = get_module_attr(module, "loss", cfg.loss, required=True)
        opt_fn = get_module_attr(module, "optimizer", cfg.optimizer, required=True)
        dataset_fn = get_module_attr(module, "dataset_fn", cfg.dataset_fn, required=False)
        metrics_fn = get_module_attr(
            module, "eval_metrics_fn", cfg.eval_metrics_fn, required=False
        )
        callbacks_fn = get_module_attr(module, "callbacks", "", required=False)
        batch_partition_fn = get_module_attr(
            module, "batch_partition", "", required=False
        )
        pop_fn = get_module_attr(
            module,
            "prediction_outputs_processor",
            cfg.prediction_outputs_processor,
            required=False,
        )

        return cls(
            model=model,
            loss=loss,
            optimizer=opt_fn(**cfg.model_params) if opt_fn else None,
            dataset_fn=dataset_fn,
            eval_metrics_fn=metrics_fn,
            callbacks=list(callbacks_fn()) if callbacks_fn else [],
            prediction_outputs_processor=pop_fn() if pop_fn else None,
            module_name=module.__name__,
            model_params=model_params,
            batch_partition=(
                dict(batch_partition_fn()) if batch_partition_fn else None
            ),
            aux_loss_weight=float(
                getattr(module, "aux_loss_weight", 0.0) or 0.0),
        )
