"""Elastic sharded embedding tier (ROADMAP 1).

Reference parity: the reference ElasticDL's parameter-service embedding
tier — tables sharded by id across PS pods (`id % ps_num`,
elasticdl/python/worker/ps_client.py), per-minibatch
pull_embedding_vectors / push_gradients round-trips, and the Go PS
applying sparse gradients row by row (elasticdl/pkg/ps/optimizer.go).

Rebuilt here as a TIER, not a sidecar process: tables are id-sharded
across owning workers (`sharding.shard_of`), the shard map is owned by
the master and committed through the control-plane journal
(`sharding.ShardMapOwner` — it survives master crash-restart), and the
per-batch protocol dedupes ids and batches per-shard calls
(`tier.EmbeddingTierClient`) before the owning store
(`store.EmbeddingShardStore`) hits the fused gather / deduped
scatter-add kernels in ops/embedding.py + ops/pallas_scatter.py.
Resharding on world change rides the same announce → quiesce → handoff
shape as mesh rescale: shards migrate via `reshard.apply_moves`
(device-to-device through parallel/elastic.reshard_state) with
exactly-once update semantics fenced by shard-map version + master
generation.

The serving-grade READ path (ISSUE 13) stacks three switchable layers
on the tier: a worker-local staleness-bounded hot-row cache fenced by
per-shard push watermarks (`cache.HotRowCache`), journal-committed read
replicas with watermark-delta sync and owner-death promotion, and a
pull/compute overlap pipeline (`tier.EmbeddingPullPipeline`).

See docs/architecture.md "Embedding tier" and docs/performance.md
"Embedding tier sizing" / "Embedding read path".
"""

from elasticdl_tpu.embedding.cache import HotRowCache  # noqa: F401
from elasticdl_tpu.embedding.sharding import (  # noqa: F401
    ShardMapOwner,
    ShardMapView,
    TableSpec,
    assign_replicas,
    plan_moves,
    shard_of,
)
from elasticdl_tpu.embedding.store import EmbeddingShardStore  # noqa: F401
from elasticdl_tpu.embedding.tier import (  # noqa: F401
    EmbeddingPullPipeline,
    EmbeddingTierClient,
)
from elasticdl_tpu.embedding.transport import LocalTransport  # noqa: F401
