"""The cross-host embedding data plane: real gRPC pull/push between
tier clients and owning stores, hardened for partitions (ISSUE 15).

Three layers, composable bottom-up:

1. **Wire** — `EmbeddingDataServicer` serves one worker's
   `EmbeddingShardStore` over five RPCs (`EmbeddingPull` /
   `EmbeddingPush` / `EmbeddingFetchShard` / `EmbeddingFetchDelta` /
   `EmbeddingWatermark`, hand-bound like proto/service.py — no
   grpcio-tools plugin on this image), from an `EmbeddingDataServer`
   each worker binds next to its observability endpoint. Id vectors
   travel as raw int32 bytes and rows as raw float32 bytes (one memcpy
   each way). The server honors the client's propagated gRPC deadline:
   a request arriving with (almost) no budget left is refused before
   any gather runs.

2. **Routing** — `GrpcTransport` implements transport.py's call
   contract over the master's OWNER ADDRESS BOOK (worker id -> data
   endpoint, riding the shard-map response): per-owner channels, an
   in-process short-circuit for the worker's own store, and the same
   request/response fault sites LocalTransport fires (`emb.pull` /
   `emb.pull.recv` / ...), so one chaos schedule drives either
   transport. gRPC failures map back to the tier's error vocabulary:
   FAILED_PRECONDITION -> StaleShardMapError, everything else ->
   OwnerUnavailableError (DeadlineExceededError for expired budgets).

3. **Robustness** — `ResilientTransport` wraps any inner transport
   with the RetryingMasterStub treatment, tuned for a data plane that
   must survive an owner partitioning away:

   - per-call DEADLINE BUDGETS: each logical call gets one budget
     (config `--embedding_rpc_deadline_ms`); retries and backoff
     sleeps spend it, and each attempt's wire deadline is the
     remaining budget split over the remaining attempts — a retry can
     never extend the caller's wait, and the budget propagates to the
     server as the gRPC deadline.
   - jittered exponential backoff RETRIES that re-send under the SAME
     client seq (the payload is untouched), so the store's
     exactly-once fence absorbs any ambiguous outcome.
   - per-OWNER CIRCUIT BREAKERS (proto/service.CircuitBreaker — the
     control plane's breaker, one per peer) with channel refresh on
     wedge: every `refresh_after` consecutive transport failures the
     owner's channel is rebuilt rather than trusted forever.
   - HEDGED READS: a pull whose primary has not answered after a
     p99-derived hedge delay races a replica; the first credible
     answer (replica credible iff its watermark is within the
     staleness bound of the highest watermark this transport has
     observed for the shard) wins, the loser is cancelled and counted.
   - the DEGRADED-MODE LADDER when an owner partitions away: hedge to
     a replica (`edl_emb_degraded_reads_total{mode="replica"}` when
     the primary actually failed, not merely lagged) -> the tier
     client serves staleness-bounded cache rows beyond `wm_probe`
     reach (mode="cache", counted in tier.py) -> block only when no
     bound can be honored (mode="blocked", counted here when every
     rung failed).
   - PUSHES QUEUE bounded-and-journaled behind an open breaker
     (`PushQueue`: an append-only journal so the partition window's
     writes are auditable and replayable) and DRAIN IN ORDER on
     reconnect — re-sent under their original seqs, so a heal can
     never double-apply (the bench's seq-fence audit) and a queued
     client keeps training through the partition instead of blocking.

`python -m elasticdl_tpu.embedding.data_plane --serve <spec.json>` runs
a standalone owner process (store + server + optional replica-sync
loop) — the multi-process half of `bench.py data_plane`.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import grpc
import numpy as np

from elasticdl_tpu.common import faults
from elasticdl_tpu.embedding import shm as _shm
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.embedding.store import StaleShardMapError
from elasticdl_tpu.embedding.transport import (
    DEGRADED_READS,
    OwnerUnavailableError,
)
from elasticdl_tpu.observability import reqtrace
from elasticdl_tpu.observability.registry import (
    default_registry,
    quantile_sorted,
)
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = default_logger(__name__)

DATA_SERVICE_NAME = "elasticdl_tpu.EmbeddingData"

# rpc name -> (request type, response type); bound generically like the
# Master service (proto/service.py _RPCS)
_DATA_RPCS = {
    "EmbeddingPull": (pb.EmbeddingPullRequest, pb.EmbeddingPullResponse),
    "EmbeddingPush": (pb.EmbeddingPushRequest, pb.EmbeddingPushResponse),
    "EmbeddingFetchShard": (
        pb.EmbeddingFetchShardRequest, pb.EmbeddingFetchShardResponse),
    "EmbeddingFetchDelta": (
        pb.EmbeddingFetchDeltaRequest, pb.EmbeddingFetchDeltaResponse),
    "EmbeddingWatermark": (
        pb.EmbeddingWatermarkRequest, pb.EmbeddingWatermarkResponse),
    # wire-speed lanes (ISSUE 18)
    "EmbeddingPullMulti": (
        pb.EmbeddingPullMultiRequest, pb.EmbeddingPullMultiResponse),
    "EmbeddingWatermarkMulti": (
        pb.EmbeddingWatermarkMultiRequest,
        pb.EmbeddingWatermarkMultiResponse),
    "EmbeddingShmNegotiate": (
        pb.EmbeddingShmNegotiateRequest, pb.EmbeddingShmNegotiateResponse),
}

# server-streamed rpcs (ISSUE 18): one call, chunked frames — replica
# sync and shard migration stop paying a unary round-trip per chunk
_DATA_STREAM_RPCS = {
    "EmbeddingFetchShardStream": (
        pb.EmbeddingFetchShardRequest, pb.EmbeddingShardChunk),
    "EmbeddingFetchDeltaStream": (
        pb.EmbeddingFetchDeltaRequest, pb.EmbeddingDeltaChunk),
}

#: stream chunk sizing: target bytes of row payload per frame (rows per
#: frame = STREAM_CHUNK_BYTES / (dim * 4), floor 1); delta streams frame
#: by entry count instead. docs/performance.md discusses the tradeoff.
STREAM_CHUNK_BYTES = int(float(os.environ.get(
    "EDL_EMB_STREAM_CHUNK_KB", "512")) * 1024)
STREAM_DELTA_ENTRIES = 64

_reg = default_registry()
_RPC_CALLS = _reg.counter(
    "edl_emb_rpc_client_calls_total",
    "data-plane RPC attempts (per method, incl. retries)",
    labels=("method",))
_RPC_FAILURES = _reg.counter(
    "edl_emb_rpc_client_failures_total",
    "failed data-plane RPC attempts", labels=("method",))
_RPC_RETRIES = _reg.counter(
    "edl_emb_rpc_client_retries_total",
    "data-plane retries after a retryable failure (same client seq — "
    "the store's exactly-once fence absorbs re-sends)",
    labels=("method",))
_RPC_DEADLINE = _reg.counter(
    "edl_emb_rpc_client_deadline_exceeded_total",
    "data-plane attempts that ran out their deadline budget",
    labels=("method",))
_RPC_LATENCY = _reg.histogram(
    "edl_emb_rpc_client_latency_seconds",
    "successful data-plane call latency", labels=("method",))
_RPC_SERVER_CALLS = _reg.counter(
    "edl_emb_rpc_server_calls_total",
    "data-plane RPCs served by this owner", labels=("method",))
_RPC_SERVER_EXPIRED = _reg.counter(
    "edl_emb_rpc_server_deadline_expired_total",
    "requests refused because the propagated deadline had (almost) no "
    "budget left — serving them would burn owner CPU on an answer the "
    "client already abandoned")
_BREAKER_OPEN = _reg.gauge(
    "edl_emb_owner_breakers_open",
    "embedding owners whose data-plane circuit breaker is currently open")
_BREAKER_TRIPS = _reg.counter(
    "edl_emb_owner_breaker_trips_total",
    "per-owner data-plane breaker open transitions")
_CHANNEL_REFRESHES = _reg.counter(
    "edl_emb_rpc_channel_refreshes_total",
    "data-plane channels rebuilt after repeated transport failures")
_HEDGED = _reg.counter(
    "edl_emb_hedged_pulls_total",
    "pulls that launched a replica hedge after the hedge delay")
_HEDGE_WINS = _reg.counter(
    "edl_emb_hedge_wins_total",
    "hedged pulls the replica answered first (credibly)")
_HEDGE_CANCELLED = _reg.counter(
    "edl_emb_hedge_losers_cancelled_total",
    "hedge losers cancelled/abandoned after the winner answered")
_HEDGE_DELAY_MS = _reg.gauge(
    "edl_emb_hedge_delay_ms",
    "current hedge delay (p99-derived unless pinned by config)")
_QUEUE_DEPTH = _reg.gauge(
    "edl_emb_push_queue_depth",
    "pushes queued behind open owner breakers, fleet of owners combined")
_QUEUE_ENQUEUED = _reg.counter(
    "edl_emb_push_queue_enqueued_total",
    "pushes accepted into the bounded partition queue")
_QUEUE_DRAINED = _reg.counter(
    "edl_emb_push_queue_drained_total",
    "queued pushes re-sent (same seq) after the owner reconnected")
_QUEUE_REJECTED = _reg.counter(
    "edl_emb_push_queue_rejected_total",
    "pushes refused because the bounded queue was full (the caller "
    "blocks/raises instead — bounded memory is part of the contract)")
_COALESCED_TABLES = _reg.histogram(
    "edl_emb_rpc_coalesced_tables",
    "(table, shard) sub-pulls fused into each EmbeddingPullMulti call "
    "— the coalescing factor the per-call amortization rides on")
_STREAM_CHUNKS = _reg.counter(
    "edl_emb_stream_chunks_total",
    "frames served/consumed on the streaming fetch lanes, by method",
    labels=("method",))


# ------------------------------------------------------------------ #
# wire codec: numpy <-> raw little-endian bytes (one memcpy each way)


def ids_to_bytes(ids: np.ndarray) -> bytes:
    return np.ascontiguousarray(
        np.asarray(ids, np.int32)).astype("<i4", copy=False).tobytes()


def ids_from_bytes(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype="<i4").astype(np.int32, copy=False)


def rows_to_bytes(rows: np.ndarray) -> bytes:
    return np.ascontiguousarray(
        np.asarray(rows, np.float32)).astype("<f4", copy=False).tobytes()


def rows_from_bytes(data: bytes, dim: int) -> np.ndarray:
    flat = np.frombuffer(data, dtype="<f4").astype(np.float32, copy=False)
    if dim <= 0:
        return flat.reshape(0, 0)
    return flat.reshape(-1, dim)


class DeadlineExceededError(OwnerUnavailableError):
    """A data-plane call ran out its deadline budget (the owner may or
    may not have applied it — the seq fence makes the re-send safe)."""


# ------------------------------------------------------------------ #
# fused serving helpers — pure store -> message functions shared by
# the gRPC servicer and the shared-memory ring dispatcher (the ring
# replaces the socket, not the codec)


def _serve_pull_multi(store, request) -> "pb.EmbeddingPullMultiResponse":
    """Serve one fused multi-(table, shard) pull: the flat id blob is
    segmented by `counts` with frombuffer views (no per-table copies
    in), the per-sub row blocks flatten into ONE response blob (one
    memcpy out), and the owner's full primary watermark set piggybacks.
    Raises StaleShardMapError for the caller to map onto its wire."""
    with reqtrace.stage("codec"):
        ids_flat = ids_from_bytes(request.ids)
    mv = request.map_version or None
    blocks: List[np.ndarray] = []
    dims: List[int] = []
    wms: List[int] = []
    off = 0
    with reqtrace.stage("store"):
        for table, shard, count in zip(request.tables, request.shards,
                                       request.counts):
            sub = ids_flat[off:off + count]
            off += count
            rows, wm = store.pull(
                table, int(shard), sub, map_version=mv,
                with_watermark=True, replica=request.replica)
            blocks.append(np.ascontiguousarray(
                np.asarray(rows, np.float32)).reshape(-1))
            dims.append(int(rows.shape[1]))
            wms.append(int(wm))
    with reqtrace.stage("codec"):
        rows_bytes = (
            np.concatenate(blocks).astype("<f4", copy=False).tobytes()
            if blocks else b"")
        resp = pb.EmbeddingPullMultiResponse(
            rows=rows_bytes, dims=dims, wms=wms)
    for t, s in store.resident_shards():
        resp.wm_tables.append(t)
        resp.wm_shards.append(int(s))
        resp.wm_values.append(int(store.shard_watermark(t, s)))
    return resp


def _serve_watermark_multi(store, request):
    return pb.EmbeddingWatermarkMultiResponse(wms=[
        int(store.shard_watermark(t, int(s), replica=request.replica))
        for t, s in zip(request.tables, request.shards)
    ])


def _decode_pull_multi(requests, resp):
    """Client side of the fused pull: segment the flat row blob into
    per-sub-request views (frombuffer — zero copies until the tier
    scatters into its output buffer) plus the piggybacked owner
    watermark map."""
    flat = np.frombuffer(resp.rows, dtype="<f4").astype(
        np.float32, copy=False)
    results = []
    off = 0
    for (_t, _s, ids), dim, wm in zip(requests, resp.dims, resp.wms):
        n = int(np.asarray(ids).shape[0])
        dim = int(dim)
        results.append((flat[off:off + n * dim].reshape(n, dim), int(wm)))
        off += n * dim
    owner_wms = {
        (t, int(s)): int(wm)
        for t, s, wm in zip(resp.wm_tables, resp.wm_shards, resp.wm_values)
    }
    return results, owner_wms


def _shm_dispatch(servicer, method_id: int, payload: bytes):
    """Serve one shared-memory ring request against the servicer's
    store. Mirrors the gRPC handlers' error mapping onto the ring's
    tiny status vocabulary (the 'shard map' marker keeps the client
    classifier routing to StaleShardMapError)."""
    store = servicer._store  # noqa: SLF001 - servicer-internal by design
    if store is None:
        return (_shm.S_STALE,
                b"stale shard map: no store bound on this owner yet")
    try:
        if method_id == _shm.M_PULL_MULTI:
            _RPC_SERVER_CALLS.inc(method="EmbeddingPullMulti")
            req = pb.EmbeddingPullMultiRequest.FromString(payload)
            resp = _serve_pull_multi(store, req)
        elif method_id == _shm.M_WATERMARK_MULTI:
            _RPC_SERVER_CALLS.inc(method="EmbeddingWatermarkMulti")
            req = pb.EmbeddingWatermarkMultiRequest.FromString(payload)
            resp = _serve_watermark_multi(store, req)
        elif method_id == _shm.M_PULL:
            _RPC_SERVER_CALLS.inc(method="EmbeddingPull")
            req = pb.EmbeddingPullRequest.FromString(payload)
            rows, wm = store.pull(
                req.table, req.shard, ids_from_bytes(req.ids),
                map_version=req.map_version or None,
                with_watermark=True, replica=req.replica)
            resp = pb.EmbeddingPullResponse(
                rows=rows_to_bytes(rows), dim=int(rows.shape[1]),
                wm=int(wm))
        elif method_id == _shm.M_PUSH:
            _RPC_SERVER_CALLS.inc(method="EmbeddingPush")
            req = pb.EmbeddingPushRequest.FromString(payload)
            applied, wm = store.push(
                req.table, req.shard, ids_from_bytes(req.ids),
                rows_from_bytes(req.rows, req.dim),
                client_id=req.client_id, seq=int(req.seq),
                map_version=req.map_version or None,
                scale=float(req.scale or 1.0), with_watermark=True)
            resp = pb.EmbeddingPushResponse(
                applied=bool(applied), wm=int(wm))
        elif method_id == _shm.M_WATERMARK:
            _RPC_SERVER_CALLS.inc(method="EmbeddingWatermark")
            req = pb.EmbeddingWatermarkRequest.FromString(payload)
            resp = pb.EmbeddingWatermarkResponse(wm=int(
                store.shard_watermark(req.table, req.shard,
                                      replica=req.replica)))
        else:
            return _shm.S_ERROR, f"unknown method {method_id}".encode()
    except StaleShardMapError as e:
        return _shm.S_STALE, f"stale shard map: {e}".encode("utf-8")
    except Exception as e:
        return _shm.S_ERROR, str(e).encode("utf-8")
    return _shm.S_OK, resp.SerializeToString()


# ------------------------------------------------------------------ #
# server side


class EmbeddingDataServicer:
    """Serves one worker's EmbeddingShardStore over the EmbeddingData
    RPCs. The store binds late (`bind_store`) so the endpoint can come
    up — and its address ride the RegisterWorker request — before the
    tier client exists to build the store."""

    #: refuse requests whose propagated deadline has less than this left:
    #: the client has already (or will immediately) abandon the answer
    MIN_BUDGET_S = 0.002

    def __init__(self, store=None):
        self._store = store

    def bind_store(self, store) -> None:
        self._store = store

    def _serve_guard(self, method: str, context) -> Any:
        _RPC_SERVER_CALLS.inc(method=method)
        if self._store is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "stale shard map: no store bound on this owner yet",
            )
        remaining = None
        try:
            remaining = context.time_remaining()
        except Exception:
            # deadline propagation is advisory on exotic contexts (tests
            # with fakes); the RPC itself is served:
            # edl-lint: disable=EDL303
            remaining = None
        if remaining is not None and remaining < self.MIN_BUDGET_S:
            _RPC_SERVER_EXPIRED.inc()
            context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                "propagated deadline budget exhausted before serve",
            )
        return self._store

    @staticmethod
    def _abort_stale(context, e: StaleShardMapError):
        # the marker "shard map" routes the client-side classifier back
        # to StaleShardMapError (GrpcTransport._map_error)
        context.abort(
            grpc.StatusCode.FAILED_PRECONDITION, f"stale shard map: {e}")

    def EmbeddingPull(self, request, context):
        store = self._serve_guard("EmbeddingPull", context)
        ids = ids_from_bytes(request.ids)
        try:
            rows, wm = store.pull(
                request.table, request.shard, ids,
                map_version=request.map_version or None,
                with_watermark=True, replica=request.replica,
            )
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        return pb.EmbeddingPullResponse(
            rows=rows_to_bytes(rows), dim=int(rows.shape[1]), wm=int(wm))

    def EmbeddingPush(self, request, context):
        store = self._serve_guard("EmbeddingPush", context)
        ids = ids_from_bytes(request.ids)
        rows = rows_from_bytes(request.rows, request.dim)
        try:
            applied, wm = store.push(
                request.table, request.shard, ids, rows,
                client_id=request.client_id, seq=int(request.seq),
                map_version=request.map_version or None,
                scale=float(request.scale or 1.0), with_watermark=True,
            )
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        return pb.EmbeddingPushResponse(applied=bool(applied), wm=int(wm))

    def EmbeddingFetchShard(self, request, context):
        store = self._serve_guard("EmbeddingFetchShard", context)
        try:
            payload = store.extract_shard(
                request.table, request.shard, replica=request.replica)
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        rows = np.asarray(payload["rows"], np.float32)
        return pb.EmbeddingFetchShardResponse(
            rows=rows_to_bytes(rows),
            rows_n=int(rows.shape[0]), dim=int(rows.shape[1]),
            applied_json=json.dumps(payload["applied"]),
            wm=int(payload.get("wm", 0)),
        )

    def EmbeddingFetchDelta(self, request, context):
        store = self._serve_guard("EmbeddingFetchDelta", context)
        try:
            delta = store.fetch_delta(
                request.table, request.shard, int(request.since_wm))
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        if delta is None:
            return pb.EmbeddingFetchDeltaResponse(found=False)
        resp = pb.EmbeddingFetchDeltaResponse(
            found=True, wm=int(delta["wm"]))
        for e in delta["entries"]:
            rows = np.asarray(e["rows"], np.float32)
            resp.entries.add(
                wm=int(e["wm"]), ids=ids_to_bytes(e["ids"]),
                rows=rows_to_bytes(rows),
                dim=int(rows.shape[1]) if rows.ndim == 2 else 0,
                scale=float(e.get("scale", 1.0)),
                client_id=str(e.get("client_id", "")),
                seq=int(e.get("seq", -1)),
            )
        return resp

    def EmbeddingWatermark(self, request, context):
        store = self._serve_guard("EmbeddingWatermark", context)
        try:
            wm = store.shard_watermark(
                request.table, request.shard, replica=request.replica)
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        return pb.EmbeddingWatermarkResponse(wm=int(wm))

    # ---- wire-speed lanes (ISSUE 18) ------------------------------- #

    def bind_shm(self, shm_server) -> None:
        """Late-bind the shared-memory ring server (EmbeddingDataServer
        owns its lifetime) so EmbeddingShmNegotiate can mint rings."""
        self._shm_server = shm_server

    def EmbeddingPullMulti(self, request, context):
        store = self._serve_guard("EmbeddingPullMulti", context)
        # server-side diary: codec/store stages land via the TLS stack
        # inside _serve_pull_multi; retained tails surface in the
        # OWNER's flight bundles next to the client's
        rec = reqtrace.get_recorder()
        d = rec.start("serve", method="pull_multi")
        try:
            resp = _serve_pull_multi(store, request)
        except StaleShardMapError as e:
            rec.finish(d, "error", f"stale shard map: {e}")
            self._abort_stale(context, e)
        except BaseException as e:
            rec.finish(d, "error", repr(e))
            raise
        rec.finish(d, "ok")
        return resp

    def EmbeddingWatermarkMulti(self, request, context):
        store = self._serve_guard("EmbeddingWatermarkMulti", context)
        try:
            return _serve_watermark_multi(store, request)
        except StaleShardMapError as e:
            self._abort_stale(context, e)

    def EmbeddingShmNegotiate(self, request, context):
        # no store guard: negotiation only mints a ring; every ring
        # request re-checks store binding at serve time
        _RPC_SERVER_CALLS.inc(method="EmbeddingShmNegotiate")
        shm_server = getattr(self, "_shm_server", None)
        if shm_server is None:
            return pb.EmbeddingShmNegotiateResponse(ok=False)
        granted = shm_server.negotiate(int(request.slot_bytes))
        if granted is None:
            return pb.EmbeddingShmNegotiateResponse(ok=False)
        name, slot_bytes = granted
        logger.info("shm ring %s (%d B slots) negotiated for client "
                    "%s pid %d", name, slot_bytes,
                    request.client_host or "?", request.client_pid)
        return pb.EmbeddingShmNegotiateResponse(
            ok=True, segment=name, slot_bytes=int(slot_bytes))

    def EmbeddingFetchShardStream(self, request, context):
        store = self._serve_guard("EmbeddingFetchShardStream", context)
        try:
            payload = store.extract_shard(
                request.table, request.shard, replica=request.replica)
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        rows = np.asarray(payload["rows"], np.float32)
        n, dim = int(rows.shape[0]), int(rows.shape[1])
        per_frame = max(1, STREAM_CHUNK_BYTES // max(1, dim * 4))
        off = 0
        first = True
        while True:
            end = min(n, off + per_frame)
            frame = pb.EmbeddingShardChunk(
                rows=rows_to_bytes(rows[off:end]), offset=off,
                last=end >= n)
            if first:
                # the fence rides the FIRST frame: a consumer that saw
                # frame 0 knows the full extent and the exactly-once
                # watermarks even if the stream dies right after
                frame.rows_n = n
                frame.dim = dim
                frame.applied_json = json.dumps(payload["applied"])
                frame.wm = int(payload.get("wm", 0))
                first = False
            _STREAM_CHUNKS.inc(method="EmbeddingFetchShardStream")
            yield frame
            off = end
            if off >= n:
                return

    def EmbeddingFetchDeltaStream(self, request, context):
        store = self._serve_guard("EmbeddingFetchDeltaStream", context)
        try:
            delta = store.fetch_delta(
                request.table, request.shard, int(request.since_wm))
        except StaleShardMapError as e:
            self._abort_stale(context, e)
        if delta is None:
            _STREAM_CHUNKS.inc(method="EmbeddingFetchDeltaStream")
            yield pb.EmbeddingDeltaChunk(found=False, last=True)
            return
        entries = delta["entries"]
        wm = int(delta["wm"])
        if not entries:
            _STREAM_CHUNKS.inc(method="EmbeddingFetchDeltaStream")
            yield pb.EmbeddingDeltaChunk(found=True, wm=wm, last=True)
            return
        for off in range(0, len(entries), STREAM_DELTA_ENTRIES):
            frame = pb.EmbeddingDeltaChunk(
                found=True, wm=wm,
                last=off + STREAM_DELTA_ENTRIES >= len(entries))
            for e in entries[off:off + STREAM_DELTA_ENTRIES]:
                erows = np.asarray(e["rows"], np.float32)
                frame.entries.add(
                    wm=int(e["wm"]), ids=ids_to_bytes(e["ids"]),
                    rows=rows_to_bytes(erows),
                    dim=int(erows.shape[1]) if erows.ndim == 2 else 0,
                    scale=float(e.get("scale", 1.0)),
                    client_id=str(e.get("client_id", "")),
                    seq=int(e.get("seq", -1)),
                )
            _STREAM_CHUNKS.inc(method="EmbeddingFetchDeltaStream")
            yield frame


def add_data_servicer(server: grpc.Server, servicer: Any) -> None:
    """Register the EmbeddingData handlers on a grpc server (generic
    handler API — same hand-binding as proto/service.add_master_servicer)."""
    handlers = {}
    for name, (req_t, _resp_t) in _DATA_RPCS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    for name, (req_t, _resp_t) in _DATA_STREAM_RPCS.items():
        handlers[name] = grpc.unary_stream_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(DATA_SERVICE_NAME, handlers),)
    )


class EmbeddingDataServer:
    """One worker's data-plane endpoint: a grpc server over an
    EmbeddingDataServicer, bound next to the observability endpoint
    (worker/worker.py starts it before registration so its address can
    ride the RegisterWorker request)."""

    def __init__(self, store=None, host: str = "127.0.0.1",
                 max_workers: int = 8, shm: bool = True):
        from elasticdl_tpu.proto.service import make_server

        self.host = host
        self.servicer = EmbeddingDataServicer(store)
        self._server = make_server(max_workers=max_workers)
        add_data_servicer(self._server, self.servicer)
        self.port: Optional[int] = None
        self._shm_server = None
        if shm:
            from elasticdl_tpu.embedding.shm import HAVE_SHM, ShmRingServer

            if HAVE_SHM:
                self._shm_server = ShmRingServer(
                    lambda method, payload: _shm_dispatch(
                        self.servicer, method, payload))
                self.servicer.bind_shm(self._shm_server)

    def start(self, port: int = 0) -> int:
        bound = self._server.add_insecure_port(f"{self.host}:{port}")
        if not bound:
            raise RuntimeError(
                f"embedding data plane failed to bind {self.host}:{port}")
        self._server.start()
        self.port = bound
        logger.info("embedding data plane serving on %s:%d",
                    self.host, bound)
        return bound

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)
        if self._shm_server is not None:
            self._shm_server.stop()

    @property
    def address(self) -> Optional[str]:
        return f"{self.host}:{self.port}" if self.port else None


# ------------------------------------------------------------------ #
# client side: routing


class DataPlaneStub:
    """Per-owner client stub over one channel (multicallables cached)."""

    def __init__(self, channel: grpc.Channel):
        self._methods = {}
        for name, (_req_t, resp_t) in _DATA_RPCS.items():
            self._methods[name] = channel.unary_unary(
                f"/{DATA_SERVICE_NAME}/{name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )
        for name, (_req_t, resp_t) in _DATA_STREAM_RPCS.items():
            self._methods[name] = channel.unary_stream(
                f"/{DATA_SERVICE_NAME}/{name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=resp_t.FromString,
            )

    def __getattr__(self, name: str):
        try:
            return self._methods[name]
        except KeyError as e:
            raise AttributeError(name) from e


class GrpcTransport:
    """transport.py's call contract over the owner address book.

    Owns one channel per peer owner; serves the LOCAL worker's own
    store in-process (a worker reading its own shard pays no wire).
    Every method takes an optional ``timeout_s`` — the deadline the
    ResilientTransport computed from its per-call budget — which rides
    to the server as the gRPC deadline (`accepts_deadline` advertises
    this; LocalTransport has no wire and no deadline)."""

    accepts_deadline = True

    def __init__(self, addresses: Optional[Dict[int, str]] = None,
                 default_timeout_s: float = 2.0, shm: bool = True):
        self._lock = threading.Lock()
        self._addrs: Dict[int, str] = dict(addresses or {})  # guarded_by: _lock
        self._channels: Dict[int, Tuple[grpc.Channel, DataPlaneStub]] = {}  # guarded_by: _lock
        self._local: Dict[int, Any] = {}                     # guarded_by: _lock
        self._default_timeout_s = default_timeout_s
        self._shm_enabled = bool(shm)
        self._shm_rings: Dict[int, Any] = {}                 # guarded_by: _lock
        self._shm_tried: Dict[int, str] = {}  # owner -> addr attempted; guarded_by: _lock
        self._shm_negotiating: Dict[int, threading.Thread] = {}  # guarded_by: _lock

    # ---- registry / address book ---------------------------------- #

    def register(self, store) -> None:
        with self._lock:
            self._local[store.owner] = store

    def deregister(self, owner: int) -> None:
        with self._lock:
            self._local.pop(owner, None)

    def owners(self) -> List[int]:
        with self._lock:
            return sorted(set(self._local) | set(self._addrs))

    def store_of(self, owner: int):
        with self._lock:
            store = self._local.get(owner)
        if store is None:
            raise OwnerUnavailableError(
                f"embedding owner {owner} is not local to this process "
                "(remote shards move via fetch_shard, not store_of)"
            )
        return store

    def update_addresses(self, addresses: Dict[int, str]) -> None:
        """Adopt the freshest owner address book (the shard-map
        response's). A changed address drops the cached channel — the
        old owner process is gone; its channel must not be trusted."""
        drop = []
        rings = []
        with self._lock:
            for owner, addr in addresses.items():
                owner = int(owner)
                if self._addrs.get(owner) != addr:
                    self._addrs[owner] = addr
                    drop.append(owner)
            for owner in drop:
                self._channels.pop(owner, None)
                # the shm short-circuit never outlives the address that
                # negotiated it: a moved/blackholed owner must not keep
                # serving through a stale ring
                ring = self._shm_rings.pop(owner, None)
                if ring is not None:
                    rings.append(ring)
        for ring in rings:
            ring.close()

    def address_of(self, owner: int) -> Optional[str]:
        with self._lock:
            return self._addrs.get(owner)

    def refresh_channel(self, owner: int) -> None:
        """Drop the cached channel so the next call rebuilds it (the
        ResilientTransport's wedge recovery — a subchannel that wedged
        across an owner restart must not be trusted forever). The old
        channel is NOT force-closed: close() cancels in-flight RPCs and
        the transport is shared across threads."""
        with self._lock:
            self._channels.pop(owner, None)
        _CHANNEL_REFRESHES.inc()

    def _stub(self, owner: int) -> DataPlaneStub:
        with self._lock:
            entry = self._channels.get(owner)
            if entry is not None:
                return entry[1]
            addr = self._addrs.get(owner)
        if addr is None:
            raise OwnerUnavailableError(
                f"embedding owner {owner} has no data-plane address "
                "(dead worker, or not yet in the address book)"
            )
        from elasticdl_tpu.proto.service import make_channel

        channel = make_channel(addr)
        stub = DataPlaneStub(channel)
        with self._lock:
            # a concurrent builder may have won; keep the first
            entry = self._channels.setdefault(owner, (channel, stub))
        return entry[1]

    # ---- error mapping -------------------------------------------- #

    @staticmethod
    def _map_error(e: BaseException, owner: int,
                   method: str) -> BaseException:
        """gRPC failure -> the tier's error vocabulary. The wrapped
        original rides as __cause__ for forensics."""
        code = details = None
        try:
            c = getattr(e, "code", None)
            code = c() if callable(c) else None
            d = getattr(e, "details", None)
            details = str(d()) if callable(d) else ""
        except Exception:
            # classification-only; an exotic error object is simply an
            # unavailable owner: edl-lint: disable=EDL303
            pass
        if (code == grpc.StatusCode.FAILED_PRECONDITION
                and "shard" in (details or "")):
            return StaleShardMapError(details)
        if code == grpc.StatusCode.DEADLINE_EXCEEDED:
            return DeadlineExceededError(
                f"{method} to owner {owner} exceeded its deadline")
        return OwnerUnavailableError(
            f"{method} to owner {owner} failed"
            f" ({code or type(e).__name__}): {details or e}")

    def _call(self, method: str, owner: int, request,
              timeout_s: Optional[float]):
        stub = self._stub(owner)
        try:
            with reqtrace.stage("wire"):
                return getattr(stub, method)(
                    request,
                    timeout=(timeout_s if timeout_s is not None
                             else self._default_timeout_s),
                )
        except grpc.RpcError as e:
            raise self._map_error(e, owner, method) from e

    # ---- same-host shared-memory short-circuit (ISSUE 18) ---------- #

    def _shm_ring(self, owner: int, timeout_s: Optional[float]):
        """The owner's attached ring, kicking off negotiation on first
        use. Negotiation is attempted AT MOST ONCE per (owner,
        address) — a declined/failed negotiate must not tax every
        later call, and a partitioned owner must not pay a negotiate
        round per pull on top of its gRPC deadline — and it runs in a
        BACKGROUND thread: the negotiate RPC + segment attach cost
        ~10ms on a loaded box, and the call that happened to arrive
        first must not eat that on its latency; it rides the socket
        while the ring comes up."""
        if not self._shm_enabled:
            return None
        if not _shm.HAVE_SHM:
            return None
        with self._lock:
            ring = self._shm_rings.get(owner)
            if ring is not None:
                return ring
            addr = self._addrs.get(owner)
            if addr is None or self._shm_tried.get(owner) == addr:
                return None
            self._shm_tried[owner] = addr
        host = addr.rsplit(":", 1)[0]
        if not _shm.same_host(host):
            return None
        t = threading.Thread(target=self._negotiate_ring,
                             args=(owner, addr),
                             name=f"edl-shm-negotiate-{owner}",
                             daemon=True)
        with self._lock:
            self._shm_negotiating[owner] = t
        t.start()
        return None

    def _negotiate_ring(self, owner: int, addr: str) -> None:
        """Background half of `_shm_ring`: one negotiate RPC, one
        attach, publish the ring (or give up — the gRPC lane keeps
        serving either way). `addr` is the address book entry the
        negotiation was initiated for: if the owner moved while the
        RPC was in flight, the ring must NOT be published —
        `update_addresses` already dropped this owner's lane, and a
        late publish would resurrect a short-circuit to the old
        process."""
        import socket

        try:
            try:
                resp = self._call(
                    "EmbeddingShmNegotiate", owner,
                    pb.EmbeddingShmNegotiateRequest(
                        client_host=socket.gethostname(),
                        client_pid=os.getpid(),
                        slot_bytes=_shm.DEFAULT_SLOT_BYTES),
                    min(self._default_timeout_s, 1.0))
            except OwnerUnavailableError:
                _shm.SHM_FALLBACKS.inc(reason="negotiate")
                return
            if not resp.ok:
                return
            try:
                ring = _shm.ShmRingClient(resp.segment,
                                          int(resp.slot_bytes))
            except _shm.ShmRingError as e:
                logger.warning("shm attach to owner %d failed: %s",
                               owner, e)
                _shm.SHM_FALLBACKS.inc(reason="attach")
                return
            with self._lock:
                if self._addrs.get(owner) != addr:
                    stale, ring = ring, None
                else:
                    # a concurrent negotiator may have won; keep the
                    # first
                    ring = self._shm_rings.setdefault(owner, ring)
            if ring is None:
                stale.close()
                _shm.SHM_FALLBACKS.inc(reason="stale")
                logger.warning(
                    "shm negotiate to owner %d raced an address change; "
                    "ring discarded", owner)
                return
            logger.info("shm short-circuit to owner %d via %s", owner,
                        resp.segment)
        finally:
            with self._lock:
                self._shm_negotiating.pop(owner, None)

    def _drop_ring(self, owner: int, reason: str) -> None:
        with self._lock:
            ring = self._shm_rings.pop(owner, None)
        if ring is not None:
            ring.close()
            _shm.SHM_FALLBACKS.inc(reason=reason)
            logger.warning(
                "shm ring to owner %d dropped (%s); gRPC lane takes over",
                owner, reason)

    def _shm_call(self, owner: int, method_id: int, req_bytes: bytes,
                  timeout_s: Optional[float]):
        """One ring round-trip, or None when the shm lane is
        unavailable (caller proceeds over gRPC). Ring failures drop
        the ring — the segment is gone or the owner stopped serving
        it; gRPC is the lane that still has liveness semantics."""
        ring = self._shm_ring(owner, timeout_s)
        if ring is None:
            return None
        if len(req_bytes) > ring.slot_bytes:
            # this one request outgrew the slot; the ring itself is
            # fine — fall back per-call without dropping it
            _shm.SHM_FALLBACKS.inc(reason="too_big")
            return None
        try:
            return ring.call(
                method_id, req_bytes,
                timeout_s=min(timeout_s or self._default_timeout_s, 1.0))
        except _shm.ShmRingTimeout:
            self._drop_ring(owner, "timeout")
            return None
        except _shm.ShmRingError:
            self._drop_ring(owner, "gone")
            return None

    def _shm_status(self, owner: int, method: str, status: int,
                    payload: bytes):
        detail = payload.decode("utf-8", "replace")
        if status == _shm.S_STALE:
            raise StaleShardMapError(detail)
        raise OwnerUnavailableError(
            f"{method} to owner {owner} failed over shm: {detail}")

    # ---- the transport contract ----------------------------------- #

    def pull(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray, map_version: Optional[int] = None,
             with_watermark: bool = False, replica: bool = False,
             timeout_s: Optional[float] = None):
        faults.fire("emb.pull")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            with reqtrace.stage("store"):
                out = local.pull(
                    table, shard, local_ids, map_version=map_version,
                    with_watermark=True, replica=replica)
            faults.fire("emb.pull.recv")
            rows, wm = out
            return (rows, wm) if with_watermark else rows
        with reqtrace.stage("codec"):
            req = pb.EmbeddingPullRequest(
                table=table, shard=int(shard),
                ids=ids_to_bytes(local_ids),
                map_version=int(map_version or 0),
                with_watermark=True, replica=bool(replica),
            )
        resp = self._call("EmbeddingPull", owner, req, timeout_s)
        faults.fire("emb.pull.recv")
        with reqtrace.stage("codec"):
            rows = rows_from_bytes(resp.rows, resp.dim)
        return (rows, int(resp.wm)) if with_watermark else rows

    def push(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray, rows: np.ndarray, *, client_id: str,
             seq: int, map_version: Optional[int] = None,
             scale: float = 1.0, with_watermark: bool = False,
             timeout_s: Optional[float] = None):
        faults.fire("emb.push")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            with reqtrace.stage("store"):
                applied, wm = local.push(
                    table, shard, local_ids, rows, client_id=client_id,
                    seq=seq, map_version=map_version, scale=scale,
                    with_watermark=True)
            faults.fire("emb.push.recv")
            return (applied, wm) if with_watermark else applied
        dim = int(rows.shape[1]) if rows.ndim == 2 else 0
        with reqtrace.stage("codec"):
            req = pb.EmbeddingPushRequest(
                table=table, shard=int(shard),
                ids=ids_to_bytes(local_ids), rows=rows_to_bytes(rows),
                dim=dim, client_id=client_id, seq=int(seq),
                map_version=int(map_version or 0), scale=float(scale),
                with_watermark=True,
            )
        resp = self._call("EmbeddingPush", owner, req, timeout_s)
        # lost-ack injection: the owner DID apply; the caller never
        # hears back and re-sends under the same seq (fence absorbs)
        faults.fire("emb.push.recv")
        applied, wm = bool(resp.applied), int(resp.wm)
        return (applied, wm) if with_watermark else applied

    def fetch_shard(self, owner: int, table: str, shard: int,
                    timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Shard migration copy — served over the streaming lane (one
        call, chunked frames, fence in frame 0) and assembled back
        into the unary payload shape every caller already expects."""
        faults.fire("emb.fetch_shard")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            payload = local.extract_shard(table, shard)
            faults.fire("emb.fetch_shard.recv")
            return payload
        stub = self._stub(owner)
        req = pb.EmbeddingFetchShardRequest(table=table, shard=int(shard))
        buf = None
        dim = 0
        meta: Dict[str, Any] = {"applied": {}, "wm": 0}
        try:
            for frame in stub.EmbeddingFetchShardStream(
                    req, timeout=(timeout_s if timeout_s is not None
                                  else self._default_timeout_s)):
                _STREAM_CHUNKS.inc(method="EmbeddingFetchShardStream.recv")
                if buf is None:
                    dim = int(frame.dim)
                    buf = np.zeros((int(frame.rows_n), dim), np.float32)
                    meta = {
                        "applied": {
                            str(k): int(v) for k, v in json.loads(
                                frame.applied_json or "{}").items()},
                        "wm": int(frame.wm),
                    }
                if frame.rows:
                    blk = rows_from_bytes(frame.rows, dim)
                    buf[frame.offset:frame.offset + blk.shape[0]] = blk
        except grpc.RpcError as e:
            raise self._map_error(e, owner, "EmbeddingFetchShardStream") \
                from e
        if buf is None:
            raise OwnerUnavailableError(
                f"fetch_shard {table}/{shard}: owner {owner} closed the "
                "stream before the first frame")
        faults.fire("emb.fetch_shard.recv")
        return {"rows": buf, "applied": meta["applied"],
                "wm": meta["wm"]}

    def shard_watermark(self, owner: int, table: str, shard: int,
                        replica: bool = False,
                        timeout_s: Optional[float] = None) -> int:
        faults.fire("emb.watermark")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            return local.shard_watermark(table, shard, replica=replica)
        resp = self._call(
            "EmbeddingWatermark", owner,
            pb.EmbeddingWatermarkRequest(
                table=table, shard=int(shard), replica=bool(replica)),
            timeout_s,
        )
        return int(resp.wm)

    def fetch_delta(self, owner: int, table: str, shard: int,
                    since_wm: int,
                    timeout_s: Optional[float] = None,
                    ) -> Optional[Dict[str, Any]]:
        faults.fire("emb.fetch_delta")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            delta = local.fetch_delta(table, shard, since_wm)
            faults.fire("emb.fetch_delta.recv")
            return delta
        resp = self._call(
            "EmbeddingFetchDelta", owner,
            pb.EmbeddingFetchDeltaRequest(
                table=table, shard=int(shard), since_wm=int(since_wm)),
            timeout_s,
        )
        faults.fire("emb.fetch_delta.recv")
        if not resp.found:
            return None
        return {
            "wm": int(resp.wm),
            "entries": [
                {
                    "wm": int(e.wm),
                    "ids": ids_from_bytes(e.ids),
                    "rows": rows_from_bytes(e.rows, e.dim),
                    "scale": float(e.scale),
                    "client_id": e.client_id,
                    "seq": int(e.seq),
                }
                for e in resp.entries
            ],
        }

    # ---- wire-speed lanes (ISSUE 18) ------------------------------- #

    def pull_multi(self, owner: int, requests,
                   map_version: Optional[int] = None,
                   replica: bool = False,
                   timeout_s: Optional[float] = None):
        """Fused multi-(table, shard) pull — LocalTransport.pull_multi's
        contract over one RPC (or one shm ring round-trip when the
        owner is same-host). One request-side and one response-side
        fault site per FUSED call: dropping it loses every sub-pull
        together, exactly what one lost wire call does."""
        faults.fire("emb.pull")
        _COALESCED_TABLES.observe(float(len(requests)))
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            with reqtrace.stage("store"):
                results = [
                    local.pull(t, s, ids, map_version=map_version,
                               with_watermark=True, replica=replica)
                    for t, s, ids in requests
                ]
                owner_wms = {
                    key: local.shard_watermark(*key)
                    for key in local.resident_shards()
                }
            faults.fire("emb.pull.recv")
            return results, owner_wms
        with reqtrace.stage("codec"):
            req = pb.EmbeddingPullMultiRequest(
                tables=[t for t, _, _ in requests],
                shards=[int(s) for _, s, _ in requests],
                counts=[int(np.asarray(ids).shape[0])
                        for _, _, ids in requests],
                ids=ids_to_bytes(
                    np.concatenate([
                        np.asarray(ids, np.int32).reshape(-1)
                        for _, _, ids in requests
                    ]) if requests else np.zeros((0,), np.int32)),
                map_version=int(map_version or 0),
                replica=bool(replica),
            )
            req_bytes = req.SerializeToString()
        got = self._shm_call(owner, _shm.M_PULL_MULTI,
                             req_bytes, timeout_s)
        if got is not None:
            status, payload = got
            if status != _shm.S_OK:
                self._shm_status(owner, "pull_multi", status, payload)
            with reqtrace.stage("codec"):
                resp = pb.EmbeddingPullMultiResponse.FromString(payload)
        else:
            resp = self._call("EmbeddingPullMulti", owner, req, timeout_s)
        faults.fire("emb.pull.recv")
        with reqtrace.stage("codec"):
            return _decode_pull_multi(requests, resp)

    def watermark_multi(self, owner: int, pairs, replica: bool = False,
                        timeout_s: Optional[float] = None):
        faults.fire("emb.watermark")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            return [local.shard_watermark(t, s, replica=replica)
                    for t, s in pairs]
        req = pb.EmbeddingWatermarkMultiRequest(
            tables=[t for t, _ in pairs],
            shards=[int(s) for _, s in pairs],
            replica=bool(replica),
        )
        got = self._shm_call(owner, _shm.M_WATERMARK_MULTI,
                             req.SerializeToString(), timeout_s)
        if got is not None:
            status, payload = got
            if status != _shm.S_OK:
                self._shm_status(owner, "watermark_multi", status, payload)
            resp = pb.EmbeddingWatermarkMultiResponse.FromString(payload)
        else:
            resp = self._call(
                "EmbeddingWatermarkMulti", owner, req, timeout_s)
        return [int(wm) for wm in resp.wms]

    def fetch_delta_stream(self, owner: int, table: str, shard: int,
                           since_wm: int, chunk_entries: int = 64,
                           timeout_s: Optional[float] = None):
        """Streaming replica sync (transport.py's reference framing
        over a real server stream). A mid-stream transport failure
        surfaces as OwnerUnavailableError from the generator — the
        caller resumes from whatever watermark its applied prefix
        reached."""
        faults.fire("emb.fetch_delta")
        with self._lock:
            local = self._local.get(owner)
        if local is not None:
            from elasticdl_tpu.embedding.transport import _delta_frames

            delta = local.fetch_delta(table, shard, since_wm)
            faults.fire("emb.fetch_delta.recv")
            return _delta_frames(delta, chunk_entries)
        stub = self._stub(owner)
        req = pb.EmbeddingFetchDeltaRequest(
            table=table, shard=int(shard), since_wm=int(since_wm))

        def gen():
            try:
                for frame in stub.EmbeddingFetchDeltaStream(
                        req, timeout=(timeout_s if timeout_s is not None
                                      else self._default_timeout_s)):
                    _STREAM_CHUNKS.inc(
                        method="EmbeddingFetchDeltaStream.recv")
                    yield {
                        "found": bool(frame.found),
                        "wm": int(frame.wm),
                        "entries": [
                            {
                                "wm": int(e.wm),
                                "ids": ids_from_bytes(e.ids),
                                "rows": rows_from_bytes(e.rows, e.dim),
                                "scale": float(e.scale),
                                "client_id": e.client_id,
                                "seq": int(e.seq),
                            }
                            for e in frame.entries
                        ],
                        "last": bool(frame.last),
                    }
                    if not frame.found:
                        return
            except grpc.RpcError as e:
                raise self._map_error(
                    e, owner, "EmbeddingFetchDeltaStream") from e
            faults.fire("emb.fetch_delta.recv")

        return gen()

    def close(self) -> None:
        with self._lock:
            channels = [c for c, _ in self._channels.values()]
            self._channels.clear()
            rings = list(self._shm_rings.values())
            self._shm_rings.clear()
        for ring in rings:
            ring.close()
        for c in channels:
            try:
                c.close()
            except Exception:
                logger.debug("channel close failed", exc_info=True)


# ------------------------------------------------------------------ #
# robustness layer


@dataclass(frozen=True)
class CallPolicy:
    """Per-method deadline budget and retry shape. `budget_s` bounds
    the WHOLE logical call — attempts, backoff sleeps, and hedges all
    spend it; each attempt's wire deadline is the remaining budget
    split over the remaining attempts."""

    budget_s: float
    max_attempts: int = 3


def default_policies(budget_s: float = 2.0) -> Dict[str, CallPolicy]:
    return {
        "pull": CallPolicy(budget_s=budget_s, max_attempts=3),
        # one fused call IS one wire call: same budget shape as pull
        "pull_multi": CallPolicy(budget_s=budget_s, max_attempts=3),
        "push": CallPolicy(budget_s=budget_s, max_attempts=3),
        # a shard copy is bulk data (recovery path, not the hot path)
        "fetch_shard": CallPolicy(budget_s=max(30.0, budget_s),
                                  max_attempts=2),
        "fetch_delta": CallPolicy(budget_s=max(5.0, budget_s),
                                  max_attempts=2),
        "watermark": CallPolicy(budget_s=min(1.0, budget_s),
                                max_attempts=2),
    }


class PushQueue:
    """Bounded, journaled FIFO of pushes parked behind an open owner
    breaker. The journal is an append-only jsonl (torn-tail tolerant,
    arrays base64'd) recording every `enqueue` and every `drain`, so
    the partition window's writes are auditable after the fact and the
    bench's replay check can reconstruct exactly what was parked and
    in what order it drained. Entries drain IN ENQUEUE ORDER per owner
    — a later seq must never reach the store before an earlier one, or
    the earlier one's drain would be swallowed as a duplicate."""

    def __init__(self, journal_path: str = "", max_entries: int = 1024):
        self._lock = threading.Lock()
        self._by_owner: Dict[int, deque] = {}       # guarded_by: _lock
        self._depth = 0                             # guarded_by: _lock
        self.max_entries = int(max_entries)
        self._journal_path = journal_path
        self._journal_failed = False
        if journal_path:
            os.makedirs(os.path.dirname(journal_path) or ".",
                        exist_ok=True)

    def _journal(self, record: Dict[str, Any]) -> None:
        if not self._journal_path or self._journal_failed:
            return
        try:
            # journaling inside the queue's critical section is the
            # replay-identity invariant (journal order == deque order);
            # plain buffered append, no fsync — see enqueue():
            # edl-lint: disable=EDL103
            with open(self._journal_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            # one-shot loud disable — queueing must not die with the disk
            self._journal_failed = True
            logger.exception(
                "push-queue journal %s failed; journaling disabled",
                self._journal_path)

    def depth(self, owner: Optional[int] = None) -> int:
        with self._lock:
            if owner is None:
                return self._depth
            return len(self._by_owner.get(owner, ()))

    def enqueue(self, entry: Dict[str, Any]) -> bool:
        """Park one push (False = full; the caller must block/raise —
        unbounded queueing would turn a partition into an OOM)."""
        with self._lock:
            if self._depth >= self.max_entries:
                _QUEUE_REJECTED.inc()
                return False
            self._by_owner.setdefault(int(entry["owner"]), deque()).append(
                entry)
            self._depth += 1
            _QUEUE_DEPTH.set(self._depth)
            # journaled INSIDE the critical section: two concurrent
            # enqueues must journal in deque order or the replay-
            # identity audit (enqueue stream == drain stream) breaks
            # spuriously. Plain buffered append, no fsync under lock.
            self._journal({
                "op": "enqueue", "owner": int(entry["owner"]),
                "table": entry["table"], "shard": int(entry["shard"]),
                "client_id": entry["client_id"], "seq": int(entry["seq"]),
                "scale": float(entry["scale"]),
                "map_version": entry["map_version"],
                "ids": base64.b64encode(
                    ids_to_bytes(entry["ids"])).decode(),
                "rows": base64.b64encode(
                    rows_to_bytes(entry["rows"])).decode(),
                "dim": int(entry["rows"].shape[1]),
            })
        _QUEUE_ENQUEUED.inc()
        return True

    def peek(self, owner: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            q = self._by_owner.get(owner)
            return q[0] if q else None

    def pop_drained(self, owner: int) -> None:
        with self._lock:
            q = self._by_owner.get(owner)
            if not q:
                return
            entry = q.popleft()
            if not q:
                self._by_owner.pop(owner, None)
            self._depth -= 1
            _QUEUE_DEPTH.set(self._depth)
            # under the lock for the same reason as enqueue's record
            self._journal({
                "op": "drain", "owner": int(entry["owner"]),
                "table": entry["table"], "shard": int(entry["shard"]),
                "client_id": entry["client_id"], "seq": int(entry["seq"]),
            })
        _QUEUE_DRAINED.inc()

    def owners_with_backlog(self) -> List[int]:
        with self._lock:
            return sorted(self._by_owner)

    @staticmethod
    def replay_journal(path: str) -> Dict[str, List[Dict[str, Any]]]:
        """Parse the journal back into its enqueue/drain streams (torn
        tail dropped) — the bench's replay-identity audit re-applies
        the enqueue stream and checks the drain stream retired exactly
        the enqueued (client_id, seq) pairs in order."""
        enqueued: List[Dict[str, Any]] = []
        drained: List[Dict[str, Any]] = []
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            return {"enqueued": [], "drained": []}
        for line in lines:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail
            if rec.get("op") == "enqueue":
                rec = dict(rec)
                rec["ids"] = ids_from_bytes(
                    base64.b64decode(rec["ids"]))
                rec["rows"] = rows_from_bytes(
                    base64.b64decode(rec["rows"]), int(rec["dim"]))
                enqueued.append(rec)
            elif rec.get("op") == "drain":
                drained.append(rec)
        return {"enqueued": enqueued, "drained": drained}


#: hedge-delay floor: below this the hedge races scheduler noise, and
#: every pull would pay a pointless executor round-trip
HEDGE_FLOOR_MS = 1.0
#: p99 window backing the derived hedge delay
_HEDGE_WINDOW = 128


def _diary_status(d: "reqtrace.Diary") -> str:
    """A call that answered but leaned on the degraded ladder (replica
    serve, hedge win) finishes its diary as `degraded` — the tail
    sampler retains those unconditionally."""
    for ev in d.events:
        if ev.get("name") == "degraded":
            return "degraded"
    return "ok"


class ResilientTransport:
    """The robustness layer over any transport (docstring at module
    top). Implements the same call contract, so the tier client, the
    replica sync loop, and reshard.py all harden for free."""

    RETRYABLE = (OwnerUnavailableError, faults.FaultInjected)

    def __init__(
        self,
        inner,
        policies: Optional[Dict[str, CallPolicy]] = None,
        staleness_bound: int = 1,
        hedge_delay_ms: float = 0.0,
        hedge: bool = True,
        view_fn: Optional[Callable[[], Any]] = None,
        queue_journal: str = "",
        queue_max: int = 1024,
        breaker_failures: int = 3,
        breaker_cooldown_s: float = 1.0,
        refresh_after: int = 3,
        backoff_base_s: float = 0.02,
        backoff_max_s: float = 0.5,
        rng=None,
        sleep: Callable[[float], None] = time.sleep,
        trace_tag: str = "",
    ):
        import random

        from elasticdl_tpu.proto.service import CircuitBreaker

        self._inner = inner
        # stamped into every request diary's meta: lets one process
        # running several transports (a hedged lane and an unhedged
        # control, a reader and a writer) slice its retained tail per
        # lane instead of per process
        self._trace_tag = str(trace_tag)
        self._policies = default_policies()
        if policies:
            self._policies.update(policies)
        self.staleness_bound = max(0, int(staleness_bound))
        self._hedge_enabled = bool(hedge)
        self._hedge_delay_ms = float(hedge_delay_ms)   # 0 = p99-derived
        self._view_fn = view_fn
        self._breaker_cls = CircuitBreaker
        self._breaker_failures = breaker_failures
        self._breaker_cooldown_s = breaker_cooldown_s
        self._refresh_after = max(1, refresh_after)
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._lock = threading.Lock()
        self._breakers: Dict[int, Any] = {}            # guarded_by: _lock
        self._consec_failures: Dict[int, int] = {}     # guarded_by: _lock
        self._observed_wm: Dict[Tuple[str, int], int] = {}  # guarded_by: _lock
        self._pull_lat: "deque[float]" = deque(maxlen=_HEDGE_WINDOW)  # guarded_by: _lock
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()
        self._inner_takes_deadline = bool(
            getattr(inner, "accepts_deadline", False))
        self.queue = (PushQueue(queue_journal, queue_max)
                      if queue_max > 0 else None)
        self._drain_lock = threading.Lock()

    # ---- plumbing -------------------------------------------------- #

    def __getattr__(self, name):
        # registry surface (register/deregister/store_of/owners/
        # update_addresses/...) passes straight through to the inner
        # transport
        return getattr(self._inner, name)

    def set_view_fn(self, view_fn: Callable[[], Any]) -> None:
        """Late-bind the shard-map view source (the tier client exists
        after the transport) — what hedging uses to find replicas and
        what drains use to re-route a moved shard."""
        self._view_fn = view_fn

    def _breaker(self, owner: int):
        with self._lock:
            br = self._breakers.get(owner)
            if br is None:
                br = self._breaker_cls(
                    failure_threshold=self._breaker_failures,
                    cooldown_s=self._breaker_cooldown_s,
                    # per-owner data-plane breakers keep their own
                    # edl_emb_owner_* metrics; the inherited master
                    # gauges/logs would misread a partitioned owner as
                    # a master outage (and mask a real one on close)
                    telemetry=False,
                )
                self._breakers[owner] = br
            return br

    def owner_degraded(self, owner: int) -> bool:
        """True while the owner's circuit is open — the tier client's
        signal that cache hits are being served beyond `wm_probe` reach
        (degraded mode \"cache\")."""
        with self._lock:
            br = self._breakers.get(owner)
        return br is not None and br.is_open

    def degraded_owners(self) -> List[int]:
        with self._lock:
            items = list(self._breakers.items())
        return [o for o, br in items if br.is_open]

    def observed_wm(self, table: str, shard: int) -> int:
        with self._lock:
            return self._observed_wm.get((table, shard), 0)

    def _note_wm(self, table: str, shard: int, wm: int) -> None:
        with self._lock:
            key = (table, shard)
            if wm > self._observed_wm.get(key, 0):
                self._observed_wm[key] = wm

    def _note_success(self, owner: int) -> None:
        br = self._breaker(owner)
        was_open = br.is_open
        br.record_success()
        with self._lock:
            self._consec_failures[owner] = 0
            open_now = sum(1 for b in self._breakers.values() if b.is_open)
        _BREAKER_OPEN.set(open_now)
        if was_open:
            logger.warning(
                "embedding owner %d reconnected (breaker closed)", owner)

    def _note_failure(self, owner: int) -> None:
        br = self._breaker(owner)
        was_open = br.is_open
        br.record_failure()
        refresh = False
        with self._lock:
            n = self._consec_failures.get(owner, 0) + 1
            self._consec_failures[owner] = n
            if n % self._refresh_after == 0:
                refresh = True
            open_now = sum(1 for b in self._breakers.values() if b.is_open)
        _BREAKER_OPEN.set(open_now)
        if br.is_open and not was_open:
            _BREAKER_TRIPS.inc()
        if refresh and hasattr(self._inner, "refresh_channel"):
            # wedge recovery: a channel that failed refresh_after times
            # in a row gets fresh sockets instead of trust
            self._inner.refresh_channel(owner)

    def _backoff(self, attempt: int) -> float:
        cap = min(self._backoff_max_s,
                  self._backoff_base_s * (2 ** attempt))
        return cap * self._rng.uniform(0.1, 1.0)

    def _kw(self, timeout_s: Optional[float]) -> Dict[str, Any]:
        return ({"timeout_s": timeout_s}
                if self._inner_takes_deadline and timeout_s is not None
                else {})

    def _hedge_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                # sized above the worst transient: abandoned primary
                # calls against a blackholed owner occupy slots until
                # their wire deadline, and the breaker needs a few
                # losses before it stops submitting them
                self._pool = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="emb-hedge")
            return self._pool

    def hedge_delay_s(self) -> float:
        """The delay before a pull hedges: pinned by config, or derived
        as the p99 of recent successful primary pulls (docs/
        performance.md \"Hedge-delay sizing\") with a floor — hedging
        the median would double read traffic for nothing; hedging only
        past p99 spends <1% extra reads to cut the tail."""
        if self._hedge_delay_ms > 0:
            delay = self._hedge_delay_ms / 1e3
        else:
            with self._lock:
                lats = sorted(self._pull_lat)
            if not lats:
                delay = 0.05
            else:
                # 1.25x p99: past p99 the primary has already missed
                # its tail SLO, and the margin only delays the rescue —
                # <1% of reads pay the extra replica call either way
                delay = max(HEDGE_FLOOR_MS / 1e3,
                            quantile_sorted(lats, 0.99) * 1.25)
        _HEDGE_DELAY_MS.set(round(delay * 1e3, 3))
        return delay

    def _replicas_of(self, shard: int, exclude: int) -> List[int]:
        if self._view_fn is None:
            return []
        try:
            view = self._view_fn()
        except Exception:
            # the view source is advisory for hedging; a failing fetch
            # just means no hedge this round: edl-lint: disable=EDL303
            return []
        if view is None:
            return []
        return [r for r in view.replicas_of(shard) if r != exclude]

    # ---- pull: deadline budget + hedge + degraded ladder ----------- #

    def pull(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray, map_version: Optional[int] = None,
             with_watermark: bool = False, replica: bool = False):
        policy = self._policies["pull"]
        t_end = time.monotonic() + policy.budget_s
        if replica:
            # the tier's own replica-routing path: deadline + retry
            # only (a replica read hedging to another replica would
            # recurse); staleness judgment stays with the caller
            return self._retry_simple(
                "pull", policy, t_end, owner,
                lambda to: self._pull_once(
                    owner, table, shard, local_ids, map_version,
                    replica=True, timeout_s=to),
                with_watermark=with_watermark)
        rec = reqtrace.get_recorder()
        d = rec.start("pull", owner=int(owner), table=table,
                      shard=int(shard), tag=self._trace_tag)
        last: Optional[BaseException] = None
        try:
            for attempt in range(policy.max_attempts):
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    break
                _RPC_CALLS.inc(method="pull")
                try:
                    rows, wm = self._pull_round(
                        owner, table, shard, local_ids, map_version,
                        remaining, policy.max_attempts - attempt)
                    rec.finish(d, status=_diary_status(d))
                    return (rows, wm) if with_watermark else rows
                except StaleShardMapError:
                    raise
                except self.RETRYABLE as e:
                    last = e
                    _RPC_FAILURES.inc(method="pull")
                    if isinstance(e, DeadlineExceededError):
                        _RPC_DEADLINE.inc(method="pull")
                    if attempt + 1 < policy.max_attempts:
                        _RPC_RETRIES.inc(method="pull")
                        reqtrace.event("retry", attempt=attempt,
                                       error=type(e).__name__)
                        with reqtrace.stage("budget_wait"):
                            self._sleep(
                                min(self._backoff(attempt),
                                    max(0.0,
                                        t_end - time.monotonic())))
        except BaseException as e:
            rec.finish(d, status="error",
                       detail=f"{type(e).__name__}: {e}")
            raise
        # the ladder's last rung: no primary, no credible replica — the
        # read blocks (the caller's retry loop / deadline decides how
        # long). Counted so partitions can't hide inside retry loops.
        DEGRADED_READS.inc(mode="blocked")
        err = last if last is not None else DeadlineExceededError(
            f"pull {table}/{shard} from owner {owner}: deadline budget "
            f"({policy.budget_s:.3f}s) spent")
        rec.finish(d, status="error",
                   detail=f"{type(err).__name__}: {err}")
        raise err

    def _pull_once(self, owner: int, table: str, shard: int,
                   local_ids, map_version, replica: bool,
                   timeout_s: Optional[float]):
        """One wire attempt; breaker + latency + watermark bookkeeping."""
        t0 = time.perf_counter()
        try:
            rows, wm = self._inner.pull(
                owner, table, shard, local_ids, map_version=map_version,
                with_watermark=True, replica=replica,
                **self._kw(timeout_s))
        except StaleShardMapError:
            # an application answer on a healthy transport — the owner
            # is alive and talking; never a breaker strike
            self._note_success(owner)
            raise
        except self.RETRYABLE:
            self._note_failure(owner)
            raise
        self._note_success(owner)
        dt = time.perf_counter() - t0
        _RPC_LATENCY.observe(dt, method="pull")
        if not replica:
            with self._lock:
                self._pull_lat.append(dt)
        self._note_wm(table, shard, int(wm))
        self._maybe_drain(owner)
        return rows, int(wm)

    def _pull_round(self, owner: int, table: str, shard: int,
                    local_ids, map_version, remaining_s: float,
                    attempts_left: int):
        """One retry-loop round of the degraded ladder: primary (hedged
        past the hedge delay when a replica exists) -> replica-only when
        the breaker already says the primary is gone."""
        breaker = self._breaker(owner)
        reps = self._replicas_of(shard, exclude=owner)
        attempt_timeout = remaining_s / max(1, attempts_left)
        if not breaker.allow():
            # fail-fast rung: the primary is known-partitioned; a
            # credible replica serves (honestly counted), else this
            # round fails without burning wire time on a dead peer
            reqtrace.event("breaker_open", owner=int(owner))
            rows_wm = self._pull_replica_any(
                reps, table, shard, local_ids, map_version,
                attempt_timeout)
            if rows_wm is not None:
                DEGRADED_READS.inc(mode="replica")
                reqtrace.event("degraded", mode="replica")
                return rows_wm
            raise OwnerUnavailableError(
                f"owner {owner} breaker open and no credible replica "
                f"for {table}/{shard}")
        if not (self._hedge_enabled and reps):
            return self._pull_once(
                owner, table, shard, local_ids, map_version,
                replica=False, timeout_s=attempt_timeout)
        return self._pull_hedged(
            owner, reps, table, shard, local_ids, map_version,
            attempt_timeout)

    def _pull_replica_any(self, reps: List[int], table: str, shard: int,
                          local_ids, map_version,
                          timeout_s: float):
        """First credible replica answer, or None. Credible = within
        the staleness bound of the highest watermark this transport has
        observed for the shard — a partition must never become a
        license to serve arbitrarily stale rows. Two rounds over the
        replica set: a transient failure (an injected drop, one lost
        packet) on the ONLY replica must not sink the whole hedge —
        the primary it is rescuing is by definition already in
        trouble."""
        known = self.observed_wm(table, shard)
        for _ in range(2):
            for rep in reps:
                try:
                    rows, wm = self._pull_once(
                        rep, table, shard, local_ids, map_version,
                        replica=True, timeout_s=timeout_s)
                except (StaleShardMapError, *self.RETRYABLE):
                    continue
                if wm + self.staleness_bound >= known:
                    return rows, wm
        return None

    def _pull_hedged(self, owner: int, reps: List[int], table: str,
                     shard: int, local_ids, map_version,
                     timeout_s: float):
        return self._hedged_race(
            owner,
            lambda: self._pull_once(
                owner, table, shard, local_ids, map_version, False,
                timeout_s),
            lambda: self._pull_replica_any(
                reps, table, shard, local_ids, map_version, timeout_s),
            f"hedged pull {table}/{shard}: primary {owner} and "
            f"replicas {reps} all failed")

    def _hedged_race(self, owner: int, primary_call, hedge_call,
                     fail_msg: str):
        """Race the primary against a replica launched after the hedge
        delay; first credible answer wins, the loser is cancelled (or
        abandoned to its own deadline — gRPC has no mid-flight recall
        for a blocking call) and counted. `hedge_call` must return
        None (not raise) on failure; both the unary and the fused pull
        lanes race through here."""
        pool = self._hedge_pool()
        primary = pool.submit(primary_call)
        # the pre-hedge wait is attributed by how it RESOLVES: a primary
        # that answers inside the hedge window spent caller-side wire
        # time, one that forces the hedge spent the hedge DELAY — that
        # delay is the hedge mechanism's transient, and charging it to
        # `wire` would make a partition tail read as wire-bound. The
        # attempt runs on a pool thread (no diary there by design), so
        # the caller attributes its own wait either way.
        t0 = time.monotonic()
        done, _ = wait([primary], timeout=self.hedge_delay_s())
        reqtrace.attribute("wire" if done else "hedge",
                           time.monotonic() - t0)
        if done:
            return primary.result()   # fast path: no hedge launched
        _HEDGED.inc()
        reqtrace.event("hedge_fired", owner=int(owner))
        hedge = pool.submit(hedge_call)
        pending = {primary, hedge}
        primary_err: Optional[BaseException] = None
        while pending:
            with reqtrace.stage("hedge"):
                done, pending = wait(pending,
                                     return_when=FIRST_COMPLETED)
            for fut in done:
                if fut is primary:
                    try:
                        result = primary.result()
                    except (StaleShardMapError, *self.RETRYABLE) as e:
                        primary_err = e
                        continue
                    if hedge in pending and hedge.cancel():
                        pending.discard(hedge)
                    _HEDGE_CANCELLED.inc()
                    reqtrace.event("hedge_loss", owner=int(owner))
                    return result
                # hedge future: never raises (returns None on failure)
                rows_wm = fut.result()
                if rows_wm is not None:
                    _HEDGE_WINS.inc()
                    reqtrace.event("hedge_win", owner=int(owner))
                    if primary in pending:
                        # the primary call cannot be recalled mid-
                        # flight; it dies at its own wire deadline
                        primary.cancel()
                        pending.discard(primary)
                        _HEDGE_CANCELLED.inc()
                        # the primary did not answer inside the hedge
                        # window AND lost the race: attribute the read
                        DEGRADED_READS.inc(mode="replica")
                        reqtrace.event("degraded", mode="replica")
                        # a lost race is a missed SLO: strike the
                        # primary's breaker NOW rather than when its
                        # abandoned call times out — a partitioned
                        # owner must stop collecting hung calls (and
                        # hedge-pool slots) after a few losses, and a
                        # merely-slow owner's next on-time answer
                        # resets the count anyway
                        self._note_failure(owner)
                    elif primary_err is not None:
                        DEGRADED_READS.inc(mode="replica")
                        reqtrace.event("degraded", mode="replica")
                    return rows_wm
        if isinstance(primary_err, StaleShardMapError):
            raise primary_err
        raise primary_err if primary_err is not None else (
            OwnerUnavailableError(fail_msg))

    def _retry_simple(self, method: str, policy: CallPolicy,
                      t_end: float, owner: int, call,
                      with_watermark: bool = True):
        last: Optional[BaseException] = None
        for attempt in range(policy.max_attempts):
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                break
            if not self._breaker(owner).allow():
                # fail fast against a known-partitioned owner: a
                # watermark probe or replica sync must not hang to its
                # deadline against a peer the breaker already condemned
                # (the caller's fallback — replica probes, deferred
                # sync — is the right response, and cheap)
                raise OwnerUnavailableError(
                    f"{method} to owner {owner}: breaker open")
            _RPC_CALLS.inc(method=method)
            try:
                rows, wm = call(
                    remaining / max(1, policy.max_attempts - attempt))
                return (rows, wm) if with_watermark else rows
            except StaleShardMapError:
                raise
            except self.RETRYABLE as e:
                last = e
                _RPC_FAILURES.inc(method=method)
                if isinstance(e, DeadlineExceededError):
                    _RPC_DEADLINE.inc(method=method)
                if attempt + 1 < policy.max_attempts:
                    _RPC_RETRIES.inc(method=method)
                    with reqtrace.stage("budget_wait"):
                        self._sleep(
                            min(self._backoff(attempt),
                                max(0.0, t_end - time.monotonic())))
        raise last if last is not None else DeadlineExceededError(
            f"{method} to owner {owner}: deadline budget spent")

    # ---- fused pull (ISSUE 18): one budget/hedge/breaker round per
    # fused call — the robustness machinery amortizes with the wire

    def supports_pull_multi(self) -> bool:
        return hasattr(self._inner, "pull_multi")

    def pull_multi(self, owner: int, requests,
                   map_version: Optional[int] = None,
                   replica: bool = False):
        """The fused LocalTransport.pull_multi contract with pull()'s
        full degraded ladder. The whole fused call gets ONE deadline
        budget, ONE hedge race, and ONE breaker verdict — n tables in
        a step no longer mean n chances to trip the breaker."""
        policy = self._policies["pull_multi"]
        t_end = time.monotonic() + policy.budget_s
        if replica:
            return self._retry_simple(
                "pull_multi", policy, t_end, owner,
                lambda to: self._pull_multi_once(
                    owner, requests, map_version, replica=True,
                    timeout_s=to),
                with_watermark=True)
        rec = reqtrace.get_recorder()
        d = rec.start("pull_multi", owner=int(owner),
                      fanin=len(requests), tag=self._trace_tag)
        last: Optional[BaseException] = None
        try:
            for attempt in range(policy.max_attempts):
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    break
                _RPC_CALLS.inc(method="pull_multi")
                try:
                    got = self._pull_multi_round(
                        owner, requests, map_version, remaining,
                        policy.max_attempts - attempt)
                    rec.finish(d, status=_diary_status(d))
                    return got
                except StaleShardMapError:
                    raise
                except self.RETRYABLE as e:
                    last = e
                    _RPC_FAILURES.inc(method="pull_multi")
                    if isinstance(e, DeadlineExceededError):
                        _RPC_DEADLINE.inc(method="pull_multi")
                    if attempt + 1 < policy.max_attempts:
                        _RPC_RETRIES.inc(method="pull_multi")
                        reqtrace.event("retry", attempt=attempt,
                                       error=type(e).__name__)
                        with reqtrace.stage("budget_wait"):
                            self._sleep(
                                min(self._backoff(attempt),
                                    max(0.0,
                                        t_end - time.monotonic())))
        except BaseException as e:
            rec.finish(d, status="error",
                       detail=f"{type(e).__name__}: {e}")
            raise
        DEGRADED_READS.inc(mode="blocked")
        err = last if last is not None else DeadlineExceededError(
            f"fused pull of {len(requests)} sub-pulls from owner "
            f"{owner}: deadline budget ({policy.budget_s:.3f}s) spent")
        rec.finish(d, status="error",
                   detail=f"{type(err).__name__}: {err}")
        raise err

    def _pull_multi_once(self, owner: int, requests, map_version,
                         replica: bool, timeout_s: Optional[float]):
        t0 = time.perf_counter()
        try:
            results, owner_wms = self._inner.pull_multi(
                owner, requests, map_version=map_version,
                replica=replica, **self._kw(timeout_s))
        except StaleShardMapError:
            self._note_success(owner)
            raise
        except self.RETRYABLE:
            self._note_failure(owner)
            raise
        self._note_success(owner)
        dt = time.perf_counter() - t0
        _RPC_LATENCY.observe(dt, method="pull_multi")
        if not replica:
            with self._lock:
                # ONE reservoir sample per FUSED call: the hedge delay
                # is p99-of-calls, and a fused call is one call — per
                # sub-table samples would multiply the window's weight
                # by the fan-in and self-inflate the derived delay as
                # coalescing grows
                self._pull_lat.append(dt)
            for (table, shard, _ids), (_rows, wm) in zip(requests,
                                                         results):
                self._note_wm(table, int(shard), int(wm))
        # the piggybacked watermarks are the OWNER'S primary set —
        # authoritative regardless of which namespace served this call
        for (table, shard), wm in owner_wms.items():
            self._note_wm(table, int(shard), int(wm))
        self._maybe_drain(owner)
        return results, owner_wms

    def _common_replicas(self, requests, exclude: int) -> List[int]:
        """Owners holding replicas of EVERY shard in the fused request
        — the only peers a fused call can hedge to wholesale."""
        common: Optional[set] = None
        for _t, shard, _ids in requests:
            reps = set(self._replicas_of(int(shard), exclude=exclude))
            common = reps if common is None else (common & reps)
            if not common:
                return []
        return sorted(common or ())

    def _pull_multi_replica_any(self, reps: List[int], requests,
                                map_version, timeout_s: float):
        """First replica owner whose fused answer is credible on EVERY
        sub-pull, or None. One stale sub-shard poisons the whole fused
        answer — partial acceptance would hand the tier a mix of fresh
        and beyond-bound rows under one watermark story."""
        for _ in range(2):
            for rep in reps:
                try:
                    results, owner_wms = self._pull_multi_once(
                        rep, requests, map_version, replica=True,
                        timeout_s=timeout_s)
                except (StaleShardMapError, *self.RETRYABLE):
                    continue
                credible = all(
                    wm + self.staleness_bound >= self.observed_wm(
                        table, int(shard))
                    for (table, shard, _ids), (_rows, wm)
                    in zip(requests, results)
                )
                if credible:
                    return results, owner_wms
        return None

    def _pull_multi_round(self, owner: int, requests, map_version,
                          remaining_s: float, attempts_left: int):
        breaker = self._breaker(owner)
        reps = self._common_replicas(requests, exclude=owner)
        attempt_timeout = remaining_s / max(1, attempts_left)
        if not breaker.allow():
            reqtrace.event("breaker_open", owner=int(owner))
            got = self._pull_multi_replica_any(
                reps, requests, map_version, attempt_timeout)
            if got is not None:
                DEGRADED_READS.inc(mode="replica")
                reqtrace.event("degraded", mode="replica")
                return got
            raise OwnerUnavailableError(
                f"owner {owner} breaker open and no credible replica "
                f"for fused pull of {len(requests)} sub-pulls")
        if not (self._hedge_enabled and reps):
            return self._pull_multi_once(
                owner, requests, map_version, replica=False,
                timeout_s=attempt_timeout)
        return self._hedged_race(
            owner,
            lambda: self._pull_multi_once(
                owner, requests, map_version, replica=False,
                timeout_s=attempt_timeout),
            lambda: self._pull_multi_replica_any(
                reps, requests, map_version, attempt_timeout),
            f"fused pull of {len(requests)} sub-pulls: primary "
            f"{owner} and replicas {reps} all failed")

    def watermark_multi(self, owner: int, pairs,
                        replica: bool = False) -> List[int]:
        """Batched freshness probe with shard_watermark()'s budget and
        breaker handling — one call per owner instead of one per
        (table, shard)."""
        policy = self._policies["watermark"]
        t_end = time.monotonic() + policy.budget_s

        def call(to):
            try:
                wms = self._inner.watermark_multi(
                    owner, pairs, replica=replica, **self._kw(to))
            except self.RETRYABLE:
                self._note_failure(owner)
                raise
            self._note_success(owner)
            return wms, 0

        wms, _ = self._retry_simple(
            "watermark", policy, t_end, owner, call)
        if not replica:
            for (table, shard), wm in zip(pairs, wms):
                self._note_wm(table, int(shard), int(wm))
        return [int(w) for w in wms]

    # ---- push: deadline budget + queue-behind-the-breaker ---------- #

    def push(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray, rows: np.ndarray, *, client_id: str,
             seq: int, map_version: Optional[int] = None,
             scale: float = 1.0, with_watermark: bool = False):
        policy = self._policies["push"]
        t_end = time.monotonic() + policy.budget_s
        breaker = self._breaker(owner)
        rec = reqtrace.get_recorder()
        d = rec.start("push", owner=int(owner), table=table,
                      shard=int(shard), tag=self._trace_tag)
        try:
            # ORDER FENCE: while this owner has a backlog, every new
            # push must join the queue behind it (a later seq applied
            # before an earlier one would make the earlier drain a
            # swallowed duplicate). A healthy owner drains the backlog
            # first.
            if self.queue is not None and self.queue.depth(owner):
                if not (breaker.allow() and self._drain_owner(owner)):
                    got = self._enqueue_or_raise(
                        owner, table, shard, local_ids, rows,
                        client_id, seq, map_version, scale,
                        with_watermark)
                    rec.finish(d, status="degraded",
                               detail="queued behind owner backlog")
                    return got
            last: Optional[BaseException] = None
            for attempt in range(policy.max_attempts):
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    break
                if not breaker.allow():
                    reqtrace.event("breaker_open", owner=int(owner))
                    last = OwnerUnavailableError(
                        f"owner {owner} breaker open")
                    break
                _RPC_CALLS.inc(method="push")
                t0 = time.perf_counter()
                try:
                    applied, wm = self._inner.push(
                        owner, table, shard, local_ids, rows,
                        client_id=client_id, seq=seq,
                        map_version=map_version, scale=scale,
                        with_watermark=True,
                        **self._kw(
                            remaining
                            / max(1, policy.max_attempts - attempt)))
                except StaleShardMapError:
                    self._note_success(owner)
                    raise
                except self.RETRYABLE as e:
                    last = e
                    self._note_failure(owner)
                    _RPC_FAILURES.inc(method="push")
                    if isinstance(e, DeadlineExceededError):
                        _RPC_DEADLINE.inc(method="push")
                    if attempt + 1 < policy.max_attempts:
                        _RPC_RETRIES.inc(method="push")
                        reqtrace.event("retry", attempt=attempt,
                                       error=type(e).__name__)
                        # SAME seq on the re-send: an ambiguous
                        # failure (the owner may have applied before
                        # the reply was lost) is absorbed by the
                        # store's fence
                        with reqtrace.stage("budget_wait"):
                            self._sleep(
                                min(self._backoff(attempt),
                                    max(0.0,
                                        t_end - time.monotonic())))
                    continue
                self._note_success(owner)
                _RPC_LATENCY.observe(time.perf_counter() - t0,
                                     method="push")
                self._note_wm(table, shard, int(wm))
                rec.finish(d, status="ok")
                return (applied, int(wm)) if with_watermark else applied
            # the breaker rung: park the push durably instead of
            # blocking the training step for the whole partition
            if self.queue is not None:
                got = self._enqueue_or_raise(
                    owner, table, shard, local_ids, rows, client_id,
                    seq, map_version, scale, with_watermark)
                rec.finish(d, status="degraded",
                           detail="queued behind open breaker")
                return got
            err = last if last is not None else DeadlineExceededError(
                f"push {table}/{shard} seq {seq}: deadline budget "
                f"spent")
            raise err
        except BaseException as e:
            rec.finish(d, status="error",
                       detail=f"{type(e).__name__}: {e}")
            raise

    def _enqueue_or_raise(self, owner, table, shard, local_ids, rows,
                          client_id, seq, map_version, scale,
                          with_watermark):
        entry = {
            "owner": int(owner), "table": table, "shard": int(shard),
            "ids": np.array(local_ids, np.int32, copy=True),
            "rows": np.array(rows, np.float32, copy=True),
            "client_id": client_id, "seq": int(seq),
            "map_version": map_version, "scale": float(scale),
        }
        if not self.queue.enqueue(entry):
            raise OwnerUnavailableError(
                f"owner {owner} partitioned and the push queue is full "
                f"({self.queue.max_entries}); refusing to buffer "
                "unboundedly")
        logger.warning(
            "push %s/%d seq %d queued behind owner %d's open breaker "
            "(%d parked)", table, shard, seq, owner,
            self.queue.depth(owner))
        # the ack is honest about what happened: applied=False (nothing
        # landed yet) with the highest watermark this client has seen —
        # the tier's write-through check (new_wm == prev_wm + 1) then
        # drops rather than patches, and the caller's training step
        # continues instead of blocking for the partition's duration
        wm = self.observed_wm(table, shard)
        return (False, wm) if with_watermark else False

    def _maybe_drain(self, owner: int) -> None:
        if self.queue is not None and self.queue.depth(owner):
            self._drain_owner(owner)

    def drain_queued(self, owner: Optional[int] = None) -> int:
        """Explicit reconnect drain (worker task boundaries, bench
        heal). Returns how many queued pushes landed."""
        if self.queue is None:
            return 0
        owners = ([owner] if owner is not None
                  else self.queue.owners_with_backlog())
        drained = 0
        for o in owners:
            before = self.queue.depth(o)
            self._drain_owner(o)
            drained += before - self.queue.depth(o)
        return drained

    def _drain_owner(self, owner: int) -> bool:
        """Re-send the owner's parked pushes in enqueue order under
        their ORIGINAL seqs (the fence absorbs any that actually
        landed before their ack was lost). Stops at the first failure
        — order is the contract. True = backlog fully drained."""
        if self.queue is None:
            return True
        with self._drain_lock:
            while True:
                entry = self.queue.peek(owner)
                if entry is None:
                    return True
                target = owner
                map_version = entry["map_version"]
                try:
                    self._inner.push(
                        target, entry["table"], entry["shard"],
                        entry["ids"], entry["rows"],
                        client_id=entry["client_id"], seq=entry["seq"],
                        map_version=map_version, scale=entry["scale"],
                        with_watermark=True,
                        **self._kw(self._policies["push"].budget_s))
                except StaleShardMapError:
                    # the map moved during the partition: re-route to
                    # the shard's CURRENT owner, version un-pinned (the
                    # store's residency check still protects us)
                    routed = self._reroute(entry)
                    if not routed:
                        return False
                except self.RETRYABLE:
                    self._note_failure(owner)
                    return False
                else:
                    self._note_success(owner)
                self.queue.pop_drained(owner)
                logger.debug(
                    "drained queued push %s/%d seq %d to owner %d",
                    entry["table"], entry["shard"], entry["seq"], target)

    def _reroute(self, entry: Dict[str, Any]) -> bool:
        if self._view_fn is None:
            return False
        try:
            view = self._view_fn()
            target = view.owner_of(int(entry["shard"]))
            self._inner.push(
                target, entry["table"], entry["shard"], entry["ids"],
                entry["rows"], client_id=entry["client_id"],
                seq=entry["seq"], map_version=None,
                scale=entry["scale"], with_watermark=True,
                **self._kw(self._policies["push"].budget_s))
            return True
        except (StaleShardMapError, *self.RETRYABLE):
            return False

    # ---- the rest of the contract: budgeted pass-through ----------- #

    def fetch_shard(self, owner: int, table: str,
                    shard: int) -> Dict[str, Any]:
        policy = self._policies["fetch_shard"]
        t_end = time.monotonic() + policy.budget_s

        def call(to):
            try:
                payload = self._inner.fetch_shard(
                    owner, table, shard, **self._kw(to))
            except self.RETRYABLE:
                self._note_failure(owner)
                raise
            self._note_success(owner)
            return payload, int(payload.get("wm", 0))

        payload, _ = self._retry_simple(
            "fetch_shard", policy, t_end, owner, call)
        return payload

    def fetch_delta(self, owner: int, table: str, shard: int,
                    since_wm: int) -> Optional[Dict[str, Any]]:
        policy = self._policies["fetch_delta"]
        t_end = time.monotonic() + policy.budget_s

        def call(to):
            try:
                delta = self._inner.fetch_delta(
                    owner, table, shard, since_wm, **self._kw(to))
            except self.RETRYABLE:
                self._note_failure(owner)
                raise
            self._note_success(owner)
            return delta, (int(delta["wm"]) if delta else 0)

        delta, _ = self._retry_simple(
            "fetch_delta", policy, t_end, owner, call)
        return delta

    def shard_watermark(self, owner: int, table: str, shard: int,
                        replica: bool = False) -> int:
        policy = self._policies["watermark"]
        t_end = time.monotonic() + policy.budget_s

        def call(to):
            try:
                wm = self._inner.shard_watermark(
                    owner, table, shard, replica=replica,
                    **self._kw(to))
            except self.RETRYABLE:
                self._note_failure(owner)
                raise
            self._note_success(owner)
            return int(wm), int(wm)

        wm, _ = self._retry_simple(
            "watermark", policy, t_end, owner, call)
        if not replica:
            self._note_wm(table, shard, wm)
        return wm

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if hasattr(self._inner, "close"):
            self._inner.close()


# ------------------------------------------------------------------ #
# standalone owner runner (the multi-process half of bench.py
# data_plane): serve a store built from a JSON spec, optionally keep
# replica copies synced from their primaries, write the bound port to
# a file the orchestrator watches.


def _runner_view(spec: Dict[str, Any]):
    from elasticdl_tpu.embedding import sharding

    tables = tuple(
        sharding.TableSpec(
            name=t["name"], vocab=int(t["vocab"]), dim=int(t["dim"]),
            seed=int(t.get("seed", 0)),
            init_scale=float(t.get("init_scale", 0.05)),
        )
        for t in spec["tables"]
    )
    return sharding.ShardMapView(
        version=int(spec.get("version", 1)),
        num_shards=int(spec["num_shards"]),
        owners=tuple(int(o) for o in spec["owners"]),
        tables=tables,
        replicas=tuple(tuple(int(x) for x in r)
                       for r in spec.get("replicas", [])),
    )


def run_owner(spec: Dict[str, Any], stop: Optional[threading.Event] = None):
    """Serve one owner process per the spec (see bench.py data_plane
    for the producing side). Blocks until `stop` (or SIGTERM)."""
    from elasticdl_tpu.embedding.store import EmbeddingShardStore

    owner = int(spec["owner"])
    view = _runner_view(spec)
    store = EmbeddingShardStore(owner, device=bool(spec.get("device")))
    store.attach(view)
    server = EmbeddingDataServer(store, shm=bool(spec.get("shm", True)))
    port = server.start(int(spec.get("port", 0)))
    port_file = spec.get("port_file")
    if port_file:
        tmp = port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, port_file)
    stop = stop or threading.Event()

    # replica-sync loop: this owner holds replica copies of shards
    # whose primaries live at peer_addrs — keep them fresh by delta so
    # a partitioned primary's clients can hedge here
    my_replicas = [
        s for s in range(view.num_shards)
        if owner in view.replicas_of(s)
    ]
    sync_s = float(spec.get("replica_sync_s", 0.05))
    peer = GrpcTransport(
        {int(k): v for k, v in (spec.get("peer_addrs") or {}).items()})

    def sync_loop():
        while not stop.is_set():
            for s in my_replicas:
                for t in view.tables:
                    try:
                        store.sync_replica_from(
                            peer, view.owner_of(s), t.name, s)
                    except Exception:
                        logger.debug(
                            "replica sync %s/%d deferred", t.name, s,
                            exc_info=True)
            stop.wait(sync_s)

    if my_replicas:
        threading.Thread(
            target=sync_loop, name="emb-replica-sync", daemon=True
        ).start()
    try:
        stop.wait()
    finally:
        server.stop()
    return port


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        description="standalone embedding data-plane owner process")
    parser.add_argument("--serve", metavar="SPEC_JSON", required=True,
                        help="owner spec file (bench.py data_plane "
                        "writes these)")
    args = parser.parse_args(argv)
    with open(args.serve) as f:
        spec = json.load(f)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    run_owner(spec, stop)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
