"""Shard math + the master-owned, journal-durable embedding shard map.

Id -> shard: `shard_of(id) = id % num_shards` on the hashed id space —
the reference's `id % ps_num` (elasticdl/python/worker/ps_client.py).
Vocab ids in this repo are already hash-bucketed at preprocessing time
(api/preprocessing.hashing), so the modulo IS `hash(id) % num_shards`
with the identity as the final mix, and it buys what a fresh hash could
not: a dense per-shard row space (`local = id // num_shards`) that the
fused gather / scatter-add kernels can address contiguously.

Shard -> owner: the master assigns shards to workers round-robin and
rebalances on world change with MINIMAL MOVEMENT (`plan_moves`): a shard
whose owner survives stays put; only shards stranded on dead workers or
pulled for balance migrate. Every map transition is committed through
the control-plane journal (`emb_shard_map` / `emb_reshard_begin` /
`emb_reshard_commit` records) so a master crash mid-resharding replays
to a CONSISTENT map: a begun-but-uncommitted resharding rolls back to
the pre-move assignment and flags `reshard_interrupted`, which clients
treat as "conservatively requeue in-flight pushes" (exactly-once is
preserved by the stores' per-client sequence fencing either way).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_MAP_VERSION = _reg.gauge(
    "edl_embedding_shard_map_version", "current embedding shard map version")
_RESHARDS = _reg.counter(
    "edl_embedding_reshards_total", "committed resharding transitions")
_SHARDS_MOVED = _reg.counter(
    "edl_embedding_shards_moved_total", "shard migrations committed")
_RESHARD_ROLLBACKS = _reg.counter(
    "edl_embedding_reshard_rollbacks_total",
    "reshardings rolled back at journal replay (master died mid-move)")


def shard_of(ids: Any, num_shards: int):
    """Owning shard per id (vectorized). ids are hashed-vocab ints; the
    modulo is the reference's `id % ps_num` placement."""
    return np.asarray(ids) % num_shards


def local_rows(ids: Any, num_shards: int):
    """Row index inside the owning shard's dense local table."""
    return np.asarray(ids) // num_shards


def shard_row_count(padded_vocab: int, num_shards: int) -> int:
    """Rows every shard allocates (uniform: shards are interchangeable
    migration units; the ceil padding is dead rows on the tail shards)."""
    return -(-padded_vocab // num_shards)


@dataclass(frozen=True)
class TableSpec:
    """One tier table: geometry + deterministic init.

    `vocab` is the PADDED row count (ops/embedding.padded_vocab — the
    same geometry rule checkpoints bake). `seed` makes shard creation
    reproducible on any owner: a shard materialized fresh is bit-identical
    wherever it is built, so bootstrap needs no transfer."""

    name: str
    vocab: int
    dim: int
    seed: int = 0
    init_scale: float = 0.05

    def to_wire(self) -> Dict[str, Any]:
        return {"name": self.name, "vocab": self.vocab, "dim": self.dim,
                "seed": self.seed, "init_scale": self.init_scale}

    @staticmethod
    def from_wire(d: Dict[str, Any]) -> "TableSpec":
        return TableSpec(
            name=str(d["name"]), vocab=int(d["vocab"]), dim=int(d["dim"]),
            seed=int(d.get("seed", 0)),
            init_scale=float(d.get("init_scale", 0.05)),
        )


@dataclass(frozen=True)
class ShardMapView:
    """An immutable snapshot of the shard map a client operates under.

    `version` fences the data plane: pulls/pushes carry it, and an owner
    serving a different version rejects the call so a client can never
    write through a stale map (the resharding exactly-once contract)."""

    version: int
    num_shards: int
    owners: Tuple[int, ...]                 # shard id -> owner worker id
    tables: Tuple[TableSpec, ...] = ()
    resharding: bool = False                # a move plan is in flight
    # shard id -> READ-replica worker ids (possibly empty): replicas
    # serve pulls within the staleness bound; writes stay primary-only.
    # Committed next to `owners` in the same journal records, so a
    # successor master replays the replica map identically.
    replicas: Tuple[Tuple[int, ...], ...] = ()
    # owner ADDRESS BOOK (ISSUE 15): (worker id, data-plane endpoint)
    # pairs for workers serving an embedding/data_plane.py endpoint —
    # sourced from registration, ridden on the shard-map response, and
    # adopted by GrpcTransport.update_addresses at every client refresh.
    # Empty for local-transport deployments.
    addrs: Tuple[Tuple[int, str], ...] = ()
    # ultra-hot id set (ISSUE 20): sketch-head ids the layout controller
    # promoted to worker-replicated status. Clients PIN these rows in
    # their hot-row cache (refreshed through the same watermark fence as
    # any cached row); demotion shrinks the tuple. Journaled beside the
    # map so a successor master replays the same promotion state.
    hot_ids: Tuple[int, ...] = ()

    def owner_of(self, shard: int) -> int:
        return self.owners[shard]

    def replicas_of(self, shard: int) -> Tuple[int, ...]:
        return self.replicas[shard] if shard < len(self.replicas) else ()

    def shards_owned_by(self, owner: int) -> List[int]:
        return [s for s, o in enumerate(self.owners) if o == owner]

    def shards_replicated_on(self, owner: int) -> List[int]:
        return [s for s, r in enumerate(self.replicas) if owner in r]


@dataclass(frozen=True)
class ShardMove:
    """One planned migration: shard `shard` leaves `src` for `dst`.
    `src < 0` means the donor is DEAD — the recipient restores the shard
    from the tier checkpoint (or re-materializes from the table seed if
    no checkpoint exists) instead of a live transfer.

    `kind` (ISSUE 20) widens the move vocabulary for layout actions:

    - ``"move"``  — the classic cross-owner migration above;
    - ``"split"`` — `shard` is a CHILD id under the DOUBLED shard count;
      `parent` names the parent shard whose resident rows the owner
      re-interleaves locally (store.split_resident) — no cross-owner
      transfer, but the recipient still confirms through the same
      two-phase handshake so a crash mid-split rolls back;
    - ``"merge"`` — `shard` is a PARENT id under the HALVED count; the
      owner folds its two co-resident children back together.

    Defaulted fields keep `from_wire` compatible with pre-split journal
    records."""

    shard: int
    src: int
    dst: int
    kind: str = "move"
    parent: int = -1

    def to_wire(self) -> Dict[str, int]:
        out = {"shard": self.shard, "src": self.src, "dst": self.dst}
        if self.kind != "move":
            out["kind"] = self.kind
            out["parent"] = self.parent
        return out

    @staticmethod
    def from_wire(d: Dict[str, Any]) -> "ShardMove":
        return ShardMove(
            int(d["shard"]), int(d["src"]), int(d["dst"]),
            kind=str(d.get("kind", "move")), parent=int(d.get("parent", -1)),
        )


def assign_round_robin(num_shards: int, owners: Sequence[int]) -> List[int]:
    """Initial placement: shard s -> owners[s % len(owners)]."""
    owners = sorted(owners)
    if not owners:
        raise ValueError("embedding tier needs at least one owner")
    return [owners[s % len(owners)] for s in range(num_shards)]


def plan_moves(
    current: Sequence[int], new_owners: Sequence[int],
    dead: Sequence[int] = (),
    prefer: Optional[Dict[int, int]] = None,
) -> List[ShardMove]:
    """Minimal-movement rebalance of `current` (shard -> owner) onto the
    surviving/new owner set.

    Invariants: (1) a shard whose owner survives moves only if the
    balance demands it; (2) stranded shards (leaving owner) are assigned
    first, to the least-loaded survivors; (3) the result is balanced to
    within one shard per owner. Deterministic (sorted owner order) so
    every process computing the same inputs plans the same moves.

    `dead` names owners KNOWN dead (reaped by membership): their shards
    get ``src = -1`` (restore-from-checkpoint moves). An owner merely
    LEAVING the set (planned shrink) stays the live donor — its shards
    transfer device-to-device before it goes; if it turns out
    unreachable anyway, reshard.apply_moves falls back to the
    checkpoint/seed restore path per shard.

    `prefer` maps a stranded shard to the survivor that should take it
    when the balance allows — the replica-promotion hint (ISSUE 13): a
    dead owner's shard lands on a worker already holding a synced read
    replica, so recovery installs by promotion instead of copy."""
    new_owners = sorted(set(new_owners))
    if not new_owners:
        raise ValueError("cannot rebalance onto an empty owner set")
    dead = set(dead)
    prefer = prefer or {}
    n = len(current)
    target_cap = -(-n // len(new_owners))
    load: Dict[int, int] = {o: 0 for o in new_owners}
    keep: List[Tuple[int, int]] = []      # (shard, surviving owner)
    stranded: List[Tuple[int, int]] = []  # (shard, donor or -1)
    for s, o in enumerate(current):
        if o in load:
            keep.append((s, o))
        else:
            stranded.append((s, -1 if o in dead else o))
    # survivors keep up to the balanced capacity; overflow shards move
    moves: List[ShardMove] = []
    overflow: List[Tuple[int, int]] = []
    for s, o in keep:
        if load[o] < target_cap:
            load[o] += 1
        else:
            overflow.append((s, o))
    def least_loaded() -> int:
        return min(new_owners, key=lambda o: (load[o], o))
    for s, src in stranded:
        pref = prefer.get(s)
        if pref is not None and pref in load and load[pref] < target_cap:
            dst = pref
        else:
            dst = least_loaded()
        load[dst] += 1
        moves.append(ShardMove(shard=s, src=src, dst=dst))
    for s, src in overflow:
        dst = least_loaded()
        load[dst] += 1
        moves.append(ShardMove(shard=s, src=src, dst=dst))
    return moves


def assign_replicas(
    owners: Sequence[int], pool: Sequence[int],
    replica_count: Any,
    current: Sequence[Sequence[int]] = (),
) -> List[List[int]]:
    """Per-shard read-replica assignment: up to `replica_count` workers
    per shard drawn from `pool`, never the shard's own primary,
    deterministic (sorted pool, shard-rotated) so every process planning
    from the same inputs lands the same map. Replicas already holding
    the shard (`current`, the pre-transition assignment) are kept when
    still eligible — a synced copy is worth more than a balanced one.

    `replica_count` is an int (uniform fan-out, the PR 13 contract) or a
    per-shard sequence of ints (ISSUE 20: the layout controller's
    skew-adaptive fan-out — hot shards get more read copies, cold
    shards drop to primary-only)."""
    pool = sorted(set(pool))
    if isinstance(replica_count, (list, tuple)):
        per_shard = [int(c) for c in replica_count]
        if len(per_shard) != len(owners):
            raise ValueError(
                f"per-shard replica counts ({len(per_shard)}) must match "
                f"num_shards ({len(owners)})"
            )
    else:
        per_shard = [int(replica_count)] * len(owners)
    out: List[List[int]] = []
    for s, p in enumerate(owners):
        cands = [o for o in pool if o != p]
        rc = min(per_shard[s], len(cands))
        if rc <= 0:
            out.append([])
            continue
        prior = list(current[s]) if s < len(current) else []
        kept = [o for o in prior if o in cands][:rc]
        rest = [o for o in cands if o not in kept]
        start = s % len(rest) if rest else 0
        rot = rest[start:] + rest[:start]
        out.append(kept + rot[: rc - len(kept)])
    return out


def apply_moves_to_assignment(
    current: Sequence[int], moves: Sequence[ShardMove],
) -> List[int]:
    out = list(current)
    for m in moves:
        out[m.shard] = m.dst
    return out


class ShardMapOwner:
    """The master's authoritative shard map, durable through the journal.

    Lifecycle: `bootstrap(owners)` assigns the initial map (journaled as
    `emb_shard_map`); `begin_resharding(new_owners)` plans minimal moves
    and journals `emb_reshard_begin` (the map version bumps and the view
    flips `resharding=True` — clients hold pushes or carry the fence);
    recipients confirm installed shards via `confirm_moves` (the servicer
    RPC lands here) and when the plan is fully confirmed the owner
    journals `emb_reshard_commit` and the new map becomes plain current.

    Crash semantics: replay of a begin WITHOUT its commit rolls back to
    the pre-move map (`restore_from_replay`) and marks the replayed state
    `reshard_interrupted` — the successor master re-plans against the
    live membership, and clients requeue unconfirmed pushes (store-side
    sequence fencing dedupes any that actually landed).

    Lock order: _lock -> journal queue (the journal never calls back).
    The ack-after-fsync discipline matches dispatcher/membership: journal
    commits are enqueued inside `_lock` and waited AFTER release, before
    the transition is acknowledged to any caller.
    """

    def __init__(self, num_shards: int, journal=None,
                 replica_count: int = 0):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        if replica_count < 0:
            raise ValueError("replica_count must be >= 0")
        self.num_shards = num_shards
        self.replica_count = replica_count
        self._journal = journal
        self._lock = threading.Lock()
        # tables enter ONLY via register_table (journaled) or
        # restore_from_replay — a ctor shortcut would silently skip the
        # journal and lose the table specs at master takeover
        self._tables: Dict[str, TableSpec] = {}  # guarded_by: _lock
        self._owners: List[int] = []             # guarded_by: _lock
        self._replicas: List[List[int]] = []     # guarded_by: _lock
        self._hot_ids: List[int] = []            # guarded_by: _lock
        self._version = 0                        # guarded_by: _lock
        # per-shard replica targets, set ONLY by the layout controller
        # (None = uniform self.replica_count everywhere)
        self._replica_counts: Optional[List[int]] = None  # guarded_by: _lock
        self._pending: Optional[Dict[str, Any]] = None  # guarded_by: _lock
        self._interrupted = False                # guarded_by: _lock
        self._listeners: List[Callable[[ShardMapView], None]] = []

    # -------------------------------------------------------------- #
    # construction / recovery

    def restore_from_replay(self, state) -> None:
        """Adopt the journal's replayed map (master takeover; `state` is
        a master/journal.py EmbeddingState). A mid-flight resharding was
        already rolled back by the replay; the `reshard_interrupted`
        flag survives so `view()` advertises it until the next committed
        transition."""
        with self._lock:
            self.num_shards = state.num_shards or self.num_shards
            self._owners = list(state.owners)
            self._replicas = [
                list(r) for r in getattr(state, "replicas", [])
            ]
            self._hot_ids = [
                int(i) for i in getattr(state, "hot_ids", [])
            ]
            counts = getattr(state, "replica_counts", None)
            self._replica_counts = (
                [int(c) for c in counts] if counts else None
            )
            self._version = state.version
            self._tables = {
                t["name"]: TableSpec.from_wire(t) for t in state.tables
            }
            self._interrupted = state.reshard_interrupted
            version = self._version
        if state.reshard_interrupted:
            _RESHARD_ROLLBACKS.inc()
            logger.warning(
                "embedding shard map recovered MID-RESHARDING: rolled back "
                "to committed map v%d; clients must requeue in-flight "
                "pushes (sequence fencing dedupes re-sends)", state.version,
            )
        _MAP_VERSION.set(version)

    # -------------------------------------------------------------- #

    def add_listener(self, fn: Callable[[ShardMapView], None]) -> None:
        """Called with the new view after every committed transition
        (exceptions swallowed — listeners are advisory)."""
        self._listeners.append(fn)

    def register_table(self, spec: TableSpec) -> None:
        commit = None
        with self._lock:
            if spec.name in self._tables:
                if self._tables[spec.name] != spec:
                    raise ValueError(
                        f"table {spec.name!r} already registered with a "
                        "different spec"
                    )
                return
            self._tables[spec.name] = spec
            if self._journal is not None:
                commit = self._journal.append("emb_table", **spec.to_wire())
        if commit is not None:
            commit.wait()

    def bootstrap(self, owners: Sequence[int]) -> ShardMapView:
        """First placement (idempotent: re-bootstrapping with a live map
        is a no-op returning the current view)."""
        commit = None
        with self._lock:
            if self._owners:
                return self._view_locked()
            self._owners = assign_round_robin(self.num_shards, owners)
            self._replicas = assign_replicas(
                self._owners, sorted(set(owners)), self.replica_count)
            self._version = 1
            self._interrupted = False
            if self._journal is not None:
                commit = self._journal.append(
                    "emb_shard_map", version=self._version,
                    num_shards=self.num_shards, owners=list(self._owners),
                    replicas=[list(r) for r in self._replicas],
                )
            view = self._view_locked()
        if commit is not None:
            # ack-after-fsync: the map is not served before it is durable
            commit.wait()
        _MAP_VERSION.set(view.version)
        self._notify(view)
        return view

    # -------------------------------------------------------------- #
    # resharding

    def begin_resharding(
        self, new_owners: Sequence[int], dead: Sequence[int] = (),
    ) -> Tuple[ShardMapView, List[ShardMove]]:
        """Plan minimal moves onto `new_owners` and journal the intent.
        Returns (pending view, moves). No-op (current view, []) when the
        assignment is already exactly servable by `new_owners`."""
        commit = None
        with self._lock:
            if not self._owners:
                raise RuntimeError("begin_resharding before bootstrap")
            if self._pending is not None:
                raise RuntimeError(
                    "resharding already in flight (version "
                    f"{self._pending['version']})"
                )
            # replica-promotion preference: a dead owner's shard goes to
            # a surviving replica holder when the balance allows — the
            # recipient promotes its synced copy instead of copying
            alive = set(new_owners)
            prefer: Dict[int, int] = {}
            for s, o in enumerate(self._owners):
                if o in alive:
                    continue
                for r in (self._replicas[s]
                          if s < len(self._replicas) else []):
                    if r in alive:
                        prefer[s] = r
                        break
            moves = plan_moves(self._owners, new_owners, dead, prefer)
            if not moves:
                return self._view_locked(), []
            version = self._version + 1
            new_assignment = apply_moves_to_assignment(self._owners, moves)
            new_replicas = assign_replicas(
                new_assignment, sorted(alive),
                (self._replica_counts if self._replica_counts is not None
                 else self.replica_count),
                current=self._replicas,
            )
            self._pending = {
                "version": version,
                "moves": moves,
                "confirmed": set(),
                "prior_owners": list(self._owners),
                "prior_replicas": [list(r) for r in self._replicas],
            }
            self._owners = new_assignment
            self._replicas = new_replicas
            self._version = version
            if self._journal is not None:
                commit = self._journal.append(
                    "emb_reshard_begin", version=version,
                    num_shards=self.num_shards,
                    owners=list(self._owners),
                    replicas=[list(r) for r in self._replicas],
                    moves=[m.to_wire() for m in moves],
                )
            view = self._view_locked()
        if commit is not None:
            commit.wait()
        tracing.event(
            "embedding.reshard_begin", version=view.version,
            moves=len(moves),
        )
        logger.warning(
            "embedding resharding v%d: %d shard move(s) planned",
            view.version, len(moves),
        )
        self._notify(view)
        return view, moves

    def confirm_moves(
        self, version: int, shard_ids: Sequence[int],
    ) -> bool:
        """A recipient installed these shards (servicer RPC). Returns
        True when accepted (version matches the in-flight plan; an
        already-confirmed shard is idempotent). The plan commits — one
        `emb_reshard_commit` journal record, acked after fsync — when
        every planned move is confirmed."""
        commit = None
        committed_view = None
        with self._lock:
            p = self._pending
            if p is None or p["version"] != version:
                # a stale confirm (pre-crash, or re-sent after commit):
                # harmless if the map already moved past it
                return p is None and version <= self._version
            p["confirmed"].update(int(s) for s in shard_ids)
            planned = {m.shard for m in p["moves"]}
            if planned <= p["confirmed"]:
                self._pending = None
                self._interrupted = False
                if self._journal is not None:
                    commit = self._journal.append(
                        "emb_reshard_commit", version=version,
                    )
                committed_view = self._view_locked()
                moved = len(planned)
        if commit is not None:
            commit.wait()
        if committed_view is not None:
            _RESHARDS.inc()
            _SHARDS_MOVED.inc(moved)
            _MAP_VERSION.set(committed_view.version)
            tracing.event(
                "embedding.reshard_commit", version=version, moves=moved,
            )
            logger.warning(
                "embedding resharding v%d COMMITTED (%d shard(s) moved)",
                version, moved,
            )
            self._notify(committed_view)
        return True

    def pending_moves(self) -> List[ShardMove]:
        with self._lock:
            return list(self._pending["moves"]) if self._pending else []

    # -------------------------------------------------------------- #
    # layout actions — driven by master/layout_controller.py (ISSUE 20).
    # edl-lint EDL503 flags calls to these from anywhere else: ad-hoc
    # layout mutation bypasses the cost gate, the cooldowns, and the
    # journaled decision history a master takeover replays.

    def update_replicas(
        self, replica_counts: Sequence[int], pool: Sequence[int],
    ) -> ShardMapView:
        """Re-fan replica assignments to per-shard targets (single
        phase: replicas are pull-only, so no exactly-once fence is
        needed — the version bump routes clients, and a pull landing on
        a not-yet-installed replica falls back to the primary through
        the existing degraded ladder). Journaled as `emb_replica_map`;
        the targets stick across later reshardings until the controller
        changes them again."""
        commit = None
        with self._lock:
            if not self._owners:
                raise RuntimeError("update_replicas before bootstrap")
            if self._pending is not None:
                raise RuntimeError(
                    "update_replicas during in-flight resharding"
                )
            counts = [max(0, int(c)) for c in replica_counts]
            if len(counts) != self.num_shards:
                raise ValueError(
                    f"replica_counts has {len(counts)} entries for "
                    f"{self.num_shards} shards"
                )
            self._replica_counts = counts
            self._replicas = assign_replicas(
                self._owners, sorted(set(pool)), counts,
                current=self._replicas,
            )
            self._version += 1
            if self._journal is not None:
                commit = self._journal.append(
                    "emb_replica_map", version=self._version,
                    replicas=[list(r) for r in self._replicas],
                    replica_counts=list(counts),
                )
            view = self._view_locked()
        if commit is not None:
            commit.wait()
        _MAP_VERSION.set(view.version)
        tracing.event("embedding.replica_map", version=view.version)
        self._notify(view)
        return view

    def set_hot_ids(self, ids: Sequence[int]) -> ShardMapView:
        """Publish the ultra-hot id set (promotion/demotion is the
        controller's call; this just makes it durable and visible).
        Single phase for the same reason as `update_replicas`: hot-id
        pinning only changes what clients CACHE, never where writes
        land."""
        commit = None
        with self._lock:
            if self._pending is not None:
                raise RuntimeError("set_hot_ids during in-flight resharding")
            hot = sorted({int(i) for i in ids})
            if hot == self._hot_ids:
                return self._view_locked()
            self._hot_ids = hot
            self._version += 1
            if self._journal is not None:
                commit = self._journal.append(
                    "emb_hot_ids", version=self._version,
                    hot_ids=list(hot),
                )
            view = self._view_locked()
        if commit is not None:
            commit.wait()
        _MAP_VERSION.set(view.version)
        self._notify(view)
        return view

    def begin_split(self) -> Tuple[ShardMapView, List[ShardMove]]:
        """Double the shard count: every parent shard s splits in place
        into children s and s + old_n on the SAME owner (id g lands in
        shard g % 2n, which is s or s + n for every g that was in s —
        no rows change hosts, so the 'move' is a local re-key). Runs
        through the ordinary two-phase begin→confirm→commit fence:
        owners confirm the child ids once `store.split_resident` has
        re-keyed rows, watermarks, and delta logs. Replicas are dropped
        (their keyspace just changed); the controller re-fans them out
        as a separate, cost-gated action."""
        commit = None
        with self._lock:
            if not self._owners:
                raise RuntimeError("begin_split before bootstrap")
            if self._pending is not None:
                raise RuntimeError("split during in-flight resharding")
            old_n = self.num_shards
            new_n = old_n * 2
            version = self._version + 1
            new_owners = list(self._owners) * 2
            moves = []
            for s, o in enumerate(self._owners):
                moves.append(ShardMove(s, o, o, kind="split", parent=s))
                moves.append(
                    ShardMove(s + old_n, o, o, kind="split", parent=s))
            self._pending = {
                "version": version,
                "moves": moves,
                "confirmed": set(),
                "prior_owners": list(self._owners),
                "prior_replicas": [list(r) for r in self._replicas],
                "prior_num_shards": old_n,
            }
            self.num_shards = new_n
            self._owners = new_owners
            self._replicas = [[] for _ in range(new_n)]
            self._replica_counts = None
            self._version = version
            if self._journal is not None:
                commit = self._journal.append(
                    "emb_reshard_begin", version=version,
                    num_shards=new_n,
                    owners=list(self._owners),
                    replicas=[list(r) for r in self._replicas],
                    moves=[m.to_wire() for m in moves],
                )
            view = self._view_locked()
        if commit is not None:
            commit.wait()
        tracing.event(
            "embedding.split_begin", version=view.version,
            num_shards=view.num_shards,
        )
        logger.warning(
            "embedding shard SPLIT v%d: %d -> %d shards",
            view.version, view.num_shards // 2, view.num_shards,
        )
        self._notify(view)
        return view, moves

    def begin_merge(self) -> Tuple[ShardMapView, List[ShardMove]]:
        """Halve the shard count: children s and s + new_n fold back
        into parent s. Only legal when every child pair is co-owned
        (the inverse of a split that never re-homed a child) — the
        merge is then a local interleave with no cross-host copy; the
        controller suppresses the action otherwise rather than paying
        a migration it can't cost-model. Child delta logs are cleared
        by `store.merge_resident` (entry keys don't compose across the
        fold), so replicas full-resync — which is why replicas are
        dropped here too."""
        commit = None
        with self._lock:
            if not self._owners:
                raise RuntimeError("begin_merge before bootstrap")
            if self._pending is not None:
                raise RuntimeError("merge during in-flight resharding")
            old_n = self.num_shards
            if old_n % 2 != 0 or old_n < 2:
                raise ValueError(f"cannot merge {old_n} shards")
            new_n = old_n // 2
            for s in range(new_n):
                if self._owners[s] != self._owners[s + new_n]:
                    raise ValueError(
                        f"children {s} and {s + new_n} live on different "
                        "owners; merge requires co-owned pairs"
                    )
            version = self._version + 1
            new_owners = self._owners[:new_n]
            moves = [
                ShardMove(s, new_owners[s], new_owners[s],
                          kind="merge", parent=s)
                for s in range(new_n)
            ]
            self._pending = {
                "version": version,
                "moves": moves,
                "confirmed": set(),
                "prior_owners": list(self._owners),
                "prior_replicas": [list(r) for r in self._replicas],
                "prior_num_shards": old_n,
            }
            self.num_shards = new_n
            self._owners = new_owners
            self._replicas = [[] for _ in range(new_n)]
            self._replica_counts = None
            self._version = version
            if self._journal is not None:
                commit = self._journal.append(
                    "emb_reshard_begin", version=version,
                    num_shards=new_n,
                    owners=list(self._owners),
                    replicas=[list(r) for r in self._replicas],
                    moves=[m.to_wire() for m in moves],
                )
            view = self._view_locked()
        if commit is not None:
            commit.wait()
        tracing.event(
            "embedding.merge_begin", version=view.version,
            num_shards=view.num_shards,
        )
        logger.warning(
            "embedding shard MERGE v%d: %d -> %d shards",
            view.version, view.num_shards * 2, view.num_shards,
        )
        self._notify(view)
        return view, moves

    # -------------------------------------------------------------- #

    def view(self) -> ShardMapView:
        with self._lock:
            return self._view_locked()

    def _view_locked(self) -> ShardMapView:  # holds: _lock
        return ShardMapView(
            version=self._version,
            num_shards=self.num_shards,
            owners=tuple(self._owners),
            tables=tuple(self._tables.values()),
            resharding=self._pending is not None or self._interrupted,
            replicas=tuple(tuple(r) for r in self._replicas),
            hot_ids=tuple(self._hot_ids),
        )

    def _notify(self, view: ShardMapView) -> None:
        for fn in self._listeners:
            try:
                fn(view)
            except Exception:
                logger.exception("shard-map listener failed (ignored)")


