"""The worker side of the embedding tier: per-batch deduped, per-shard
batched pull -> compute -> push.

Reference parity: elasticdl/python/worker/worker.py's
pull_embedding_vectors / push_gradients — but where the reference paid
one RPC pair per PS pod per minibatch with the FULL id stream, this
client (1) DEDUPES the batch's ids once (`np.unique`), (2) groups the
unique ids by owning shard with vectorized modulo math, (3) issues ONE
batched call per shard (never per row — edl-lint EDL206 polices the
per-row anti-pattern), and (4) sums duplicate gradients client-side
(sorted segment reduce) so the owner applies one deduped scatter-add.
On skewed (production recsys) id distributions the deduped stream is a
fraction of the raw batch — `edl_embedding_dedupe_ratio` measures it.

Request lengths are padded to power-of-two buckets (sentinel id -1) so
the owner's jitted pull/apply programs stay in a handful of
compile-cache entries per table instead of recompiling per batch shape.

Exactly-once pushes: every `push()` call takes one sequence number and
sends it to every touched shard; any retry — lost ack, stale shard map
mid-resharding, owner handoff — re-sends the SAME seq, and the store's
per-(shard, client) watermark turns duplicates into acked no-ops. A
push returns only when every shard acked, so a client that returns from
`push()` KNOWS the update landed exactly once.

The serving-grade READ path (ISSUE 13) stacks three switchable layers
on top, each taking traffic off the owner RPC:

1. **hot-row cache** (`cache_rows > 0`): a worker-local staleness-
   bounded LRU over unique ids (embedding/cache.py) consulted before
   any shard call — only misses travel; responses carry the shard push
   watermark that fences freshness, the worker's own pushes write
   through, and any shard-map change drops the cache whole.
2. **read replicas** (`read_replicas=True` + a master map carrying
   replica assignments): misses fan out to the least-loaded replica of
   each shard; a replica answering from further back than the staleness
   bound is rejected and the primary serves. Writes NEVER go to
   replicas.
3. **pull pipeline** (`EmbeddingPullPipeline`): step N+1's pull issued
   while step N computes — `get()` blocks only on what compute did not
   already cover, which is the only part that still bills the goodput
   ledger's `emb_pull_blocked`. `drain()` hands back in-flight id
   batches on rescale/reshard so they re-issue under the fresh map.

tier_stats() reports the two latencies the split creates: `emb_pull_
p99_ms` (owner RPC rounds only — what the embedding_pull_p99 alert
pages on) vs `emb_read_p99_ms` (effective reads, cache included).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.embedding import sharding
from elasticdl_tpu.embedding.cache import HotRowCache
from elasticdl_tpu.embedding.sketch import DecayingSpaceSaving, SpaceSaving
from elasticdl_tpu.embedding.store import StaleShardMapError
from elasticdl_tpu.embedding.transport import (
    DEGRADED_READS,
    OwnerUnavailableError,
)
from elasticdl_tpu.observability import reqtrace
from elasticdl_tpu.observability.registry import (
    default_registry,
    quantile_sorted,
)

logger = default_logger(__name__)

_reg = default_registry()
_PULL_S = _reg.histogram(
    "edl_embedding_pull_seconds", "client pull wall time per batch")
_PUSH_S = _reg.histogram(
    "edl_embedding_push_seconds", "client push wall time per batch")
_PULL_IDS = _reg.counter(
    "edl_embedding_pull_ids_total", "raw ids in pulled batches")
_PULL_UNIQUE = _reg.counter(
    "edl_embedding_pull_unique_ids_total", "deduped ids actually requested")
_PUSH_IDS = _reg.counter(
    "edl_embedding_push_ids_total", "raw ids in pushed batches")
_PUSH_SENT = _reg.counter(
    "edl_embedding_push_ids_sent_total", "deduped ids actually sent")
_DEDUPE_RATIO = _reg.gauge(
    "edl_embedding_dedupe_ratio",
    "ids sent / ids in batch, most recent push (1.0 = no duplicates)")
_REFRESHES = _reg.counter(
    "edl_embedding_map_refreshes_total",
    "shard-map refreshes forced by stale-map/owner errors")
_RETRIES = _reg.counter(
    "edl_embedding_push_retries_total",
    "push rounds re-sent after an error (seq fence dedupes)")
_SHARD_CALLS = _reg.histogram(
    "edl_embedding_shard_batch_ids",
    "deduped ids per per-shard call (batching effectiveness)")
# skew telemetry (ISSUE 11): the measurement ground for the hot-row
# cache / read replicas (ROADMAP 1) — docs/observability.md "Embedding
# skew telemetry"
_HOT_SHARE = _reg.gauge(
    "edl_embedding_hot_id_share",
    "guaranteed lower bound on the share of pull traffic carried by the "
    "Space-Saving sketch's top-K ids (1.0 = all traffic hits K ids)")
_SHARD_IMBALANCE = _reg.gauge(
    "edl_embedding_shard_load_imbalance",
    "max per-shard pull load over the uniform mean (1.0 = perfectly "
    "balanced shards)")
_SHARD_LOAD = _reg.gauge(
    "edl_embedding_client_shard_load_rows",
    "deduped rows this client pulled per shard (rolling window)",
    labels=("shard",))
# read-path telemetry (ISSUE 13): per-shard replica serves are bounded
# by --embedding_shards (config, not data): edl-lint: disable=EDL405
_REPLICA_READS = _reg.counter(
    "edl_embedding_replica_reads_total",
    "per-shard pulls served by a read replica (within the staleness "
    "bound) instead of the primary", labels=("shard",))
_REPLICA_STALE = _reg.counter(
    "edl_embedding_replica_stale_rejects_total",
    "replica answers rejected for exceeding the staleness bound "
    "(primary re-served the shard)")
_PIPE_DEPTH = _reg.gauge(
    "edl_embedding_pull_pipeline_depth",
    "configured lookahead of the newest pull pipeline (0 = pipeline off)")
_PIPE_BLOCKED_S = _reg.histogram(
    "edl_embedding_pull_pipeline_blocked_seconds",
    "time get() actually waited on a pipelined pull — the residual the "
    "compute overlap did not cover")


_GOODPUT_LEDGER = None
#: set on pipeline worker threads: a background pull overlaps compute,
#: so its wall time must NOT bill the goodput ledger's emb_pull_blocked
#: (only the get()-side residual wait does) nor the effective-read
#: latency window
_BILL_TLS = threading.local()


def _goodput_pull(seconds: float) -> None:
    """Tee pull wall time into the process goodput ledger: client pulls
    block the step (the pull pipeline exists to change that), so they
    are the `emb_pull_blocked` category — distinct from compute, which
    times only the jitted step dispatch. Pipeline worker threads are
    exempt (their pulls overlap compute; the residual `get()` wait
    bills instead). The ledger reference is cached after the first pull
    (same idiom as StepProfiler's tee): this runs per pull on the step
    path and must not pay the singleton lock every time. (Tests calling
    goodput.reset_for_tests may leave a stale cached ledger here — adds
    then land on a detached ledger, which is harmless; nothing asserts
    on it across resets.)"""
    if getattr(_BILL_TLS, "off", False):
        return
    global _GOODPUT_LEDGER
    if _GOODPUT_LEDGER is None:
        from elasticdl_tpu.observability import goodput

        _GOODPUT_LEDGER = goodput.get_ledger()
    _GOODPUT_LEDGER.add("emb_pull_blocked", seconds)


#: rolling window of recent client pull/push wall times backing the
#: heartbeat payload's emb_pull_p99_ms (the cumulative histogram cannot
#: forget a quiet past, so a fresh spike would be diluted)
LATENCY_WINDOW = 128

#: smallest pow2 padding bucket — below this, padding overhead dominates
MIN_BUCKET = 256


def pad_pow2(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def _dedupe_sum(ids: np.ndarray, rows: np.ndarray):
    """(sorted unique ids, per-unique summed rows): ONE argsort + one
    gather + one segment reduce — the client half of the deduped push
    (duplicate ids ADD, matching sparse-gradient semantics). Sorted
    output is part of the protocol: the store's fast path is a
    vectorized unique-index add gated on sorted-unique input."""
    order = np.argsort(ids, kind="stable")
    sids = ids[order]
    is_start = np.empty(sids.shape[0], bool)
    is_start[0] = True
    np.not_equal(sids[1:], sids[:-1], out=is_start[1:])
    starts = np.flatnonzero(is_start)
    if starts.shape[0] == sids.shape[0]:
        return sids, rows[order]
    return sids[starts], np.add.reduceat(rows[order], starts, axis=0)


class _MultiSub:
    """One (table, shard) sub-pull of a fused multi-table round (ISSUE
    18): the per-shard padded request plus where its rows scatter back
    (`sel` indexes the table's miss stream)."""

    __slots__ = ("table", "shard", "sel", "n", "padded", "target",
                 "is_replica")

    def __init__(self, table: str, shard: int, sel: np.ndarray, n: int,
                 padded: np.ndarray, target: int, is_replica: bool):
        self.table = table
        self.shard = shard
        self.sel = sel
        self.n = n
        self.padded = padded
        self.target = target
        self.is_replica = is_replica


class EmbeddingTierClient:
    """Per-worker handle on the tier: a shard-map view + a transport.

    `map_fetch` returns the CURRENT ShardMapView (workers wire the
    master's GetEmbeddingShardMap RPC; tests/bench hand a closure over a
    ShardMapOwner). The client refreshes on any stale-map or dead-owner
    error and replays the affected call — pushes under the same seq, so
    resharding mid-push is exactly-once by construction."""

    def __init__(
        self,
        map_fetch: Callable[[], sharding.ShardMapView],
        transport,
        client_id: str,
        dedupe: bool = True,
        max_retries: int = 8,
        retry_backoff_s: float = 0.05,
        fanout_workers: int = 0,
        sketch_k: int = 0,
        sketch_every: int = 1,
        sketch_window: int = 0,
        cache_rows: int = 0,
        cache_staleness: int = 1,
        read_replicas: bool = False,
    ):
        self._map_fetch = map_fetch
        self._transport = transport
        self._wm_replica_ok: Optional[bool] = None  # lazy capability probe
        self._pull_multi_ok: Optional[bool] = None  # lazy capability probe
        # incarnation-scoped identity: the stores' seq watermarks OUTLIVE
        # this client (they ride drain checkpoints and shard migrations),
        # so a relaunched worker reusing a bare worker-id client_id would
        # restart seq at 1 and have its first pushes silently swallowed
        # as duplicates. The nonce makes every client incarnation its own
        # watermark namespace; exactly-once across a relaunch boundary is
        # the task-accounting layer's job (a re-run task re-pushes on
        # purpose — its pre-crash work was never reported done).
        self.client_id = f"{client_id}:{uuid.uuid4().hex[:8]}"
        self.dedupe = dedupe
        self._max_retries = max_retries
        self._backoff_s = retry_backoff_s
        self._lock = threading.Lock()
        self._view: Optional[sharding.ShardMapView] = None  # guarded_by: _lock
        self._seq = 0                                        # guarded_by: _lock
        # skew telemetry (ISSUE 11), all under the client's leaf lock:
        # the Space-Saving sketch observes every deduped pull stream
        # (0 = default K_DEFAULT; its own leaf lock), per-shard load
        # counts feed the imbalance gauge, and bounded recent-latency
        # windows back the heartbeat payload's p99s (appends AND the
        # tier_stats sort both take _lock: iterating a deque while
        # another thread appends raises "mutated during iteration")
        # sketch_window > 0 switches to the exponential-decay variant
        # (ISSUE 20): the sketch halves itself every `window` stream
        # weight, so hot_share and the exported head track RECENT
        # traffic — after a popularity flip the new head overtakes the
        # old one within a couple of windows instead of letting a job-
        # lifetime cumulative count chase yesterday's distribution. The
        # layout controller's promotion/demotion both read this head.
        k = sketch_k if sketch_k > 0 else 128
        self.sketch = (DecayingSpaceSaving(k, window=sketch_window)
                       if sketch_window > 0 else SpaceSaving(k))
        # sketch feed sampling (ISSUE 13): the Space-Saving update is
        # per-unique-id PYTHON heap work — at serving rates it becomes
        # the pull's dominant cost (profiled ~75% of a cached pull) and,
        # being GIL-bound, the one thing a background pipeline pull
        # cannot overlap. hot_share is a traffic statistic: feeding
        # every Nth batch estimates it unbiasedly over the stream.
        # Default 1 (every batch — ISSUE 11's exact-telemetry contract);
        # serving-grade read paths sample (bench uses the staleness
        # stride; docs/performance.md "Embedding read path").
        self.sketch_every = max(1, int(sketch_every))
        self._sketch_tick = 0                                # guarded_by: _lock
        self._shard_loads: Optional[np.ndarray] = None      # guarded_by: _lock
        # LATENCY SPLIT (ISSUE 13 bugfix): `_pull_times` records OWNER
        # RPC rounds only — what the embedding_pull_p99 alert pages on;
        # a cache serving most reads must not dilute it. `_read_times`
        # records the effective read the step saw (cache included, and
        # pipelined reads record only their residual get() wait).
        self._pull_times: "deque[float]" = deque(maxlen=LATENCY_WINDOW)  # guarded_by: _lock
        self._read_times: "deque[float]" = deque(maxlen=LATENCY_WINDOW)  # guarded_by: _lock
        self._push_times: "deque[float]" = deque(maxlen=LATENCY_WINDOW)  # guarded_by: _lock
        # read path (ISSUE 13): hot-row cache + per-(table, shard)
        # OBSERVED owner push watermarks (the staleness fence's "what
        # the owner is known to have absorbed") + replica read-target
        # rolling loads + pipeline lookahead (the newest pipeline's)
        self.staleness_bound = max(0, int(cache_staleness))
        if cache_rows > 0 and not dedupe:
            # the cache (and its write-through) assumes the sorted-
            # unique deduped protocol; a non-deduping client is the
            # reference-PS baseline shape and gets no cache
            raise ValueError(
                "embedding cache requires the deduping client "
                "(dedupe=True)")
        self.cache: Optional[HotRowCache] = (
            HotRowCache(cache_rows, self.staleness_bound)
            if cache_rows > 0 else None)
        self.read_replicas = bool(read_replicas)
        self._owner_wm: Dict[str, np.ndarray] = {}          # guarded_by: _lock
        self._target_loads: Dict[int, int] = {}             # guarded_by: _lock
        self._pipeline_depth = 0
        # freshness probes: a FULLY cache-served pull touches no shard,
        # so the observed watermark would never advance and the
        # staleness fence would never fire for a read-mostly client —
        # every `wm_probe_every` consecutive full-hit lookups per table,
        # ask each primary for its bare watermark (no rows on the wire).
        # The worker's own push acks make this a no-op in training.
        self.wm_probe_every = 16
        self._full_hits: Dict[str, int] = {}                # guarded_by: _lock
        self.refresh()
        # fanout_workers > 0: per-shard calls to distinct owners run
        # concurrently — right for REMOTE transports, where the calls
        # are network-bound and genuinely overlap. The in-process
        # LocalTransport default stays inline: measured on this box,
        # thread fan-in over GIL-holding numpy work on small deduped
        # arrays is a net LOSS (~2x) over inline dispatch.
        self._pool: Optional[ThreadPoolExecutor] = None
        if fanout_workers > 0 and self.view.num_shards > 1:
            self._pool = ThreadPoolExecutor(
                max_workers=min(fanout_workers, self.view.num_shards),
                thread_name_prefix=f"emb-{client_id}",
            )

    def _fanout(self, calls) -> None:
        """Run the per-shard thunks, concurrently when a pool exists.
        Thunks handle their own errors (they record failures for the
        caller's retry round) — every shard's attempt completes before
        this returns."""
        if self._pool is None or len(calls) <= 1:
            for c in calls:
                c()
            return
        for f in [self._pool.submit(c) for c in calls]:
            f.result()

    # -------------------------------------------------------------- #

    def refresh(self) -> sharding.ShardMapView:
        view = self._map_fetch()
        # owner address book (ISSUE 15): a remote transport learns where
        # the owners serve from the same response that names them —
        # adopted BEFORE the view swap so no call routes to an owner
        # whose address the transport does not know yet
        if view.addrs and hasattr(self._transport, "update_addresses"):
            self._transport.update_addresses(dict(view.addrs))
        invalidate = False
        with self._lock:
            old = self._view
            self._view = view
            if old is not None and (old.version != view.version
                                    or old.num_shards != view.num_shards):
                # shard-map change: ownership AND watermark history are
                # re-keyed (a migrated shard's watermark traveled, but a
                # promoted/restored one may not line up) — drop the
                # whole cache and the observed-watermark state rather
                # than reason per entry. Reshards are rare; staleness
                # bugs are forever.
                invalidate = True
                self._owner_wm.clear()
                self._target_loads.clear()
        if invalidate and self.cache is not None:
            self.cache.invalidate_all()
        # ultra-hot promotion (ISSUE 20): a NEW hot set on the map is
        # the layout controller telling every worker "these ids carry
        # the head of the traffic — hold them locally". Warm them
        # through the normal pull path (cache write-through + staleness
        # fences apply; they stay resident by being genuinely hot).
        # Best-effort: a failed prefetch is just a later cache miss.
        if (self.cache is not None and view.hot_ids
                and (old is None or tuple(old.hot_ids)
                     != tuple(view.hot_ids))):
            self._prefetch_hot(view)
        return view

    def _prefetch_hot(self, view: sharding.ShardMapView) -> None:
        hot = np.asarray(view.hot_ids, np.int64)
        for spec in view.tables:
            ids = hot[(hot >= 0) & (hot < spec.vocab)]
            if not ids.size:
                continue
            try:
                self.pull(spec.name, ids)
            except Exception:
                logger.debug("hot-set prefetch failed for %r (ignored)",
                             spec.name, exc_info=True)

    def _owner_wm_locked(self, table: str, num_shards: int) -> np.ndarray:
        arr = self._owner_wm.get(table)
        if arr is None or arr.shape[0] != num_shards:
            arr = np.zeros(num_shards, np.int64)
            self._owner_wm[table] = arr
        return arr

    def _note_wm(self, table: str, num_shards: int, shard: int,
                 wm: int) -> None:
        """Advance the observed owner watermark (monotonic: a replica's
        lagging answer never walks freshness knowledge backwards)."""
        with self._lock:
            arr = self._owner_wm_locked(table, num_shards)
            if wm > arr[shard]:
                arr[shard] = wm

    @property
    def view(self) -> sharding.ShardMapView:
        with self._lock:
            return self._view

    def table(self, name: str) -> sharding.TableSpec:
        for t in self.view.tables:
            if t.name == name:
                return t
        raise KeyError(f"table {name!r} not registered with the tier")

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    # -------------------------------------------------------------- #
    # pull

    def pull(self, table: str, ids: np.ndarray) -> np.ndarray:
        """Batch lookup: int ids of any shape -> vectors of shape
        ``ids.shape + (dim,)``. Negative ids (bag padding sentinels)
        return zero vectors. One deduped, pow2-padded call per shard."""
        t0 = time.perf_counter()
        spec = self.table(table)
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        valid = (flat >= 0) & (flat < spec.vocab)
        all_valid = bool(valid.all())
        vids = flat if all_valid else flat[valid]
        _PULL_IDS.inc(int(flat.shape[0]))
        if not vids.shape[0]:
            out = np.zeros((flat.shape[0], spec.dim), np.float32)
        else:
            if self.dedupe:
                uniq, inverse, id_counts = np.unique(
                    vids, return_inverse=True, return_counts=True)
            else:
                uniq, inverse, id_counts = vids, None, None
            _PULL_UNIQUE.inc(int(uniq.shape[0]))
            # skew measurement: the sketch sees every id's true
            # occurrence weight (one dict op per UNIQUE id), sampled at
            # the configured batch stride
            if self._sketch_due():
                self.sketch.update_batch(uniq, id_counts)
            vectors = self._pull_unique(table, spec, uniq, id_counts)
            expanded = vectors if inverse is None else vectors[inverse]
            if all_valid:
                out = expanded
            else:
                out = np.zeros((flat.shape[0], spec.dim), np.float32)
                out[valid] = expanded
        dt = time.perf_counter() - t0
        _PULL_S.observe(dt)
        _goodput_pull(dt)
        self._note_read_time(dt)
        return out.reshape(*np.asarray(ids).shape, spec.dim)

    def _sketch_due(self) -> bool:
        if self.sketch_every == 1:
            return True
        with self._lock:
            due = self._sketch_tick % self.sketch_every == 0
            self._sketch_tick += 1
        return due

    def _note_read_time(self, dt: float) -> None:
        """Effective-read latency window — skipped on pipeline worker
        threads (the step never saw that wall; get()'s residual wait is
        recorded instead)."""
        if getattr(_BILL_TLS, "off", False):
            return
        with self._lock:
            self._read_times.append(dt)

    def _pull_unique(self, table: str, spec, uniq: np.ndarray,
                     counts: Optional[np.ndarray] = None) -> np.ndarray:
        """The read path over a sorted-unique in-range id stream: hot-row
        cache first (watermark-fenced), owner/replica shard calls for
        the misses only, miss rows admitted to the cache tagged with the
        watermark their serving response carried."""
        if self.cache is None:
            rows, _ = self._pull_owner(table, spec, uniq)
            return rows
        view = self.view
        with self._lock:
            owner_arr = self._owner_wm_locked(
                table, view.num_shards).copy()
        hit_mask, hit_rows = self.cache.lookup(
            table, spec.vocab, spec.dim, uniq, owner_arr,
            view.num_shards, counts)
        out = np.empty((uniq.shape[0], spec.dim), np.float32)
        if hit_rows is not None:
            out[hit_mask] = hit_rows
            self._attribute_degraded_hits(view, uniq, hit_mask, counts)
        miss = ~hit_mask
        if miss.any():
            miss_ids = uniq[miss]
            rows_m, wms_m = self._pull_owner(table, spec, miss_ids)
            out[miss] = rows_m
            self.cache.insert(
                table, spec.vocab, spec.dim, miss_ids, rows_m, wms_m)
            with self._lock:
                self._full_hits[table] = 0
        else:
            self._maybe_probe_watermarks(table, view)
        return out

    def _attribute_degraded_hits(self, view, uniq, hit_mask,
                                 counts) -> None:
        """The degraded ladder's \"cache\" rung, honestly attributed
        (ISSUE 15): a cache hit is normally fenced by watermarks the
        owner keeps refreshing — but while the owner's breaker is OPEN
        the observed watermark is frozen (probes fall back to replicas,
        or fail entirely), so hits on that owner's shards are served
        beyond `wm_probe` reach. They still honor the LAST verified
        bound; counting them `edl_emb_degraded_reads_total{mode=
        \"cache\"}` is what keeps the partition from hiding inside a
        healthy-looking hit rate."""
        degraded_fn = getattr(self._transport, "owner_degraded", None)
        if degraded_fn is None or not hit_mask.any():
            return
        bad_shards = [
            s for s in range(view.num_shards)
            if degraded_fn(view.owner_of(s))
        ]
        if not bad_shards:
            return
        hit_ids = uniq[hit_mask]
        shards = sharding.shard_of(hit_ids, view.num_shards)
        sel = np.isin(shards, np.asarray(bad_shards))
        if not sel.any():
            return
        if counts is None:
            n = int(sel.sum())
        else:
            n = int(counts[hit_mask][sel].sum())
        DEGRADED_READS.inc(n, mode="cache")
        reqtrace.event("degraded", mode="cache", ids=n)

    def _wm_probe_accepts_replica(self) -> bool:
        """Whether the transport's `shard_watermark` takes `replica=`
        (minimal test transports may not). Decided ONCE by signature
        inspection, not by catching TypeError per probe — a genuine
        TypeError raised inside a real transport must surface, not
        silently freeze the watermark fence."""
        ok = self._wm_replica_ok
        if ok is None:
            try:
                import inspect

                params = inspect.signature(
                    self._transport.shard_watermark).parameters
                ok = "replica" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
            except (TypeError, ValueError):
                ok = True   # unintrospectable: assume the full contract
            self._wm_replica_ok = ok
        return ok

    def _maybe_probe_watermarks(self, table: str, view) -> None:
        """Bound a read-mostly client's staleness: after
        `wm_probe_every` consecutive fully-cache-served lookups, fetch
        each primary's bare watermark so the next lookup's fence sees
        how far the owners really moved. Best-effort — a dead owner's
        probe is the retry path's problem, not the hit path's.

        Partition fallback (ISSUE 15): when the PRIMARY's probe fails,
        ask its replicas for THEIR watermark. A replica's watermark is
        a lower bound on the primary's — enough to keep the staleness
        contract one-sided during a partition: foreign pushes that the
        replica has synced WILL advance the observed watermark and
        evict rows past the bound, even though the primary is
        unreachable (the satellite test pins this)."""
        with self._lock:
            n = self._full_hits.get(table, 0) + 1
            self._full_hits[table] = 0 if n >= self.wm_probe_every else n
        if n < self.wm_probe_every:
            return
        for shard in range(view.num_shards):
            wm = self._probe_shard_wm(table, shard, view)
            if wm is not None:
                self._note_wm(table, view.num_shards, shard, int(wm))

    def _probe_shard_wm(self, table: str, shard: int,
                        view) -> Optional[int]:
        """One shard's bare freshness probe with the partition ladder
        (ISSUE 15): the primary first; on failure, any replica's
        watermark (a lower bound on the primary's). None when every
        rung failed — best-effort, the fence keeps its last bound."""
        try:
            return self._transport.shard_watermark(
                view.owner_of(shard), table, shard)
        except (StaleShardMapError, OwnerUnavailableError,
                faults.FaultInjected):
            if not self._wm_probe_accepts_replica():
                return None
            for rep in view.replicas_of(shard):
                if rep == view.owner_of(shard):
                    continue
                try:
                    return self._transport.shard_watermark(
                        rep, table, shard, replica=True)
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected):
                    continue
            return None

    def _maybe_probe_watermarks_multi(self, tables: List[str],
                                      view) -> None:
        """The fused probe cadence (ISSUE 18): tables served entirely
        from cache advance the same per-table counter as the unary
        path, and the ones that come due probe TOGETHER — one
        `watermark_multi` call per owner covering every due table's
        shards, instead of tables x shards bare probes. An owner whose
        fused probe fails falls back to the unary ladder (primary,
        then replicas) per shard. In a steady-state training loop this
        rarely fires at all: any fused pull's piggybacked owner
        watermarks reset the counters first."""
        due = []
        with self._lock:
            for table in tables:
                n = self._full_hits.get(table, 0) + 1
                self._full_hits[table] = (
                    0 if n >= self.wm_probe_every else n)
                if n >= self.wm_probe_every:
                    due.append(table)
        if not due:
            return
        wmm = getattr(self._transport, "watermark_multi", None)
        if wmm is None:
            for table in due:
                for shard in range(view.num_shards):
                    wm = self._probe_shard_wm(table, shard, view)
                    if wm is not None:
                        self._note_wm(
                            table, view.num_shards, shard, int(wm))
            return
        by_owner: Dict[int, list] = {}
        for shard in range(view.num_shards):
            owner = view.owner_of(shard)
            for table in due:
                by_owner.setdefault(owner, []).append((table, shard))
        for owner, pairs in sorted(by_owner.items()):
            try:
                wms = wmm(owner, pairs)
            except (StaleShardMapError, OwnerUnavailableError,
                    faults.FaultInjected):
                for table, shard in pairs:
                    wm = self._probe_shard_wm(table, shard, view)
                    if wm is not None:
                        self._note_wm(
                            table, view.num_shards, shard, int(wm))
                continue
            for (table, shard), wm in zip(pairs, wms):
                self._note_wm(table, view.num_shards, shard, int(wm))

    def _pull_owner(self, table: str, spec,
                    uniq: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """One call per owning shard (or its freshest-enough replica)
        over the deduped miss stream; retried whole against a refreshed
        map on stale/dead-owner errors (reads are idempotent). Returns
        ``(rows, per_id_watermarks)``. The wall across ALL rounds lands
        in the owner-RPC latency window — an outage pull records the
        outage, which is exactly what the pull-p99 alert needs to see."""
        if self._supports_pull_multi():
            # fused lane (ISSUE 18): even a single table's misses
            # coalesce across shards into ONE call per owner — under a
            # per-call-dominated wire the per-shard loop was most of
            # the pull (4 owned shards = 4x the per-call tax)
            return self._pull_owner_multi({table: uniq})[table]
        t0 = time.perf_counter()
        try:
            for attempt in range(self._max_retries + 1):
                view = self.view
                try:
                    return self._pull_once(view, table, uniq)
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected) as e:
                    self._note_retry("pull", attempt, e)
            raise OwnerUnavailableError(
                f"embedding pull for {table!r} failed after "
                f"{self._max_retries} retries"
            )
        finally:
            with self._lock:
                self._pull_times.append(time.perf_counter() - t0)

    def pull_unique(self, table: str, ids: np.ndarray):
        """The deduped-end-to-end lookup: returns ``(unique_rows,
        inverse, unique_ids)`` where ``unique_rows[inverse].reshape(
        ids.shape + (dim,))`` are the full vectors. The expansion is the
        CALLER'S gather — done inside the jitted step (TierEmbedding's
        `inverse` input), it runs on device memory bandwidth and, more
        importantly, autodiff through it hands back gradients PER UNIQUE
        ROW, already duplicate-summed — so the matching push needs no
        client-side re-dedupe at all. Negative/out-of-range ids map to
        the LAST unique slot, which is a zero row (a reserved padding
        slot), so combiner masking semantics match `pull`."""
        t0 = time.perf_counter()
        spec = self.table(table)
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        valid = (flat >= 0) & (flat < spec.vocab)
        _PULL_IDS.inc(int(flat.shape[0]))
        uniq, inverse, id_counts = np.unique(
            np.where(valid, flat, np.int64(-1)),
            return_inverse=True, return_counts=True)
        has_pad = bool(uniq.shape[0]) and uniq[0] < 0
        if has_pad:
            # rotate the sentinel slot to the END: unique ids stay a
            # sorted in-range stream for the per-shard calls, and slot
            # U-1 is the reserved zero row
            uniq = np.concatenate([uniq[1:], uniq[:1]])
            inverse = np.where(
                inverse == 0, uniq.shape[0] - 1, inverse - 1)
            id_counts = np.concatenate([id_counts[1:], id_counts[:1]])
        _PULL_UNIQUE.inc(int(uniq.shape[0]) - int(has_pad))
        rows = np.zeros((uniq.shape[0], spec.dim), np.float32)
        real = uniq.shape[0] - int(has_pad)
        if real:
            # the sentinel slot never reaches the sketch — padding is
            # protocol, not traffic (feed sampled at the batch stride)
            if self._sketch_due():
                self.sketch.update_batch(uniq[:real], id_counts[:real])
            rows[:real] = self._pull_unique(
                table, spec, uniq[:real], id_counts[:real])
        dt = time.perf_counter() - t0
        _PULL_S.observe(dt)
        _goodput_pull(dt)
        self._note_read_time(dt)
        return rows, inverse.reshape(np.asarray(ids).shape), uniq

    def _pick_read_target(self, view, shard: int) -> Tuple[int, bool]:
        """(worker id, is_replica) for one shard read: the least-loaded
        of primary + replicas (rolling client-side counts), primary-only
        while a reshard is in flight (replica copies may be mid-move).
        Writes never come through here."""
        primary = view.owner_of(shard)
        if not self.read_replicas or view.resharding:
            return primary, False
        reps = view.replicas_of(shard)
        if not reps:
            return primary, False
        with self._lock:
            loads = dict(self._target_loads)
        target = min(
            (primary,) + tuple(reps),
            key=lambda o: (loads.get(o, 0), o))
        return target, target != primary

    def _note_target_load(self, target: int, n: int) -> None:
        with self._lock:
            self._target_loads[target] = (
                self._target_loads.get(target, 0) + n)
            if self._target_loads[target] > (1 << 20):
                for k in self._target_loads:
                    self._target_loads[k] //= 2

    def _pull_once(
        self, view, table: str, uniq: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One serving round: ``(rows, per_id_watermarks)`` — each id's
        watermark is its shard's push watermark as carried by whichever
        store (primary or accepted replica) served it."""
        shards = sharding.shard_of(uniq, view.num_shards)
        local = sharding.local_rows(uniq, view.num_shards)
        out = np.empty((uniq.shape[0], self.table(table).dim), np.float32)
        wms = np.zeros(uniq.shape[0], np.int64)
        errs = []
        errs_lock = threading.Lock()

        def one(shard: int, sel):
            ids_s = local[sel].astype(np.int32)
            _SHARD_CALLS.observe(float(ids_s.shape[0]))
            n = pad_pow2(ids_s.shape[0])
            padded = np.full((n,), -1, np.int32)
            padded[: ids_s.shape[0]] = ids_s
            target, is_replica = self._pick_read_target(view, shard)
            rows = wm = None
            if is_replica:
                with self._lock:
                    known = int(self._owner_wm_locked(
                        table, view.num_shards)[shard])
                try:
                    rows, wm = self._transport.pull(
                        target, table, shard, padded,
                        map_version=view.version,
                        with_watermark=True, replica=True,
                    )
                    if wm + self.staleness_bound < known:
                        # the replica is further behind the owner than
                        # the bound allows — the primary serves, and the
                        # lagging answer is discarded (never cached)
                        _REPLICA_STALE.inc()
                        rows = wm = None
                    else:
                        _REPLICA_READS.inc(shard=str(shard))
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected):
                    # replica miss/death is never an error round: the
                    # primary is the fallback within the SAME attempt
                    rows = wm = None
            if rows is None:
                try:
                    rows, wm = self._transport.pull(
                        view.owner_of(shard), table, shard, padded,
                        map_version=view.version, with_watermark=True,
                    )
                    target = view.owner_of(shard)
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected) as e:
                    with errs_lock:
                        errs.append(e)
                    return
            out[sel] = rows[: ids_s.shape[0]]
            wms[sel] = int(wm)
            self._note_wm(table, view.num_shards, shard, int(wm))
            self._note_target_load(target, int(ids_s.shape[0]))

        self._fanout([
            (lambda s=int(shard): one(s, shards == s))
            for shard in np.unique(shards)
        ])
        if errs:
            raise errs[0]
        # load accounting only for the attempt that SERVED: a retried
        # round against a stale map would double-count rows that were
        # never pulled — skewing the imbalance signal exactly when the
        # shard-imbalance alert reads it (mid-resharding)
        self._note_shard_loads(shards, view.num_shards)
        return out, wms

    # -------------------------------------------------------------- #
    # fused multi-table pull (ISSUE 18)

    def _supports_pull_multi(self) -> bool:
        """Whether the transport offers the fused `pull_multi` lane.
        Wrappers with a `__getattr__` passthrough (ResilientTransport)
        make a plain hasattr() true even when their INNER transport
        lacks the method, so they export `supports_pull_multi()` and
        that answer wins. Decided once."""
        ok = self._pull_multi_ok
        if ok is None:
            probe = getattr(self._transport, "supports_pull_multi", None)
            if callable(probe):
                ok = bool(probe())
            else:
                ok = hasattr(self._transport, "pull_multi")
            self._pull_multi_ok = ok
        return ok

    def pull_unique_multi(
        self, table_ids: Dict[str, np.ndarray],
    ) -> Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Fused multi-table lookup (ISSUE 18): `pull_unique` semantics
        for every table in ``table_ids`` — same dedupe, sentinel
        rotation, cache and staleness fences — but all tables' misses
        travel in ONE `pull_multi` call per read target instead of one
        call per (table, shard). Under a per-call-dominated wire (the
        measured loopback truth) this is where the per-call gap closes;
        the response's piggybacked owner watermarks refresh EVERY
        table's freshness fence, so a steady-state training loop stops
        paying watermark probe calls entirely. Returns ``{table:
        (unique_rows, inverse, unique_ids)}`` exactly as per-table
        `pull_unique` would. Transports without the fused lane fall
        back to per-table calls — same results, per-table wire cost."""
        if not self._supports_pull_multi():
            return {
                table: self.pull_unique(table, ids)
                for table, ids in table_ids.items()
            }
        t0 = time.perf_counter()
        rec = reqtrace.get_recorder()
        diary = rec.start("tier_pull", tables=len(table_ids))
        states: Dict[str, Dict[str, Any]] = {}
        with reqtrace.stage("dedupe"):
            for table, ids in table_ids.items():
                spec = self.table(table)
                flat = np.asarray(ids).reshape(-1).astype(np.int64)
                valid = (flat >= 0) & (flat < spec.vocab)
                _PULL_IDS.inc(int(flat.shape[0]))
                uniq, inverse, id_counts = np.unique(
                    np.where(valid, flat, np.int64(-1)),
                    return_inverse=True, return_counts=True)
                has_pad = bool(uniq.shape[0]) and uniq[0] < 0
                if has_pad:
                    # sentinel slot rotated to the END, as in
                    # pull_unique: slot U-1 is the reserved zero row
                    uniq = np.concatenate([uniq[1:], uniq[:1]])
                    inverse = np.where(
                        inverse == 0, uniq.shape[0] - 1, inverse - 1)
                    id_counts = np.concatenate(
                        [id_counts[1:], id_counts[:1]])
                _PULL_UNIQUE.inc(int(uniq.shape[0]) - int(has_pad))
                real = uniq.shape[0] - int(has_pad)
                if real and self._sketch_due():
                    self.sketch.update_batch(
                        uniq[:real], id_counts[:real])
                states[table] = {
                    "spec": spec, "uniq": uniq, "counts": id_counts,
                    "real": real, "miss_mask": None,
                    "rows": np.zeros((uniq.shape[0], spec.dim),
                                     np.float32),
                    "inverse": inverse.reshape(np.asarray(ids).shape),
                }
        view = self.view
        try:
            misses: Dict[str, np.ndarray] = {}
            full_hit: List[str] = []
            for table, st in states.items():
                real = st["real"]
                if not real:
                    continue
                uniq_r = st["uniq"][:real]
                if self.cache is None:
                    misses[table] = uniq_r
                    continue
                counts_r = st["counts"][:real]
                with self._lock:
                    owner_arr = self._owner_wm_locked(
                        table, view.num_shards).copy()
                hit_mask, hit_rows = self.cache.lookup(
                    table, st["spec"].vocab, st["spec"].dim, uniq_r,
                    owner_arr, view.num_shards, counts_r)
                if hit_rows is not None:
                    st["rows"][:real][hit_mask] = hit_rows
                    self._attribute_degraded_hits(
                        view, uniq_r, hit_mask, counts_r)
                miss = ~hit_mask
                if miss.any():
                    misses[table] = uniq_r[miss]
                    st["miss_mask"] = miss
                else:
                    full_hit.append(table)
            if misses:
                served = self._pull_owner_multi(misses)
                for table, (rows_m, wms_m) in served.items():
                    st = states[table]
                    miss = st["miss_mask"]
                    if miss is None:
                        st["rows"][:st["real"]] = rows_m
                    else:
                        st["rows"][:st["real"]][miss] = rows_m
                    if self.cache is not None:
                        self.cache.insert(
                            table, st["spec"].vocab, st["spec"].dim,
                            misses[table], rows_m, wms_m)
                        with self._lock:
                            self._full_hits[table] = 0
            if full_hit:
                # fully-cache-served tables keep the probe cadence
                # honest; a fused pull's piggyback just reset their
                # counters, so the residual probe only fires for a
                # client whose batches stopped missing entirely
                self._maybe_probe_watermarks_multi(full_hit, view)
        except BaseException as e:
            rec.finish(diary, status="error",
                       detail=f"{type(e).__name__}: {e}")
            raise
        rec.finish(diary, status=(
            "degraded" if any(ev.get("name") == "degraded"
                              for ev in diary.events) else "ok"))
        dt = time.perf_counter() - t0
        _PULL_S.observe(dt)
        _goodput_pull(dt)
        self._note_read_time(dt)
        return {
            table: (st["rows"], st["inverse"], st["uniq"])
            for table, st in states.items()
        }

    def _pull_owner_multi(
        self, misses: Dict[str, np.ndarray],
    ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
        """The fused analogue of `_pull_owner`: one `pull_multi` call
        per read target covering EVERY table's misses on it, retried
        whole against a refreshed map on stale/dead-owner errors (reads
        are idempotent). Returns ``{table: (rows, per_id_watermarks)}``
        parallel to each table's miss stream; the wall across ALL
        rounds lands in the owner-RPC latency window."""
        t0 = time.perf_counter()
        try:
            for attempt in range(self._max_retries + 1):
                view = self.view
                try:
                    return self._pull_once_multi(view, misses)
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected) as e:
                    self._note_retry("pull", attempt, e)
            raise OwnerUnavailableError(
                f"fused embedding pull over {sorted(misses)} failed "
                f"after {self._max_retries} retries"
            )
        finally:
            with self._lock:
                self._pull_times.append(time.perf_counter() - t0)

    def _pull_once_multi(
        self, view, misses: Dict[str, np.ndarray],
    ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
        """One fused serving round: per-shard sub-pulls built exactly
        as `_pull_once` would (padded, least-loaded read target), then
        grouped by (target, replica) so each owner serves ONE
        `pull_multi` covering every table that misses on it. Replica
        groups go first; a sub whose replica failed OR answered past
        the staleness bound falls back to its primary's group within
        the SAME attempt. Each response's piggybacked owner watermarks
        advance the freshness fence for every resident shard — the
        probe traffic this kills is the point of the piggyback."""
        out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        shard_arrays: Dict[str, np.ndarray] = {}
        subs: List[_MultiSub] = []
        for table, miss in misses.items():
            dim = self.table(table).dim
            out[table] = (np.empty((miss.shape[0], dim), np.float32),
                          np.zeros(miss.shape[0], np.int64))
            shards = sharding.shard_of(miss, view.num_shards)
            local = sharding.local_rows(miss, view.num_shards)
            shard_arrays[table] = shards
            for shard in np.unique(shards):
                sel = shards == shard
                ids_s = local[sel].astype(np.int32)
                _SHARD_CALLS.observe(float(ids_s.shape[0]))
                n = pad_pow2(ids_s.shape[0])
                padded = np.full((n,), -1, np.int32)
                padded[: ids_s.shape[0]] = ids_s
                target, is_rep = self._pick_read_target(view, int(shard))
                subs.append(_MultiSub(
                    table, int(shard), sel, int(ids_s.shape[0]),
                    padded, target, is_rep))
        groups: Dict[Tuple[int, bool], List[_MultiSub]] = {}
        for sub in subs:
            groups.setdefault((sub.target, sub.is_replica),
                              []).append(sub)
        known_tables = {t.name for t in view.tables}
        errs: List[Exception] = []
        fallback: List[_MultiSub] = []
        box_lock = threading.Lock()

        def note_piggyback(owner_wms) -> None:
            # every resident primary on the serving store rode back;
            # advancing their fences here is what lets steady-state
            # freshness probes stop being calls (monotonic _note_wm —
            # a replica's own primaries are authoritative too)
            refreshed = set()
            for (t, s), wm in owner_wms.items():
                if t in known_tables and int(s) < view.num_shards:
                    self._note_wm(t, view.num_shards, int(s), int(wm))
                    refreshed.add(t)
            if refreshed:
                with self._lock:
                    for t in refreshed:
                        self._full_hits[t] = 0

        def accept(sub: _MultiSub, rows, wm, target: int) -> None:
            rows_t, wms_t = out[sub.table]
            rows_t[sub.sel] = rows[: sub.n]
            wms_t[sub.sel] = int(wm)
            self._note_wm(sub.table, view.num_shards, sub.shard, int(wm))
            self._note_target_load(target, sub.n)

        def serve_replica(target: int, group: List[_MultiSub]) -> None:
            with self._lock:
                known = {
                    sub: int(self._owner_wm_locked(
                        sub.table, view.num_shards)[sub.shard])
                    for sub in group
                }
            try:
                results, owner_wms = self._transport.pull_multi(
                    target,
                    [(s.table, s.shard, s.padded) for s in group],
                    map_version=view.version, replica=True)
            except (StaleShardMapError, OwnerUnavailableError,
                    faults.FaultInjected):
                # replica miss/death is never an error round: the
                # primary serves these subs within the SAME attempt
                with box_lock:
                    fallback.extend(group)
                return
            note_piggyback(owner_wms)
            for sub, (rows, wm) in zip(group, results):
                if wm + self.staleness_bound < known[sub]:
                    # further behind the owner than the bound allows —
                    # the primary serves; the lagging answer is
                    # discarded (never cached)
                    _REPLICA_STALE.inc()
                    with box_lock:
                        fallback.append(sub)
                else:
                    # bounded by the shard map's num_shards:
                    # edl-lint: disable=EDL405
                    _REPLICA_READS.inc(shard=str(sub.shard))
                    accept(sub, rows, wm, target)

        def serve_primary(owner: int, group: List[_MultiSub]) -> None:
            try:
                results, owner_wms = self._transport.pull_multi(
                    owner,
                    [(s.table, s.shard, s.padded) for s in group],
                    map_version=view.version)
            except (StaleShardMapError, OwnerUnavailableError,
                    faults.FaultInjected) as e:
                with box_lock:
                    errs.append(e)
                return
            note_piggyback(owner_wms)
            for sub, (rows, wm) in zip(group, results):
                accept(sub, rows, wm, owner)

        rep_groups = [(t, g) for (t, r), g in groups.items() if r]
        if rep_groups:
            self._fanout([
                (lambda tg=tg: serve_replica(*tg)) for tg in rep_groups
            ])
        primary: Dict[int, List[_MultiSub]] = {}
        for (t, r), g in groups.items():
            if not r:
                primary.setdefault(t, []).extend(g)
        for sub in fallback:
            primary.setdefault(view.owner_of(sub.shard), []).append(sub)
        if primary:
            self._fanout([
                (lambda og=og: serve_primary(*og))
                for og in sorted(primary.items())
            ])
        if errs:
            raise errs[0]
        # load accounting only for the round that SERVED (see
        # _pull_once: a retried round must not double-count)
        for table, shards in shard_arrays.items():
            self._note_shard_loads(shards, view.num_shards)
        return out

    # -------------------------------------------------------------- #
    # skew telemetry (ISSUE 11)

    def _note_shard_loads(self, shards: np.ndarray,
                          num_shards: int) -> None:
        """Accumulate per-shard deduped pull traffic (one bincount + a
        vector add under the leaf lock — the hot-path half; the gauge
        refresh and hot-share computation live in tier_stats(), on the
        heartbeat/scrape cadence). Rolling: loads halve once the window
        outgrows its bound, so the signal tracks RECENT traffic instead
        of averaging a reshard away."""
        counts = np.bincount(shards, minlength=num_shards)
        with self._lock:
            if (self._shard_loads is None
                    or self._shard_loads.shape[0] != num_shards):
                self._shard_loads = np.zeros(num_shards, np.int64)
            self._shard_loads += counts
            if int(self._shard_loads.sum()) > (1 << 20):
                self._shard_loads //= 2

    def tier_stats(self) -> Dict[str, object]:
        """The compact skew row that rides the heartbeat stats payload
        (observability/health.py budget: few keys, scalars only — plus
        the two ≤64-char STRING vectors below) so the master's fleet
        rollup sees tier skew without scraping workers: hot-id traffic
        share, shard load imbalance, per-shard load shares + the sketch
        head (`emb_shard_loads` / `emb_hot_ids`, the layout
        controller's inputs — ISSUE 20), and RECENT pull/push p99s (a
        bounded window, not the job-lifetime histogram — a fresh
        owner-loss spike must not be diluted by a quiet past). Also the
        ONE place the skew gauges refresh — heartbeat/scrape cadence,
        never per pull (the sketch's hot_share sorts its counters).

        Latency split (ISSUE 13 bugfix): `emb_pull_p99_ms` is OWNER RPC
        rounds only — the embedding_pull_p99 alert keeps paging on real
        shard trouble instead of being diluted once a cache serves most
        reads — while `emb_read_p99_ms` is the effective read the step
        saw (cache included; pipelined reads contribute their residual
        get() wait). The cache hit rate and pipeline depth ride along —
        the fleet series' hot-set-migration sensor."""
        with self._lock:
            loads = (None if self._shard_loads is None
                     else self._shard_loads.copy())
            pulls = sorted(self._pull_times)
            reads = sorted(self._read_times)
            pushes = sorted(self._push_times)
            pipe_depth = self._pipeline_depth
        hot_share = round(self.sketch.hot_share(), 4)
        _HOT_SHARE.set(hot_share)
        out: Dict[str, object] = {"emb_hot_id_share": hot_share}
        if loads is not None and int(loads.sum()):
            total = int(loads.sum())
            imbalance = round(
                float(loads.max()) * loads.shape[0] / total, 4)
            out["emb_shard_imbalance"] = imbalance
            _SHARD_IMBALANCE.set(imbalance)
            for s in range(loads.shape[0]):
                # per-shard labels are bounded by --embedding_shards (a
                # config constant, not data): edl-lint: disable=EDL405
                _SHARD_LOAD.set(float(loads[s]), shard=str(s))
            # layout-controller telemetry (ISSUE 20): per-shard load
            # shares ride the heartbeat as ONE compact string — integer
            # percents, comma-joined — because decode_stats drops
            # nested containers and truncates strings at 64 chars. The
            # key is emitted only when the full vector fits: a
            # truncated vector would parse as the wrong shard count and
            # the controller treats that worker as non-reporting (no
            # data = hold), which is the safe failure mode.
            shares = ",".join(
                str(int(round(100.0 * float(c) / total))) for c in loads)
            if len(shares) <= 64:
                out["emb_shard_loads"] = shares
        # the sketch head (hottest first) rides the same way: as many
        # whole ids as fit the 64-char string budget — the layout
        # controller aggregates these into a fleet-quorum ultra-hot set
        head = [str(i) for i, _c, _e in self.sketch.top(16)]
        if head:
            ids = ""
            for tok in head:
                cand = tok if not ids else ids + "," + tok
                if len(cand) > 64:
                    break
                ids = cand
            if ids:
                out["emb_hot_ids"] = ids
        if pulls:
            out["emb_pull_p99_ms"] = round(
                1e3 * quantile_sorted(pulls, 0.99), 3)
        if reads:
            out["emb_read_p99_ms"] = round(
                1e3 * quantile_sorted(reads, 0.99), 3)
        if pushes:
            out["emb_push_p99_ms"] = round(
                1e3 * quantile_sorted(pushes, 0.99), 3)
        if self.cache is not None:
            out["emb_cache_hit_rate"] = round(self.cache.hit_rate(), 4)
        if pipe_depth:
            out["emb_pipeline_depth"] = float(pipe_depth)
        return out

    # -------------------------------------------------------------- #
    # push

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             scale: float = 1.0) -> Dict[str, float]:
        """Batch sparse update: ``table[id] += scale * grad`` with
        duplicate ids summed client-side. Returns push stats (the
        dedupe ratio the bench records). Blocks until every touched
        shard acked — exactly once, across retries and resharding."""
        t0 = time.perf_counter()
        spec = self.table(table)
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        rows = np.asarray(grads, np.float32).reshape(-1, spec.dim)
        valid = (flat >= 0) & (flat < spec.vocab)
        if bool(valid.all()):
            vids, vrows = flat, rows   # no-sentinel fast path: no copies
        else:
            vids, vrows = flat[valid], rows[valid]
        n_batch = int(flat.shape[0])
        _PUSH_IDS.inc(n_batch)
        if not vids.shape[0]:
            dt = time.perf_counter() - t0
            _PUSH_S.observe(dt)
            with self._lock:
                self._push_times.append(dt)
            return {"ids_in_batch": n_batch, "ids_sent": 0,
                    "dedupe_ratio": 0.0}
        if self.dedupe:
            uniq, sums = _dedupe_sum(vids, vrows)
        else:
            order = np.argsort(vids, kind="stable")
            uniq, sums = vids[order], vrows[order]
        seq = self._next_seq()
        self._push_unique(table, uniq, sums, seq, scale)
        sent = int(uniq.shape[0])
        _PUSH_SENT.inc(sent)
        ratio = sent / max(1, n_batch)
        _DEDUPE_RATIO.set(ratio)
        dt = time.perf_counter() - t0
        _PUSH_S.observe(dt)
        with self._lock:
            self._push_times.append(dt)
        return {"ids_in_batch": n_batch, "ids_sent": sent,
                "dedupe_ratio": round(ratio, 4)}

    def _push_unique(self, table: str, uniq, sums, seq: int,
                     scale: float) -> None:
        """Send the deduped stream, one call per shard, ALL under one
        seq. Unacked shards are conservatively re-sent whole against a
        refreshed map (interrupted resharding, lost acks); the store's
        watermark makes re-applied shards no-ops, so the update lands
        exactly once no matter how many rounds this takes.

        With the hot-row cache on, acks carry the post-apply push
        watermark and the pushed rows WRITE THROUGH: an entry that was
        fresh as of the pre-push watermark (and whose shard advanced by
        exactly our push) gets the delta applied in place — the worker's
        own training loop keeps its hot set warm without re-pulling."""
        # watermark acks feed BOTH fences: the cache's freshness tag and
        # the replica-read staleness check (a replica-reading client
        # without a cache still needs to know the owners moved on)
        want_wm = self.cache is not None or self.read_replicas
        prev_wm = None
        if want_wm:
            with self._lock:
                prev_wm = self._owner_wm_locked(
                    table, self._view.num_shards).copy()
        ack_wms: Dict[int, int] = {}
        alock = threading.Lock()
        pending = None   # shard ids still unacked (None = all)
        view = self.view
        for attempt in range(self._max_retries + 1):
            view = self.view
            shards = sharding.shard_of(uniq, view.num_shards)
            local = sharding.local_rows(uniq, view.num_shards)
            todo = np.unique(shards) if pending is None else pending
            failed = []
            errbox = []
            flock = threading.Lock()

            def one(shard: int, sel):
                ids_s = local[sel].astype(np.int32)
                _SHARD_CALLS.observe(float(ids_s.shape[0]))
                n = pad_pow2(ids_s.shape[0])
                padded_ids = np.full((n,), -1, np.int32)
                padded_ids[: ids_s.shape[0]] = ids_s
                padded_rows = np.zeros((n, sums.shape[1]), np.float32)
                padded_rows[: ids_s.shape[0]] = sums[sel]
                try:
                    ack = self._transport.push(
                        view.owner_of(shard), table, shard,
                        padded_ids, padded_rows, client_id=self.client_id,
                        seq=seq, map_version=view.version, scale=scale,
                        with_watermark=want_wm,
                    )
                    if want_wm:
                        _, wm = ack
                        with alock:
                            ack_wms[int(shard)] = int(wm)
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected) as e:
                    with flock:
                        failed.append(shard)
                        errbox.append(e)

            self._fanout([
                (lambda s=int(shard): one(s, shards == s))
                for shard in todo
            ])
            err = errbox[0] if errbox else None
            if not failed:
                if want_wm:
                    self._write_through(
                        table, view, uniq, sums, scale, prev_wm, ack_wms)
                return
            # NOTE: after a map refresh the ids of a failed shard may hash
            # to the same shard id but a NEW owner — recomputing shards
            # from the refreshed view each round handles moves; num_shards
            # itself never changes within a map's lifetime.
            pending = np.asarray(failed)
            self._note_retry("push", attempt, err)
        raise OwnerUnavailableError(
            f"embedding push for {table!r} (seq {seq}) has "
            f"{len(pending)} unacked shard(s) after {self._max_retries} "
            "retries"
        )

    def _write_through(self, table: str, view, uniq, sums, scale: float,
                       prev_wm: np.ndarray,
                       ack_wms: Dict[int, int]) -> None:
        """Patch the worker's own push into its cache and advance the
        observed watermarks. `prev_wm` may be sized for an older map
        (refresh mid-retry re-keyed everything and dropped the cache —
        the patch is then a no-op by construction)."""
        if prev_wm is None or prev_wm.shape[0] != view.num_shards:
            return
        new_wm = prev_wm.copy()
        for s, wm in ack_wms.items():
            if s < new_wm.shape[0]:
                new_wm[s] = wm
            self._note_wm(table, view.num_shards, s, wm)
        if self.cache is None:
            return
        self.cache.write_through(
            table, np.asarray(uniq, np.int64),
            np.asarray(scale, np.float32) * np.asarray(sums, np.float32),
            view.num_shards, prev_wm, new_wm)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _note_retry(self, what: str, attempt: int, err) -> None:
        _RETRIES.inc()
        _REFRESHES.inc()
        logger.warning(
            "embedding %s retry %d (%s: %s); refreshing shard map",
            what, attempt + 1, type(err).__name__, err,
        )
        time.sleep(self._backoff_s * min(4, attempt + 1))
        self.refresh()


class EmbeddingPullPipeline:
    """Read layer 3: overlap step N+1's deduped pull with step N's
    compute (templated on DevicePrefetcher's depth/drain shape — the
    host->device lookahead's tier twin).

    The caller keeps up to `depth` id batches submitted ahead
    (`submit`), and `get()` returns pulls IN SUBMIT ORDER, blocking only
    on whatever the overlapped compute did not already cover — that
    residual wait is the only part that still bills the goodput ledger's
    `emb_pull_blocked` and the client's effective-read window (the
    background pull's own wall is exempt via the billing thread-local).

    One puller thread: the pulls themselves are GIL-holding numpy over
    small deduped arrays (measured: thread fan-in LOSES in-process, see
    EmbeddingTierClient.fanout note), so the pipeline buys pull-vs-
    compute overlap, not pull-vs-pull parallelism.

    Elasticity contract (the DevicePrefetcher `drain()` semantics): on
    rescale/reshard the caller drains — in-flight and queued id batches
    come BACK as host arrays to resubmit under the refreshed map — and
    `get()` itself re-issues synchronously when a completed result was
    pulled under a map the client has since abandoned, so a pipelined
    step can never consume rows routed by a stale map."""

    def __init__(self, client: EmbeddingTierClient, table: str,
                 depth: int = 2):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.client = client
        self.table = table
        self.depth = int(depth)
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"emb-pipe-{table}")
        self._q: "deque" = deque()     # (ids, future) in submit order
        self._lock = threading.Lock()
        self._closed = False
        client._pipeline_depth = self.depth
        _PIPE_DEPTH.set(float(self.depth))

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def submit(self, ids: np.ndarray) -> None:
        """Queue the next batch's pull (non-blocking). The ids are
        copied — the caller's buffer may be reused."""
        ids = np.array(ids, np.int64, copy=True)
        with self._lock:
            if self._closed:
                raise RuntimeError("pipeline is closed")
            if len(self._q) >= self.depth:
                raise RuntimeError(
                    f"pipeline depth {self.depth} exceeded: get() before "
                    "submitting further batches")
            self._q.append((ids, self._pool.submit(self._pull, ids)))

    def _pull(self, ids: np.ndarray):
        _BILL_TLS.off = True
        try:
            rows, inverse, uniq = self.client.pull_unique(self.table, ids)
            return rows, inverse, uniq, self.client.view.version
        finally:
            _BILL_TLS.off = False

    def get(self):
        """Next submitted batch's ``(rows, inverse, unique_ids)``,
        blocking on the residual the compute overlap did not cover."""
        with self._lock:
            if not self._q:
                raise RuntimeError("pipeline is empty: submit() first")
            ids, fut = self._q.popleft()
        t0 = time.perf_counter()
        rows, inverse, uniq, version = fut.result()
        blocked = time.perf_counter() - t0
        _PIPE_BLOCKED_S.observe(blocked)
        _goodput_pull(blocked)
        self.client._note_read_time(blocked)
        if version != self.client.view.version:
            # pulled under a map the client has since abandoned (reshard
            # landed between completion and consumption): re-issue under
            # the fresh map — this one blocks for real and bills as such
            rows, inverse, uniq = self.client.pull_unique(self.table, ids)
        return rows, inverse, uniq

    def drain(self) -> List[np.ndarray]:
        """Rescale/reshard: hand back every queued/in-flight id batch
        (submit order) for re-submission under the refreshed map.
        Unstarted pulls are cancelled; the in-flight one (if any) is
        abandoned — its result is discarded, never served."""
        with self._lock:
            pending = [(ids, fut) for ids, fut in self._q]
            self._q.clear()
        for _, fut in pending:
            fut.cancel()
        return [ids for ids, _ in pending]

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.clear()
        self._pool.shutdown(wait=False)
        self.client._pipeline_depth = 0
        _PIPE_DEPTH.set(0.0)


def view_from_response(resp) -> Optional[sharding.ShardMapView]:
    """GetEmbeddingShardMapResponse -> ShardMapView (None when the
    master has no map yet — version 0)."""
    if not resp.version:
        return None
    rc = int(getattr(resp, "replica_count", 0) or 0)
    flat = list(getattr(resp, "shard_replicas", ()) or ())
    replicas: Tuple[Tuple[int, ...], ...] = ()
    if rc and flat:
        replicas = tuple(
            tuple(int(o) for o in flat[s * rc:(s + 1) * rc] if int(o) >= 0)
            for s in range(int(resp.num_shards))
        )
    # owner address book (ISSUE 15): parallel arrays on the wire, pairs
    # in the view (old masters never set them — empty book, local
    # transport routing only)
    addr_ids = list(getattr(resp, "addr_worker_ids", ()) or ())
    addr_strs = list(getattr(resp, "addrs", ()) or ())
    addrs = tuple(
        (int(w), a) for w, a in zip(addr_ids, addr_strs) if a
    )
    return sharding.ShardMapView(
        version=int(resp.version),
        num_shards=int(resp.num_shards),
        owners=tuple(int(o) for o in resp.shard_owners),
        tables=tuple(
            sharding.TableSpec(
                name=t.name, vocab=int(t.vocab), dim=int(t.dim),
                seed=int(t.seed), init_scale=float(t.init_scale),
            )
            for t in resp.tables
        ),
        resharding=bool(resp.resharding),
        replicas=replicas,
        addrs=addrs,
        # ultra-hot set (ISSUE 20): old masters never set it — empty
        hot_ids=tuple(
            int(i) for i in (getattr(resp, "hot_ids", ()) or ())),
    )


def stub_map_fetch(stub, worker_id: int,
                   poll_s: float = 0.5, max_polls: int = 20):
    """A `map_fetch` closure over the master's GetEmbeddingShardMap RPC
    (workers wire this into EmbeddingTierClient). Polls while the master
    has no map yet (version 0 — e.g. before the first worker registered);
    raises OwnerUnavailableError once the poll budget is gone."""
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    def fetch() -> sharding.ShardMapView:
        for _ in range(max_polls):
            view = view_from_response(
                stub.GetEmbeddingShardMap(
                    pb.GetEmbeddingShardMapRequest(worker_id=worker_id)
                )
            )
            if view is not None:
                return view
            time.sleep(poll_s)
        raise OwnerUnavailableError(
            "master served no embedding shard map (tier disabled, or no "
            "workers alive to own shards)"
        )

    return fetch


def confirm_reshard(stub, worker_id: int, version: int,
                    shard_ids) -> bool:
    """The recipient half of a shard migration: report installed shards
    so the master can commit the plan (idempotent — safe to retry)."""
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    resp = stub.ReportEmbeddingReshard(
        pb.ReportEmbeddingReshardRequest(
            worker_id=worker_id, version=version,
            shard_ids=[int(s) for s in shard_ids],
        )
    )
    return bool(resp.accepted)


#: process-local default transport: single-process jobs (and the thread
#: cohorts tests/bench run) share one registry, so every worker-side
#: store in this process is reachable without a wire
_default_transport = None
_default_transport_lock = threading.Lock()


def default_transport():
    from elasticdl_tpu.embedding.transport import LocalTransport

    global _default_transport
    with _default_transport_lock:
        if _default_transport is None:
            _default_transport = LocalTransport()
        return _default_transport


class WorkerTierRuntime:
    """Everything one worker process runs for the tier: its owning store
    (registered in the transport), the pull/push client, and the
    reshard reaction — fetch newly-owned shards (live donor first, then
    checkpoint, then seed) and confirm them to the master so the plan
    can commit.

    The worker wires this at boot (worker/worker.py `_init_embedding_
    tier`, cohort leaders in cohort.py run()); `on_world_change()` runs
    at task boundaries after a membership bump (never on the heartbeat
    thread — shard installs can take a while), and `drain()` rides the
    preemption/forced-checkpoint path so a planned kill loses no acked
    push."""

    def __init__(self, stub, worker_id: int, checkpoint_dir: str = "",
                 transport=None, cache_rows: int = 0,
                 cache_staleness: int = 1, read_replicas: bool = False,
                 pipeline_depth: int = 0, bind_servicer=None):
        from elasticdl_tpu.embedding.store import EmbeddingShardStore

        self._stub = stub
        self.worker_id = worker_id
        self.checkpoint_dir = checkpoint_dir
        self.pipeline_depth = max(0, int(pipeline_depth))
        self.transport = transport if transport is not None \
            else default_transport()
        self.store = EmbeddingShardStore(worker_id)
        self.transport.register(self.store)
        if bind_servicer is not None:
            # gRPC data plane (ISSUE 15): the worker's endpoint came up
            # before registration (its address rides RegisterWorker);
            # the store binds late, here, once it exists
            bind_servicer.bind_store(self.store)
        self.client = EmbeddingTierClient(
            stub_map_fetch(stub, worker_id), self.transport,
            client_id=f"worker-{worker_id}",
            cache_rows=cache_rows, cache_staleness=cache_staleness,
            read_replicas=read_replicas,
        )
        if hasattr(self.transport, "set_view_fn"):
            # the robustness layer hedges to replicas and re-routes
            # drained pushes off the client's live view
            self.transport.set_view_fn(lambda: self.client.view)
        created = self.store.attach(self.client.view, checkpoint_dir)
        if created and self.client.view.resharding:
            confirm_reshard(
                stub, worker_id, self.client.view.version, created)
        self._install_replicas(self.client.view)

    def on_world_change(self) -> int:
        """Re-fetch the map; install shards newly assigned here (live
        donor -> checkpoint -> seed, through reshard.apply_moves so the
        migration is spanned and exactly-once), confirm them. Returns
        how many shards moved in."""
        from elasticdl_tpu.embedding import reshard, sharding as sh

        old = self.client.view
        view = self.client.refresh()
        # replica PROMOTION first (owner-death fast recovery): a shard
        # newly mine for which I hold a replica copy becomes primary in
        # place — rows, seq fence, and watermark move wholesale — unless
        # a drained checkpoint is FRESHER (its watermark outranks the
        # replica's last sync; bit-exactness beats warmth)
        promoted = self._promote_replicas(view)
        # residency, not version delta, decides what to install: the
        # client may have refreshed mid-push-retry already, so an equal
        # version can still mean shards are missing here
        resident = set(self.store.resident_shards())
        mine = [
            s for s, o in enumerate(view.owners)
            if o == self.worker_id and any(
                (t.name, s) not in resident for t in view.tables
            )
        ]
        if not mine and not promoted:
            self.store.adopt_version(view.version)
            self._install_replicas(view)
            return 0
        moves = [
            sh.ShardMove(
                shard=s,
                src=(old.owners[s]
                     if s < len(old.owners)
                     and old.owners[s] != self.worker_id else -1),
                dst=self.worker_id,
            )
            for s in sorted(set(mine) | promoted)
        ]
        reshard.apply_moves(
            view, moves, self.transport,
            checkpoint_dir=self.checkpoint_dir,
            confirm=lambda v, shards: confirm_reshard(
                self._stub, self.worker_id, v, shards),
        )
        self._install_replicas(view)
        return len(moves)

    def _promote_replicas(self, view) -> set:
        """Promote resident replica copies of shards this view newly
        assigns here. Returns the promoted shard ids (they still ride
        the move/confirm round so the master's plan commits)."""
        from elasticdl_tpu.embedding import store as store_lib

        promoted = set()
        replica_resident = set(self.store.resident_replicas())
        if not replica_resident:
            return promoted
        resident = set(self.store.resident_shards())
        for s, o in enumerate(view.owners):
            if o != self.worker_id:
                continue
            for t in view.tables:
                if (t.name, s) in resident or (t.name, s) not in replica_resident:
                    continue
                rep_wm = self.store.replica_watermark(t.name, s)
                ckpt_wm = -1
                if self.checkpoint_dir:
                    peeked = store_lib.peek_shard_watermark(
                        self.checkpoint_dir, t.name, s)
                    if peeked is not None:
                        ckpt_wm = peeked
                if ckpt_wm > rep_wm:
                    # the drained checkpoint saw pushes the replica
                    # never synced: let apply_moves restore from it
                    continue
                self.store.promote_replica(t.name, s)
                promoted.add(s)
                logger.warning(
                    "embedding shard %s/%d promoted from replica at "
                    "watermark %d (map v%d)", t.name, s, rep_wm,
                    view.version,
                )
        return promoted

    def _install_replicas(self, view) -> int:
        """Adopt this view's replica assignments: install copies for
        shards newly replicated here (full fetch from the primary; the
        sync loop keeps them fresh by delta), drop copies no longer
        assigned. Best-effort — a dead primary just defers the install
        to the next sync round."""
        # primaries only pay the per-push delta log while the map
        # actually carries replicas to consume it
        self.store.set_delta_logging(
            any(view.replicas_of(s) for s in range(view.num_shards)))
        assigned = {
            (t.name, s)
            for s in view.shards_replicated_on(self.worker_id)
            for t in view.tables
        }
        resident = set(self.store.resident_replicas())
        for (table, s) in resident - assigned:
            self.store.release_replica(table, s)
        installed = 0
        for (table, s) in assigned - resident:
            try:
                self.store.sync_replica_from(
                    self.transport, view.owner_of(s), table, s)
                installed += 1
            except Exception:
                logger.warning(
                    "replica install %s/%d from owner %d failed; will "
                    "retry on the next sync round", table, s,
                    view.owner_of(s), exc_info=True,
                )
        return installed

    def sync_replicas(self) -> int:
        """One delta-sync round over every replica copy resident here
        (worker run loop, task boundaries; cheap when nothing is
        assigned). Also retries any ASSIGNED-but-missing install — a
        replica whose primary was not up yet at assignment time lands
        on a later round. Returns shards synced. Never raises — a dead
        primary mid-recovery is the reshard reaction's problem, not the
        sync loop's."""
        view = self.client.view
        synced = 0
        if hasattr(self.transport, "drain_queued"):
            # reconnect drain (ISSUE 15): pushes parked behind an open
            # owner breaker re-send in order on the task-boundary
            # cadence — the same cadence that already retries deferred
            # replica installs
            try:
                self.transport.drain_queued()
            except Exception:
                logger.debug("queued-push drain deferred", exc_info=True)
        if set(self.store.resident_replicas()) != {
            (t.name, s)
            for s in view.shards_replicated_on(self.worker_id)
            for t in view.tables
        }:
            synced += self._install_replicas(view)
        for (table, s) in self.store.resident_replicas():
            if s >= len(view.owners) or view.owner_of(s) == self.worker_id:
                continue
            try:
                self.store.sync_replica_from(
                    self.transport, view.owner_of(s), table, s)
                synced += 1
            except Exception:
                logger.debug(
                    "replica sync %s/%d failed (primary down?)", table, s,
                    exc_info=True,
                )
        return synced

    def drain(self) -> int:
        """Persist this worker's resident shards (rows + seq watermarks)
        beside the checkpoints — the tier half of the preemption drain."""
        if not self.checkpoint_dir:
            return 0
        from elasticdl_tpu.embedding import reshard

        return reshard.drain_to_checkpoint(self.store, self.checkpoint_dir)

    def close(self) -> None:
        self.transport.deregister(self.worker_id)
        self.client.close()


class EmbeddingTierSession:
    """Training integration: pull -> jitted compute (grads w.r.t. the
    pulled vectors) -> push, per batch.

    `tables` maps table name -> the batch feature key holding its ids.
    The jitted step is compile-cache keyed (training/compile_cache) on
    the vector/batch avals, so rescale/resharding reuses the executable.
    The model consumes vectors through api/layers.TierEmbedding (the
    vectors are a jit INPUT — the tier pull happens outside the trace,
    which is what lets the table exceed one host's memory)."""

    def __init__(self, client: EmbeddingTierClient,
                 tables: Dict[str, str], compile_cache=None,
                 pipeline_depth: int = 0):
        self.client = client
        self.tables = dict(tables)
        if compile_cache is None:
            from elasticdl_tpu.training import compile_cache as cc

            compile_cache = cc.global_cache()
        self._cache = compile_cache
        # pull/compute overlap (ISSUE 13 layer 3): one pipeline per
        # table; run() keeps `pipeline_depth` batches of pulls in
        # flight behind the current step's compute
        self.pipeline_depth = max(0, int(pipeline_depth))
        self._pipes: Dict[str, EmbeddingPullPipeline] = {}

    def pull_batch(self, batch: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Full (expanded) vectors for every table, one FUSED pull per
        owner across tables (ISSUE 18). The unique-row gather happens
        here client-side; invalid/padding ids land on the reserved
        zero row, matching `pull` semantics."""
        pulled = self.client.pull_unique_multi({
            name: np.asarray(batch[key])
            for name, key in self.tables.items()
        })
        return {name: rows[inverse]
                for name, (rows, inverse, _uniq) in pulled.items()}

    def _pipe(self, name: str) -> EmbeddingPullPipeline:
        p = self._pipes.get(name)
        if p is None:
            p = EmbeddingPullPipeline(
                self.client, name, depth=self.pipeline_depth)
            self._pipes[name] = p
        return p

    def drain_pipelines(self) -> int:
        """Rescale/reshard hook: abandon in-flight pulls (they requeue
        inside run(); a direct driver resubmits what this returns)."""
        n = 0
        for p in self._pipes.values():
            n += len(p.drain())
        return n

    def close(self) -> None:
        for p in self._pipes.values():
            p.close()
        self._pipes.clear()

    def run(self, loss_fn, batches, lr: float = 0.0):
        """Pipelined step stream: yields ``(loss, push_stats)`` per
        batch with up to `pipeline_depth` NEXT batches' pulls in flight
        while the current batch computes and pushes (depth 0 degrades to
        the plain blocking `step`). If a reshard/rescale lands mid-
        stream, get() re-issues under the fresh map — no drained batch
        is lost and none is served stale."""
        if self.pipeline_depth <= 0:
            for batch in batches:
                yield self.step(loss_fn, batch, lr)
            return
        it = iter(batches)
        window: "deque" = deque()      # batches whose pulls are in flight

        def _submit(batch) -> None:
            window.append(batch)
            for name, key in self.tables.items():
                self._pipe(name).submit(np.asarray(batch[key]))

        def _get_all():
            # a drain_pipelines() from a rescale hook mid-run empties
            # the pipes while `window` still holds their batches: heal
            # by re-submitting the window IN ORDER under the (by then
            # refreshed) map — the docstring's "no drained batch is
            # lost" is this re-issue, not a hope
            for name, key in self.tables.items():
                p = self._pipe(name)
                if len(p) != len(window):
                    p.drain()
                    for b in window:
                        p.submit(np.asarray(b[key]))
            return {name: self._pipe(name).get() for name in self.tables}

        try:
            for batch in it:           # prime the lookahead window
                _submit(batch)
                if len(window) >= self.pipeline_depth:
                    break
            for batch in it:
                pulled = _get_all()
                done = window.popleft()
                # submit the NEXT batch before computing this one — the
                # whole point: its pull rides under our compute+push
                # (submitting after the step would serialize them)
                _submit(batch)
                yield self._finish_pulled(loss_fn, done, pulled, lr)
            while window:
                pulled = _get_all()
                done = window.popleft()
                yield self._finish_pulled(loss_fn, done, pulled, lr)
        finally:
            self.drain_pipelines()

    def _finish_pulled(self, loss_fn, batch, pulled, lr: float):
        vectors = {n: p[0] for n, p in pulled.items()}
        inverses = {n: p[1] for n, p in pulled.items()}
        uniq_ids = {n: p[2] for n, p in pulled.items()}
        return self._finish_step(
            loss_fn, batch, vectors, inverses, uniq_ids, lr)

    def step(self, loss_fn, batch: Dict[str, Any],
             lr: float = 0.0) -> Tuple[float, Dict[str, Dict[str, float]]]:
        """One tier step, deduped END TO END: pull one row per unique id
        (`pull_unique`), run ``loss_fn(vectors, inverses, batch)`` jitted
        with grads w.r.t. the unique vectors (the in-step `inverse`
        gather — TierEmbedding — makes autodiff hand back per-unique-row
        gradients, duplicate-summed for free), push ``-lr * grad``
        straight back (tier-side SGD — the reference's PS-resident
        optimizer, minus its per-row apply). Returns (loss, per-table
        push stats)."""
        vectors: Dict[str, Any] = {}
        inverses: Dict[str, Any] = {}
        uniq_ids: Dict[str, Any] = {}
        # ONE fused pull per owner across every table (ISSUE 18) —
        # under a per-call-dominated wire the per-table loop was the
        # step's dominant cost; transports without the fused lane
        # degrade to per-table calls inside pull_unique_multi
        pulled = self.client.pull_unique_multi({
            name: np.asarray(batch[key])
            for name, key in self.tables.items()
        })
        for name, (rows, inverse, uniq) in pulled.items():
            vectors[name], inverses[name], uniq_ids[name] = (
                rows, inverse, uniq)
        return self._finish_step(
            loss_fn, batch, vectors, inverses, uniq_ids, lr)

    def _finish_step(self, loss_fn, batch, vectors, inverses, uniq_ids,
                     lr: float) -> Tuple[float, Dict[str, Dict[str, float]]]:
        loss, grads = self._grad_fn(loss_fn, vectors, batch)(
            vectors, inverses, batch)
        stats = {}
        if lr:
            for name in self.tables:
                stats[name] = self.client.push(
                    name, uniq_ids[name], np.asarray(grads[name]),
                    scale=-lr,
                )
        return float(loss), stats

    def _grad_fn(self, loss_fn, vectors, batch):
        import jax

        key = (
            "emb_tier_step", id(loss_fn),
            tuple(sorted(
                (k, np.asarray(v).shape) for k, v in vectors.items())),
            tuple(sorted(
                (k, np.asarray(v).shape) for k, v in batch.items()
                if hasattr(v, "shape") or isinstance(v, np.ndarray))),
        )

        def build():
            return jax.jit(jax.value_and_grad(loss_fn, argnums=0))

        return self._cache.get_or_build(key, build)
