"""The worker side of the embedding tier: per-batch deduped, per-shard
batched pull -> compute -> push.

Reference parity: elasticdl/python/worker/worker.py's
pull_embedding_vectors / push_gradients — but where the reference paid
one RPC pair per PS pod per minibatch with the FULL id stream, this
client (1) DEDUPES the batch's ids once (`np.unique`), (2) groups the
unique ids by owning shard with vectorized modulo math, (3) issues ONE
batched call per shard (never per row — edl-lint EDL206 polices the
per-row anti-pattern), and (4) sums duplicate gradients client-side
(sorted segment reduce) so the owner applies one deduped scatter-add.
On skewed (production recsys) id distributions the deduped stream is a
fraction of the raw batch — `edl_embedding_dedupe_ratio` measures it.

Request lengths are padded to power-of-two buckets (sentinel id -1) so
the owner's jitted pull/apply programs stay in a handful of
compile-cache entries per table instead of recompiling per batch shape.

Exactly-once pushes: every `push()` call takes one sequence number and
sends it to every touched shard; any retry — lost ack, stale shard map
mid-resharding, owner handoff — re-sends the SAME seq, and the store's
per-(shard, client) watermark turns duplicates into acked no-ops. A
push returns only when every shard acked, so a client that returns from
`push()` KNOWS the update landed exactly once.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.embedding import sharding
from elasticdl_tpu.embedding.sketch import SpaceSaving
from elasticdl_tpu.embedding.store import StaleShardMapError
from elasticdl_tpu.embedding.transport import OwnerUnavailableError
from elasticdl_tpu.observability.registry import (
    default_registry,
    quantile_sorted,
)

logger = default_logger(__name__)

_reg = default_registry()
_PULL_S = _reg.histogram(
    "edl_embedding_pull_seconds", "client pull wall time per batch")
_PUSH_S = _reg.histogram(
    "edl_embedding_push_seconds", "client push wall time per batch")
_PULL_IDS = _reg.counter(
    "edl_embedding_pull_ids_total", "raw ids in pulled batches")
_PULL_UNIQUE = _reg.counter(
    "edl_embedding_pull_unique_ids_total", "deduped ids actually requested")
_PUSH_IDS = _reg.counter(
    "edl_embedding_push_ids_total", "raw ids in pushed batches")
_PUSH_SENT = _reg.counter(
    "edl_embedding_push_ids_sent_total", "deduped ids actually sent")
_DEDUPE_RATIO = _reg.gauge(
    "edl_embedding_dedupe_ratio",
    "ids sent / ids in batch, most recent push (1.0 = no duplicates)")
_REFRESHES = _reg.counter(
    "edl_embedding_map_refreshes_total",
    "shard-map refreshes forced by stale-map/owner errors")
_RETRIES = _reg.counter(
    "edl_embedding_push_retries_total",
    "push rounds re-sent after an error (seq fence dedupes)")
_SHARD_CALLS = _reg.histogram(
    "edl_embedding_shard_batch_ids",
    "deduped ids per per-shard call (batching effectiveness)")
# skew telemetry (ISSUE 11): the measurement ground for the hot-row
# cache / read replicas (ROADMAP 1) — docs/observability.md "Embedding
# skew telemetry"
_HOT_SHARE = _reg.gauge(
    "edl_embedding_hot_id_share",
    "guaranteed lower bound on the share of pull traffic carried by the "
    "Space-Saving sketch's top-K ids (1.0 = all traffic hits K ids)")
_SHARD_IMBALANCE = _reg.gauge(
    "edl_embedding_shard_load_imbalance",
    "max per-shard pull load over the uniform mean (1.0 = perfectly "
    "balanced shards)")
_SHARD_LOAD = _reg.gauge(
    "edl_embedding_client_shard_load_rows",
    "deduped rows this client pulled per shard (rolling window)",
    labels=("shard",))


_GOODPUT_LEDGER = None


def _goodput_pull(seconds: float) -> None:
    """Tee pull wall time into the process goodput ledger: client pulls
    block the step (ROADMAP 1's pipeline item exists to change that), so
    they are the `emb_pull_blocked` category — distinct from compute,
    which times only the jitted step dispatch. The ledger reference is
    cached after the first pull (same idiom as StepProfiler's tee): this
    runs per pull on the step path and must not pay the singleton lock
    every time. (Tests calling goodput.reset_for_tests may leave a
    stale cached ledger here — adds then land on a detached ledger,
    which is harmless; nothing asserts on it across resets.)"""
    global _GOODPUT_LEDGER
    if _GOODPUT_LEDGER is None:
        from elasticdl_tpu.observability import goodput

        _GOODPUT_LEDGER = goodput.get_ledger()
    _GOODPUT_LEDGER.add("emb_pull_blocked", seconds)


#: rolling window of recent client pull/push wall times backing the
#: heartbeat payload's emb_pull_p99_ms (the cumulative histogram cannot
#: forget a quiet past, so a fresh spike would be diluted)
LATENCY_WINDOW = 128

#: smallest pow2 padding bucket — below this, padding overhead dominates
MIN_BUCKET = 256


def pad_pow2(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def _dedupe_sum(ids: np.ndarray, rows: np.ndarray):
    """(sorted unique ids, per-unique summed rows): ONE argsort + one
    gather + one segment reduce — the client half of the deduped push
    (duplicate ids ADD, matching sparse-gradient semantics). Sorted
    output is part of the protocol: the store's fast path is a
    vectorized unique-index add gated on sorted-unique input."""
    order = np.argsort(ids, kind="stable")
    sids = ids[order]
    is_start = np.empty(sids.shape[0], bool)
    is_start[0] = True
    np.not_equal(sids[1:], sids[:-1], out=is_start[1:])
    starts = np.flatnonzero(is_start)
    if starts.shape[0] == sids.shape[0]:
        return sids, rows[order]
    return sids[starts], np.add.reduceat(rows[order], starts, axis=0)


class EmbeddingTierClient:
    """Per-worker handle on the tier: a shard-map view + a transport.

    `map_fetch` returns the CURRENT ShardMapView (workers wire the
    master's GetEmbeddingShardMap RPC; tests/bench hand a closure over a
    ShardMapOwner). The client refreshes on any stale-map or dead-owner
    error and replays the affected call — pushes under the same seq, so
    resharding mid-push is exactly-once by construction."""

    def __init__(
        self,
        map_fetch: Callable[[], sharding.ShardMapView],
        transport,
        client_id: str,
        dedupe: bool = True,
        max_retries: int = 8,
        retry_backoff_s: float = 0.05,
        fanout_workers: int = 0,
        sketch_k: int = 0,
    ):
        self._map_fetch = map_fetch
        self._transport = transport
        # incarnation-scoped identity: the stores' seq watermarks OUTLIVE
        # this client (they ride drain checkpoints and shard migrations),
        # so a relaunched worker reusing a bare worker-id client_id would
        # restart seq at 1 and have its first pushes silently swallowed
        # as duplicates. The nonce makes every client incarnation its own
        # watermark namespace; exactly-once across a relaunch boundary is
        # the task-accounting layer's job (a re-run task re-pushes on
        # purpose — its pre-crash work was never reported done).
        self.client_id = f"{client_id}:{uuid.uuid4().hex[:8]}"
        self.dedupe = dedupe
        self._max_retries = max_retries
        self._backoff_s = retry_backoff_s
        self._lock = threading.Lock()
        self._view: Optional[sharding.ShardMapView] = None  # guarded_by: _lock
        self._seq = 0                                        # guarded_by: _lock
        # skew telemetry (ISSUE 11), all under the client's leaf lock:
        # the Space-Saving sketch observes every deduped pull stream
        # (0 = default K_DEFAULT; its own leaf lock), per-shard load
        # counts feed the imbalance gauge, and bounded recent-latency
        # windows back the heartbeat payload's p99s (appends AND the
        # tier_stats sort both take _lock: iterating a deque while
        # another thread appends raises "mutated during iteration")
        self.sketch = SpaceSaving(sketch_k if sketch_k > 0 else 128)
        self._shard_loads: Optional[np.ndarray] = None      # guarded_by: _lock
        self._pull_times: "deque[float]" = deque(maxlen=LATENCY_WINDOW)  # guarded_by: _lock
        self._push_times: "deque[float]" = deque(maxlen=LATENCY_WINDOW)  # guarded_by: _lock
        self.refresh()
        # fanout_workers > 0: per-shard calls to distinct owners run
        # concurrently — right for REMOTE transports, where the calls
        # are network-bound and genuinely overlap. The in-process
        # LocalTransport default stays inline: measured on this box,
        # thread fan-in over GIL-holding numpy work on small deduped
        # arrays is a net LOSS (~2x) over inline dispatch.
        self._pool: Optional[ThreadPoolExecutor] = None
        if fanout_workers > 0 and self.view.num_shards > 1:
            self._pool = ThreadPoolExecutor(
                max_workers=min(fanout_workers, self.view.num_shards),
                thread_name_prefix=f"emb-{client_id}",
            )

    def _fanout(self, calls) -> None:
        """Run the per-shard thunks, concurrently when a pool exists.
        Thunks handle their own errors (they record failures for the
        caller's retry round) — every shard's attempt completes before
        this returns."""
        if self._pool is None or len(calls) <= 1:
            for c in calls:
                c()
            return
        for f in [self._pool.submit(c) for c in calls]:
            f.result()

    # -------------------------------------------------------------- #

    def refresh(self) -> sharding.ShardMapView:
        view = self._map_fetch()
        with self._lock:
            self._view = view
        return view

    @property
    def view(self) -> sharding.ShardMapView:
        with self._lock:
            return self._view

    def table(self, name: str) -> sharding.TableSpec:
        for t in self.view.tables:
            if t.name == name:
                return t
        raise KeyError(f"table {name!r} not registered with the tier")

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    # -------------------------------------------------------------- #
    # pull

    def pull(self, table: str, ids: np.ndarray) -> np.ndarray:
        """Batch lookup: int ids of any shape -> vectors of shape
        ``ids.shape + (dim,)``. Negative ids (bag padding sentinels)
        return zero vectors. One deduped, pow2-padded call per shard."""
        t0 = time.perf_counter()
        spec = self.table(table)
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        valid = (flat >= 0) & (flat < spec.vocab)
        all_valid = bool(valid.all())
        vids = flat if all_valid else flat[valid]
        _PULL_IDS.inc(int(flat.shape[0]))
        if not vids.shape[0]:
            out = np.zeros((flat.shape[0], spec.dim), np.float32)
        else:
            if self.dedupe:
                uniq, inverse, id_counts = np.unique(
                    vids, return_inverse=True, return_counts=True)
            else:
                uniq, inverse, id_counts = vids, None, None
            _PULL_UNIQUE.inc(int(uniq.shape[0]))
            # skew measurement: the sketch sees every id's true
            # occurrence weight (one dict op per UNIQUE id)
            self.sketch.update_batch(uniq, id_counts)
            vectors = self._pull_unique(table, spec, uniq)
            expanded = vectors if inverse is None else vectors[inverse]
            if all_valid:
                out = expanded
            else:
                out = np.zeros((flat.shape[0], spec.dim), np.float32)
                out[valid] = expanded
        dt = time.perf_counter() - t0
        _PULL_S.observe(dt)
        _goodput_pull(dt)
        with self._lock:
            self._pull_times.append(dt)
        return out.reshape(*np.asarray(ids).shape, spec.dim)

    def _pull_unique(self, table: str, spec, uniq: np.ndarray) -> np.ndarray:
        """One call per owning shard over the deduped stream; retried
        whole against a refreshed map on stale/dead-owner errors (reads
        are idempotent)."""
        for attempt in range(self._max_retries + 1):
            view = self.view
            try:
                return self._pull_once(view, table, uniq)
            except (StaleShardMapError, OwnerUnavailableError,
                    faults.FaultInjected) as e:
                self._note_retry("pull", attempt, e)
        raise OwnerUnavailableError(
            f"embedding pull for {table!r} failed after "
            f"{self._max_retries} retries"
        )

    def pull_unique(self, table: str, ids: np.ndarray):
        """The deduped-end-to-end lookup: returns ``(unique_rows,
        inverse, unique_ids)`` where ``unique_rows[inverse].reshape(
        ids.shape + (dim,))`` are the full vectors. The expansion is the
        CALLER'S gather — done inside the jitted step (TierEmbedding's
        `inverse` input), it runs on device memory bandwidth and, more
        importantly, autodiff through it hands back gradients PER UNIQUE
        ROW, already duplicate-summed — so the matching push needs no
        client-side re-dedupe at all. Negative/out-of-range ids map to
        the LAST unique slot, which is a zero row (a reserved padding
        slot), so combiner masking semantics match `pull`."""
        t0 = time.perf_counter()
        spec = self.table(table)
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        valid = (flat >= 0) & (flat < spec.vocab)
        _PULL_IDS.inc(int(flat.shape[0]))
        uniq, inverse, id_counts = np.unique(
            np.where(valid, flat, np.int64(-1)),
            return_inverse=True, return_counts=True)
        has_pad = bool(uniq.shape[0]) and uniq[0] < 0
        if has_pad:
            # rotate the sentinel slot to the END: unique ids stay a
            # sorted in-range stream for the per-shard calls, and slot
            # U-1 is the reserved zero row
            uniq = np.concatenate([uniq[1:], uniq[:1]])
            inverse = np.where(
                inverse == 0, uniq.shape[0] - 1, inverse - 1)
            id_counts = np.concatenate([id_counts[1:], id_counts[:1]])
        _PULL_UNIQUE.inc(int(uniq.shape[0]) - int(has_pad))
        rows = np.zeros((uniq.shape[0], spec.dim), np.float32)
        real = uniq.shape[0] - int(has_pad)
        if real:
            # the sentinel slot never reaches the sketch — padding is
            # protocol, not traffic
            self.sketch.update_batch(uniq[:real], id_counts[:real])
            rows[:real] = self._pull_unique(table, spec, uniq[:real])
        dt = time.perf_counter() - t0
        _PULL_S.observe(dt)
        _goodput_pull(dt)
        with self._lock:
            self._pull_times.append(dt)
        return rows, inverse.reshape(np.asarray(ids).shape), uniq

    def _pull_once(self, view, table: str, uniq: np.ndarray) -> np.ndarray:
        shards = sharding.shard_of(uniq, view.num_shards)
        local = sharding.local_rows(uniq, view.num_shards)
        out = np.empty((uniq.shape[0], self.table(table).dim), np.float32)
        errs = []
        errs_lock = threading.Lock()

        def one(shard: int, sel):
            ids_s = local[sel].astype(np.int32)
            _SHARD_CALLS.observe(float(ids_s.shape[0]))
            n = pad_pow2(ids_s.shape[0])
            padded = np.full((n,), -1, np.int32)
            padded[: ids_s.shape[0]] = ids_s
            try:
                rows = self._transport.pull(
                    view.owner_of(shard), table, shard, padded,
                    map_version=view.version,
                )
            except (StaleShardMapError, OwnerUnavailableError,
                    faults.FaultInjected) as e:
                with errs_lock:
                    errs.append(e)
                return
            out[sel] = rows[: ids_s.shape[0]]

        self._fanout([
            (lambda s=int(shard): one(s, shards == s))
            for shard in np.unique(shards)
        ])
        if errs:
            raise errs[0]
        # load accounting only for the attempt that SERVED: a retried
        # round against a stale map would double-count rows that were
        # never pulled — skewing the imbalance signal exactly when the
        # shard-imbalance alert reads it (mid-resharding)
        self._note_shard_loads(shards, view.num_shards)
        return out

    # -------------------------------------------------------------- #
    # skew telemetry (ISSUE 11)

    def _note_shard_loads(self, shards: np.ndarray,
                          num_shards: int) -> None:
        """Accumulate per-shard deduped pull traffic (one bincount + a
        vector add under the leaf lock — the hot-path half; the gauge
        refresh and hot-share computation live in tier_stats(), on the
        heartbeat/scrape cadence). Rolling: loads halve once the window
        outgrows its bound, so the signal tracks RECENT traffic instead
        of averaging a reshard away."""
        counts = np.bincount(shards, minlength=num_shards)
        with self._lock:
            if (self._shard_loads is None
                    or self._shard_loads.shape[0] != num_shards):
                self._shard_loads = np.zeros(num_shards, np.int64)
            self._shard_loads += counts
            if int(self._shard_loads.sum()) > (1 << 20):
                self._shard_loads //= 2

    def tier_stats(self) -> Dict[str, float]:
        """The compact skew row that rides the heartbeat stats payload
        (observability/health.py budget: few keys, scalars only) so the
        master's fleet rollup sees tier skew without scraping workers:
        hot-id traffic share, shard load imbalance, and RECENT pull/push
        p99s (a bounded window, not the job-lifetime histogram — a fresh
        owner-loss spike must not be diluted by a quiet past). Also the
        ONE place the skew gauges refresh — heartbeat/scrape cadence,
        never per pull (the sketch's hot_share sorts its counters)."""
        with self._lock:
            loads = (None if self._shard_loads is None
                     else self._shard_loads.copy())
            pulls = sorted(self._pull_times)
            pushes = sorted(self._push_times)
        hot_share = round(self.sketch.hot_share(), 4)
        _HOT_SHARE.set(hot_share)
        out: Dict[str, float] = {"emb_hot_id_share": hot_share}
        if loads is not None and int(loads.sum()):
            total = int(loads.sum())
            imbalance = round(
                float(loads.max()) * loads.shape[0] / total, 4)
            out["emb_shard_imbalance"] = imbalance
            _SHARD_IMBALANCE.set(imbalance)
            for s in range(loads.shape[0]):
                # per-shard labels are bounded by --embedding_shards (a
                # config constant, not data): edl-lint: disable=EDL405
                _SHARD_LOAD.set(float(loads[s]), shard=str(s))
        if pulls:
            out["emb_pull_p99_ms"] = round(
                1e3 * quantile_sorted(pulls, 0.99), 3)
        if pushes:
            out["emb_push_p99_ms"] = round(
                1e3 * quantile_sorted(pushes, 0.99), 3)
        return out

    # -------------------------------------------------------------- #
    # push

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray,
             scale: float = 1.0) -> Dict[str, float]:
        """Batch sparse update: ``table[id] += scale * grad`` with
        duplicate ids summed client-side. Returns push stats (the
        dedupe ratio the bench records). Blocks until every touched
        shard acked — exactly once, across retries and resharding."""
        t0 = time.perf_counter()
        spec = self.table(table)
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        rows = np.asarray(grads, np.float32).reshape(-1, spec.dim)
        valid = (flat >= 0) & (flat < spec.vocab)
        if bool(valid.all()):
            vids, vrows = flat, rows   # no-sentinel fast path: no copies
        else:
            vids, vrows = flat[valid], rows[valid]
        n_batch = int(flat.shape[0])
        _PUSH_IDS.inc(n_batch)
        if not vids.shape[0]:
            dt = time.perf_counter() - t0
            _PUSH_S.observe(dt)
            with self._lock:
                self._push_times.append(dt)
            return {"ids_in_batch": n_batch, "ids_sent": 0,
                    "dedupe_ratio": 0.0}
        if self.dedupe:
            uniq, sums = _dedupe_sum(vids, vrows)
        else:
            order = np.argsort(vids, kind="stable")
            uniq, sums = vids[order], vrows[order]
        seq = self._next_seq()
        self._push_unique(table, uniq, sums, seq, scale)
        sent = int(uniq.shape[0])
        _PUSH_SENT.inc(sent)
        ratio = sent / max(1, n_batch)
        _DEDUPE_RATIO.set(ratio)
        dt = time.perf_counter() - t0
        _PUSH_S.observe(dt)
        with self._lock:
            self._push_times.append(dt)
        return {"ids_in_batch": n_batch, "ids_sent": sent,
                "dedupe_ratio": round(ratio, 4)}

    def _push_unique(self, table: str, uniq, sums, seq: int,
                     scale: float) -> None:
        """Send the deduped stream, one call per shard, ALL under one
        seq. Unacked shards are conservatively re-sent whole against a
        refreshed map (interrupted resharding, lost acks); the store's
        watermark makes re-applied shards no-ops, so the update lands
        exactly once no matter how many rounds this takes."""
        pending = None   # shard ids still unacked (None = all)
        for attempt in range(self._max_retries + 1):
            view = self.view
            shards = sharding.shard_of(uniq, view.num_shards)
            local = sharding.local_rows(uniq, view.num_shards)
            todo = np.unique(shards) if pending is None else pending
            failed = []
            errbox = []
            flock = threading.Lock()

            def one(shard: int, sel):
                ids_s = local[sel].astype(np.int32)
                _SHARD_CALLS.observe(float(ids_s.shape[0]))
                n = pad_pow2(ids_s.shape[0])
                padded_ids = np.full((n,), -1, np.int32)
                padded_ids[: ids_s.shape[0]] = ids_s
                padded_rows = np.zeros((n, sums.shape[1]), np.float32)
                padded_rows[: ids_s.shape[0]] = sums[sel]
                try:
                    self._transport.push(
                        view.owner_of(shard), table, shard,
                        padded_ids, padded_rows, client_id=self.client_id,
                        seq=seq, map_version=view.version, scale=scale,
                    )
                except (StaleShardMapError, OwnerUnavailableError,
                        faults.FaultInjected) as e:
                    with flock:
                        failed.append(shard)
                        errbox.append(e)

            self._fanout([
                (lambda s=int(shard): one(s, shards == s))
                for shard in todo
            ])
            err = errbox[0] if errbox else None
            if not failed:
                return
            # NOTE: after a map refresh the ids of a failed shard may hash
            # to the same shard id but a NEW owner — recomputing shards
            # from the refreshed view each round handles moves; num_shards
            # itself never changes within a map's lifetime.
            pending = np.asarray(failed)
            self._note_retry("push", attempt, err)
        raise OwnerUnavailableError(
            f"embedding push for {table!r} (seq {seq}) has "
            f"{len(pending)} unacked shard(s) after {self._max_retries} "
            "retries"
        )

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _note_retry(self, what: str, attempt: int, err) -> None:
        _RETRIES.inc()
        _REFRESHES.inc()
        logger.warning(
            "embedding %s retry %d (%s: %s); refreshing shard map",
            what, attempt + 1, type(err).__name__, err,
        )
        time.sleep(self._backoff_s * min(4, attempt + 1))
        self.refresh()


def view_from_response(resp) -> Optional[sharding.ShardMapView]:
    """GetEmbeddingShardMapResponse -> ShardMapView (None when the
    master has no map yet — version 0)."""
    if not resp.version:
        return None
    return sharding.ShardMapView(
        version=int(resp.version),
        num_shards=int(resp.num_shards),
        owners=tuple(int(o) for o in resp.shard_owners),
        tables=tuple(
            sharding.TableSpec(
                name=t.name, vocab=int(t.vocab), dim=int(t.dim),
                seed=int(t.seed), init_scale=float(t.init_scale),
            )
            for t in resp.tables
        ),
        resharding=bool(resp.resharding),
    )


def stub_map_fetch(stub, worker_id: int,
                   poll_s: float = 0.5, max_polls: int = 20):
    """A `map_fetch` closure over the master's GetEmbeddingShardMap RPC
    (workers wire this into EmbeddingTierClient). Polls while the master
    has no map yet (version 0 — e.g. before the first worker registered);
    raises OwnerUnavailableError once the poll budget is gone."""
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    def fetch() -> sharding.ShardMapView:
        for _ in range(max_polls):
            view = view_from_response(
                stub.GetEmbeddingShardMap(
                    pb.GetEmbeddingShardMapRequest(worker_id=worker_id)
                )
            )
            if view is not None:
                return view
            time.sleep(poll_s)
        raise OwnerUnavailableError(
            "master served no embedding shard map (tier disabled, or no "
            "workers alive to own shards)"
        )

    return fetch


def confirm_reshard(stub, worker_id: int, version: int,
                    shard_ids) -> bool:
    """The recipient half of a shard migration: report installed shards
    so the master can commit the plan (idempotent — safe to retry)."""
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    resp = stub.ReportEmbeddingReshard(
        pb.ReportEmbeddingReshardRequest(
            worker_id=worker_id, version=version,
            shard_ids=[int(s) for s in shard_ids],
        )
    )
    return bool(resp.accepted)


#: process-local default transport: single-process jobs (and the thread
#: cohorts tests/bench run) share one registry, so every worker-side
#: store in this process is reachable without a wire
_default_transport = None
_default_transport_lock = threading.Lock()


def default_transport():
    from elasticdl_tpu.embedding.transport import LocalTransport

    global _default_transport
    with _default_transport_lock:
        if _default_transport is None:
            _default_transport = LocalTransport()
        return _default_transport


class WorkerTierRuntime:
    """Everything one worker process runs for the tier: its owning store
    (registered in the transport), the pull/push client, and the
    reshard reaction — fetch newly-owned shards (live donor first, then
    checkpoint, then seed) and confirm them to the master so the plan
    can commit.

    The worker wires this at boot (worker/worker.py `_init_embedding_
    tier`, cohort leaders in cohort.py run()); `on_world_change()` runs
    at task boundaries after a membership bump (never on the heartbeat
    thread — shard installs can take a while), and `drain()` rides the
    preemption/forced-checkpoint path so a planned kill loses no acked
    push."""

    def __init__(self, stub, worker_id: int, checkpoint_dir: str = "",
                 transport=None):
        from elasticdl_tpu.embedding.store import EmbeddingShardStore

        self._stub = stub
        self.worker_id = worker_id
        self.checkpoint_dir = checkpoint_dir
        self.transport = transport if transport is not None \
            else default_transport()
        self.store = EmbeddingShardStore(worker_id)
        self.transport.register(self.store)
        self.client = EmbeddingTierClient(
            stub_map_fetch(stub, worker_id), self.transport,
            client_id=f"worker-{worker_id}",
        )
        created = self.store.attach(self.client.view, checkpoint_dir)
        if created and self.client.view.resharding:
            confirm_reshard(
                stub, worker_id, self.client.view.version, created)

    def on_world_change(self) -> int:
        """Re-fetch the map; install shards newly assigned here (live
        donor -> checkpoint -> seed, through reshard.apply_moves so the
        migration is spanned and exactly-once), confirm them. Returns
        how many shards moved in."""
        from elasticdl_tpu.embedding import reshard, sharding as sh

        old = self.client.view
        view = self.client.refresh()
        # residency, not version delta, decides what to install: the
        # client may have refreshed mid-push-retry already, so an equal
        # version can still mean shards are missing here
        resident = set(self.store.resident_shards())
        mine = [
            s for s, o in enumerate(view.owners)
            if o == self.worker_id and any(
                (t.name, s) not in resident for t in view.tables
            )
        ]
        if not mine:
            self.store.adopt_version(view.version)
            return 0
        moves = [
            sh.ShardMove(
                shard=s,
                src=(old.owners[s]
                     if s < len(old.owners)
                     and old.owners[s] != self.worker_id else -1),
                dst=self.worker_id,
            )
            for s in mine
        ]
        reshard.apply_moves(
            view, moves, self.transport,
            checkpoint_dir=self.checkpoint_dir,
            confirm=lambda v, shards: confirm_reshard(
                self._stub, self.worker_id, v, shards),
        )
        return len(moves)

    def drain(self) -> int:
        """Persist this worker's resident shards (rows + seq watermarks)
        beside the checkpoints — the tier half of the preemption drain."""
        if not self.checkpoint_dir:
            return 0
        from elasticdl_tpu.embedding import reshard

        return reshard.drain_to_checkpoint(self.store, self.checkpoint_dir)

    def close(self) -> None:
        self.transport.deregister(self.worker_id)
        self.client.close()


class EmbeddingTierSession:
    """Training integration: pull -> jitted compute (grads w.r.t. the
    pulled vectors) -> push, per batch.

    `tables` maps table name -> the batch feature key holding its ids.
    The jitted step is compile-cache keyed (training/compile_cache) on
    the vector/batch avals, so rescale/resharding reuses the executable.
    The model consumes vectors through api/layers.TierEmbedding (the
    vectors are a jit INPUT — the tier pull happens outside the trace,
    which is what lets the table exceed one host's memory)."""

    def __init__(self, client: EmbeddingTierClient,
                 tables: Dict[str, str], compile_cache=None):
        self.client = client
        self.tables = dict(tables)
        if compile_cache is None:
            from elasticdl_tpu.training import compile_cache as cc

            compile_cache = cc.global_cache()
        self._cache = compile_cache

    def pull_batch(self, batch: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            name: self.client.pull(name, np.asarray(batch[key]))
            for name, key in self.tables.items()
        }

    def step(self, loss_fn, batch: Dict[str, Any],
             lr: float = 0.0) -> Tuple[float, Dict[str, Dict[str, float]]]:
        """One tier step, deduped END TO END: pull one row per unique id
        (`pull_unique`), run ``loss_fn(vectors, inverses, batch)`` jitted
        with grads w.r.t. the unique vectors (the in-step `inverse`
        gather — TierEmbedding — makes autodiff hand back per-unique-row
        gradients, duplicate-summed for free), push ``-lr * grad``
        straight back (tier-side SGD — the reference's PS-resident
        optimizer, minus its per-row apply). Returns (loss, per-table
        push stats)."""
        vectors: Dict[str, Any] = {}
        inverses: Dict[str, Any] = {}
        uniq_ids: Dict[str, Any] = {}
        for name, key in self.tables.items():
            rows, inverse, uniq = self.client.pull_unique(
                name, np.asarray(batch[key]))
            vectors[name], inverses[name], uniq_ids[name] = (
                rows, inverse, uniq)
        loss, grads = self._grad_fn(loss_fn, vectors, batch)(
            vectors, inverses, batch)
        stats = {}
        if lr:
            for name in self.tables:
                stats[name] = self.client.push(
                    name, uniq_ids[name], np.asarray(grads[name]),
                    scale=-lr,
                )
        return float(loss), stats

    def _grad_fn(self, loss_fn, vectors, batch):
        import jax

        key = (
            "emb_tier_step", id(loss_fn),
            tuple(sorted(
                (k, np.asarray(v).shape) for k, v in vectors.items())),
            tuple(sorted(
                (k, np.asarray(v).shape) for k, v in batch.items()
                if hasattr(v, "shape") or isinstance(v, np.ndarray))),
        )

        def build():
            return jax.jit(jax.value_and_grad(loss_fn, argnums=0))

        return self._cache.get_or_build(key, build)
