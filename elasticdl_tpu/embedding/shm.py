"""Same-host shared-memory short-circuit for the embedding data plane.

When a tier client and an owning store land on the same host (common in
the bench swarm and in packed deployments), the gRPC loopback tax —
~1ms/call measured by the `wire_truth` probe — is pure overhead: both
ends can see the same bytes. This module gives each (client, owner)
pair a dedicated SPSC ring in one `multiprocessing.shared_memory`
segment, negotiated over the regular gRPC channel
(`EmbeddingShmNegotiate`): the owner creates the segment and a poll
thread, the client attaches and round-trips serialized data-plane
requests through it. Payloads are the SAME protobuf messages the gRPC
lane carries — the ring replaces the socket, not the codec — so the
fused zero-copy row layout rides unchanged.

Protocol (single segment, 64-byte header + request slot + response
slot, all header fields aligned u64):

    [magic][slot_bytes][req_seq][resp_seq][req_len][resp_len]
    [req_method][resp_status]

The client writes the request payload FIRST, then length+method, then
bumps ``req_seq`` — the publish. The server polls for ``req_seq !=
resp_seq``, serves against the store, writes the response payload, and
publishes by setting ``resp_seq = req_seq``. One in-flight request per
ring (SPSC); the client serializes its threads on an in-process lock.
Seq-last publication keeps the pattern safe on x86's total store
order; this short-circuit is only negotiated same-host, so there is no
cross-architecture wire to worry about.

Failure is always an option and always transparent: negotiation
declined, segment gone (owner died, /dev/shm wiped), payload larger
than the slot, or a response deadline miss all surface as
`ShmRingError` — the caller (GrpcTransport) drops the ring and falls
back to the gRPC lane, counting the fallback. A partition is modeled
by the address book changing (the bench's blackhole swaps the owner's
addr), which drops the ring with the channel — the short-circuit never
outlives the address that negotiated it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional, Tuple

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import reqtrace
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

try:
    from multiprocessing import shared_memory as _shm_mod
    HAVE_SHM = True
except Exception:  # pragma: no cover - exotic platforms only
    _shm_mod = None
    HAVE_SHM = False

#: ring request method ids (the shm lane's tiny method table — only the
#: hot unary calls ride the ring; bulk fetch/stream stays on gRPC)
M_PULL_MULTI = 1
M_PULL = 2
M_PUSH = 3
M_WATERMARK = 4
M_WATERMARK_MULTI = 5

#: ring response status codes
S_OK = 0
S_STALE = 1        # payload = utf8 detail -> StaleShardMapError
S_ERROR = 2        # payload = utf8 detail -> OwnerUnavailableError

_MAGIC = 0xED1E57D1
_HDR_BYTES = 64
_I_MAGIC, _I_SLOT, _I_REQ_SEQ, _I_RESP_SEQ = 0, 1, 2, 3
_I_REQ_LEN, _I_RESP_LEN, _I_REQ_METHOD, _I_RESP_STATUS = 4, 5, 6, 7

#: default/granted slot sizing: 1 MiB holds a fused pull of ~16k rows
#: at dim 16 with headroom; anything larger falls back to gRPC per-call
DEFAULT_SLOT_BYTES = 1 << 20
MAX_SLOT_BYTES = 1 << 22

#: server poll + client spin cadence; sleep() floors around 50-100us on
#: Linux (timer slack), which still beats the ~1ms gRPC loopback by 10x
POLL_S = float(os.environ.get("EDL_EMB_SHM_POLL_US", "20")) * 1e-6
_SPIN_ITERS = 200

SHM_CALLS = default_registry().counter(
    "edl_emb_shm_calls_total",
    "client-side shm ring round-trips ATTEMPTED, by method — the "
    "fallback share's denominator (edl_emb_shm_reads_total counts "
    "only the ones that completed)",
    labels=("method",))
SHM_READS = default_registry().counter(
    "edl_emb_shm_reads_total",
    "data-plane calls served over the same-host shared-memory ring, "
    "by method",
    labels=("method",))
SHM_FALLBACKS = default_registry().counter(
    "edl_emb_shm_fallbacks_total",
    "shm short-circuit attempts that fell back to the gRPC lane, by "
    "reason (negotiate / attach / too_big / timeout / gone)",
    labels=("reason",))
SHM_RINGS = default_registry().gauge(
    "edl_emb_shm_rings",
    "shared-memory rings currently served by this owner")
SHM_OCCUPANCY = default_registry().gauge(
    "edl_emb_shm_ring_occupancy",
    "rings on this owner currently serving a request (busy rings; "
    "occupancy near edl_emb_shm_rings means poll threads saturated)")

_METHOD_NAMES = {
    M_PULL_MULTI: "pull_multi", M_PULL: "pull", M_PUSH: "push",
    M_WATERMARK: "watermark", M_WATERMARK_MULTI: "watermark_multi",
}


class ShmRingError(RuntimeError):
    """The ring is unusable (gone / timed out / payload too big) —
    the caller falls back to gRPC and drops the ring."""


class ShmRingTimeout(ShmRingError):
    """The response deadline passed — lets the caller count the
    fallback as `timeout` rather than `gone`."""


def same_host(host: str) -> bool:
    """Is `host` (the address-book host part of a data_addr) this
    machine? Loopback literals and our own hostname qualify; anything
    else is treated as remote — a false negative only costs the
    short-circuit, never correctness."""
    if not host:
        return False
    if host in ("127.0.0.1", "localhost", "::1", "[::1]", "0.0.0.0"):
        return True
    try:
        import socket
        return host == socket.gethostname()
    except Exception:
        # hostname unavailable -> "remote": costs only the
        # short-circuit, never correctness: edl-lint: disable=EDL303
        return False


def _np():
    import numpy as np
    return np


class _Ring:
    """Header + slot views over one attached/created segment."""

    def __init__(self, seg, slot_bytes: int):
        np = _np()
        self.seg = seg
        self.slot_bytes = int(slot_bytes)
        self.hdr = np.ndarray((8,), dtype=np.uint64, buffer=seg.buf)
        self.buf = seg.buf
        self.req_off = _HDR_BYTES
        self.resp_off = _HDR_BYTES + self.slot_bytes

    def write_slot(self, off: int, payload: bytes) -> None:
        self.buf[off:off + len(payload)] = payload

    def read_slot(self, off: int, n: int) -> bytes:
        return bytes(self.buf[off:off + n])


def _segment_size(slot_bytes: int) -> int:
    return _HDR_BYTES + 2 * slot_bytes


class ShmRingServer:
    """Owner side: creates ring segments on negotiation and serves each
    with a daemon poll thread dispatching into ``serve_fn(method,
    payload) -> (status, payload)`` (bound to the data-plane store by
    data_plane.EmbeddingDataServer)."""

    def __init__(self, serve_fn: Callable[[int, bytes],
                                          Tuple[int, bytes]],
                 tag: str = "", max_slot_bytes: int = MAX_SLOT_BYTES):
        self._serve_fn = serve_fn
        self._max_slot = int(max_slot_bytes)
        self._tag = tag or f"{os.getpid():x}"
        self._lock = threading.Lock()
        self._rings = {}              # name -> (_Ring, stop Event)
        self._counter = 0
        self._stopped = False

    def negotiate(self, slot_bytes: int) -> Optional[Tuple[str, int]]:
        """Create one ring for one client; returns (segment_name,
        granted_slot_bytes) or None when shm is unavailable/stopped."""
        if not HAVE_SHM or self._stopped:
            return None
        granted = max(1 << 12, min(int(slot_bytes) or DEFAULT_SLOT_BYTES,
                                   self._max_slot))
        with self._lock:
            self._counter += 1
            name = (f"edl_emb_{self._tag}_{self._counter}_"
                    f"{os.urandom(3).hex()}")
        try:
            seg = _shm_mod.SharedMemory(
                name=name, create=True, size=_segment_size(granted))
        except Exception as e:
            logger.warning("shm negotiate failed creating %s: %s",
                           name, e)
            return None
        ring = _Ring(seg, granted)
        ring.hdr[_I_MAGIC] = _MAGIC
        ring.hdr[_I_SLOT] = granted
        stop = threading.Event()
        t = threading.Thread(target=self._serve_ring,
                             args=(ring, stop),
                             name=f"edl-shm-{self._counter}",
                             daemon=True)
        with self._lock:
            self._rings[seg.name] = (ring, stop)
            SHM_RINGS.set(len(self._rings))
        t.start()
        return seg.name, granted

    def _serve_ring(self, ring: _Ring, stop: threading.Event) -> None:
        hdr = ring.hdr
        idle = 0
        last_sleep = 0.0
        rec = reqtrace.get_recorder()
        while not stop.is_set():
            req = int(hdr[_I_REQ_SEQ])
            if req == int(hdr[_I_RESP_SEQ]):
                idle += 1
                # adaptive poll: a short hot window catches a client's
                # back-to-back next call (the throughput regime keeps
                # idle pinned near 0), then exponential backoff to a
                # 1ms cadence — a ring serving intermittent traffic
                # must not sit at a 20us wakeup cadence between calls
                # or its poll threads starve everything else on a
                # small box, including the owner's own gRPC lane
                if idle < 16:
                    last_sleep = POLL_S
                else:
                    last_sleep = min(1e-3,
                                     POLL_S * (1 << min(8, idle >> 4)))
                time.sleep(last_sleep)
                continue
            idle = 0
            method = int(hdr[_I_REQ_METHOD])
            n = int(hdr[_I_REQ_LEN])
            payload = ring.read_slot(ring.req_off, n)
            # serve-side request diary: the request waited at most one
            # poll interval before we saw it — the honest serve_queue
            # bound this lane can observe; the dispatcher's codec/store
            # stages land via the thread-local stack
            d = rec.start("serve", lane="shm",
                          method=_METHOD_NAMES.get(method, str(method)))
            d.add("serve_queue", last_sleep)
            last_sleep = 0.0
            SHM_OCCUPANCY.add(1)
            try:
                status, out = self._serve_fn(method, payload)
            except Exception as e:
                status, out = S_ERROR, str(e).encode("utf-8")
            finally:
                SHM_OCCUPANCY.add(-1)
            if len(out) > ring.slot_bytes:
                status, out = S_ERROR, b"shm response exceeds slot"
            rec.finish(d, "ok" if status == S_OK else "error",
                       "" if status == S_OK
                       else out.decode("utf-8", "replace")[:128])
            ring.write_slot(ring.resp_off, out)
            hdr[_I_RESP_LEN] = len(out)
            hdr[_I_RESP_STATUS] = status
            hdr[_I_RESP_SEQ] = req          # publish

    def stop(self) -> None:
        with self._lock:
            rings, self._rings = dict(self._rings), {}
            self._stopped = True
            SHM_RINGS.set(0)
        for _name, (ring, stop) in rings.items():
            stop.set()
            try:
                ring.seg.close()
                ring.seg.unlink()
            except Exception:
                # segment already gone — nothing left to release:
                # edl-lint: disable=EDL303
                pass


class ShmRingClient:
    """Client side: attaches to a negotiated segment and round-trips
    serialized requests. Thread-safe via an in-process lock (one
    in-flight request per ring — SPSC)."""

    def __init__(self, name: str, slot_bytes: int):
        if not HAVE_SHM:
            raise ShmRingError("shared_memory unavailable")
        try:
            seg = _shm_mod.SharedMemory(name=name)
        except Exception as e:
            raise ShmRingError(f"attach {name}: {e}") from e
        # the OWNER holds the segment's lifetime; keep Python's
        # resource tracker from unlinking (and warning about) a
        # segment this process merely borrowed
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(seg._name,  # noqa: SLF001
                                        "shared_memory")
        except Exception:
            # tracker internals shifted — cosmetic only (a spurious
            # resource_tracker warning at exit):
            # edl-lint: disable=EDL303
            pass
        self._ring = _Ring(seg, slot_bytes)
        if int(self._ring.hdr[_I_MAGIC]) != _MAGIC:
            seg.close()
            raise ShmRingError(f"bad magic in {name}")
        self._name = name
        self.slot_bytes = int(slot_bytes)
        self._lock = threading.Lock()
        self._dead = False

    def _segment_exists(self) -> bool:
        """Is the owner's segment still linked? Our own mapping stays
        valid after an unlink, so a response deadline alone cannot
        distinguish a slow owner (`timeout`) from one that tore the
        lane down (`gone`)."""
        path = "/dev/shm/" + self._name.lstrip("/")
        if os.path.isdir("/dev/shm"):
            return os.path.exists(path)
        try:
            probe = _shm_mod.SharedMemory(name=self._name)
        except FileNotFoundError:
            return False
        except Exception:
            # probe failed for a reason OTHER than unlink — treat the
            # segment as alive; the caller's timeout label is the
            # conservative one: edl-lint: disable=EDL303
            return True
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(probe._name,  # noqa: SLF001
                                        "shared_memory")
        except Exception:
            # tracker internals shifted — cosmetic only (a spurious
            # resource_tracker warning at exit):
            # edl-lint: disable=EDL303
            pass
        probe.close()
        return True

    def call(self, method: int, payload: bytes,
             timeout_s: float = 1.0) -> Tuple[int, bytes]:
        if self._dead:
            raise ShmRingError("ring closed")
        if len(payload) > self.slot_bytes:
            raise ShmRingError(
                f"payload {len(payload)}B exceeds slot "
                f"{self.slot_bytes}B")
        SHM_CALLS.inc(method=_METHOD_NAMES.get(method, str(method)))
        with self._lock, reqtrace.stage("shm"):
            ring = self._ring
            hdr = ring.hdr
            try:
                seq = int(hdr[_I_REQ_SEQ]) + 1
                ring.write_slot(ring.req_off, payload)
                hdr[_I_REQ_LEN] = len(payload)
                hdr[_I_REQ_METHOD] = method
                hdr[_I_REQ_SEQ] = seq       # publish
                deadline = time.monotonic() + max(0.01, timeout_s)
                spins = 0
                while int(hdr[_I_RESP_SEQ]) != seq:
                    spins += 1
                    if spins > _SPIN_ITERS:
                        if time.monotonic() > deadline:
                            if not self._segment_exists():
                                raise ShmRingError(
                                    "ring segment unlinked under us")
                            raise ShmRingTimeout("ring response timeout")
                        # the lock IS the SPSC serialization: one
                        # in-flight request per ring, so the response
                        # wait holds it by design (deadline-bounded):
                        # edl-lint: disable=EDL103
                        time.sleep(POLL_S)
                status = int(hdr[_I_RESP_STATUS])
                out = ring.read_slot(ring.resp_off,
                                     int(hdr[_I_RESP_LEN]))
            except ShmRingError:
                raise
            except Exception as e:
                # segment yanked out from under us mid-call
                raise ShmRingError(f"ring I/O failed: {e}") from e
        SHM_READS.inc(method=_METHOD_NAMES.get(method, str(method)))
        return status, out

    def close(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            try:
                self._ring.seg.close()
            except Exception:
                # double-close on teardown races is harmless:
                # edl-lint: disable=EDL303
                pass
