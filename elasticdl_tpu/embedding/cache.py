"""Worker-local staleness-bounded hot-row cache (ISSUE 13, read layer 1).

The measurement that motivates it is PR 11's own telemetry: on the
zipf(1.3) bench stream the Space-Saving sketch reports
``hot_id_share ~= 0.69`` against a 0.11 dedupe ratio — a small head of
the id distribution carries most pull traffic, so a bounded worker-local
cache over that head absorbs most reads without touching the owning
shard (the Google ads training-infra trick, 2501.10546).

Freshness is **watermark-fenced**, not TTL'd: every owner shard counts
applied pushes (store.py ``_Shard.wm``), pulls and push acks carry the
count, and the client tracks the highest watermark it has OBSERVED per
(table, shard). A cached row tagged with the watermark at which it was
fetched is served only while

    ``entry_wm + staleness_bound >= observed_owner_wm``

i.e. the row is at most ``staleness_bound`` *pushes* behind what the
client knows the owner has absorbed. The unit is writes, not seconds: a
quiet table never goes stale, a hot one ages exactly as fast as it is
written. The bound is conservative — the watermark is per *shard*, so a
row can read stale because its neighbours were written — which keeps the
contract one-sided: a hit is never MORE than ``staleness_bound`` pushes
old, misses are merely wasted freshness.

Write-through keeps the worker's own training loop hot: after a push
acks, pushed rows whose cache entry was fresh as of the pre-push
watermark get the delta applied in place and re-tagged at the post-push
watermark (the common single-writer recsys case); entries that
interleaved with someone else's push are dropped instead of patched.

Everything is vectorized: per table the cache is a dense
``slot_of[vocab]`` index (int32 — 4 bytes/vocab-row, small next to the
table itself) plus slot-major rows/watermark/recency arrays, so a batch
lookup is a handful of numpy gathers, never a Python loop over ids.
Eviction is batch-LRU: recency ticks advance per lookup, and an
over-full insert evicts the oldest-ticked slots via one argpartition.

Invalidation is all-or-nothing on shard-map change: a reshard commit or
map-epoch bump re-keys shard ownership AND watermark history, so the
client drops the whole cache (`invalidate_all`) rather than reason about
which entries survive — correctness over warmth, reshards are rare.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional, Tuple

import numpy as np

from elasticdl_tpu.observability.registry import default_registry

_reg = default_registry()
_HITS = _reg.counter(
    "edl_embedding_cache_hits_total",
    "pull ids (occurrence-weighted) served from the worker-local "
    "hot-row cache")
_MISSES = _reg.counter(
    "edl_embedding_cache_misses_total",
    "pull ids (occurrence-weighted) that went to the owning shard")
_STALE_EVICTIONS = _reg.counter(
    "edl_embedding_cache_stale_evictions_total",
    "cached rows evicted by the watermark staleness fence")
_INVALIDATIONS = _reg.counter(
    "edl_embedding_cache_invalidations_total",
    "full cache drops (reshard commit / shard-map epoch change)")

#: recent-lookup window backing the heartbeat payload's cache hit rate
#: (cumulative counters cannot forget a cold start — a hot-set migration
#: must show up as a FRESH collapse, which is what the alert rule reads)
RECENT_WINDOW = 128


class _TableCache:
    """One table's slot store (all arrays slot-major; no per-id Python).

    Guarded by the owning HotRowCache's lock."""

    __slots__ = ("vocab", "dim", "capacity", "slot_of", "ids", "rows",
                 "wm", "tick_of", "free", "tick")

    def __init__(self, vocab: int, dim: int, capacity: int):
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.slot_of = np.full(self.vocab, -1, np.int32)
        self.ids = np.full(self.capacity, -1, np.int64)
        self.rows = np.zeros((self.capacity, self.dim), np.float32)
        self.wm = np.zeros(self.capacity, np.int64)
        self.tick_of = np.zeros(self.capacity, np.int64)
        self.free = list(range(self.capacity - 1, -1, -1))
        self.tick = 0

    def _evict_slots(self, slots: np.ndarray) -> None:
        if not slots.size:
            return
        self.slot_of[self.ids[slots]] = -1
        self.ids[slots] = -1
        self.free.extend(int(s) for s in slots)


class HotRowCache:
    """Staleness-bounded LRU over hot embedding rows, one slot store per
    table. ``staleness_bound`` is in push-watermark units (see module
    doc); ``capacity_rows`` bounds EACH table's slots (the per-table hot
    set is what the sketch sizes — docs/performance.md "Embedding read
    path")."""

    def __init__(self, capacity_rows: int, staleness_bound: int = 1):
        if capacity_rows <= 0:
            raise ValueError("capacity_rows must be > 0 (0 = cache off: "
                             "don't construct one)")
        if staleness_bound < 0:
            raise ValueError("staleness_bound must be >= 0")
        self.capacity_rows = int(capacity_rows)
        self.staleness_bound = int(staleness_bound)
        self._lock = threading.Lock()
        self._tables: Dict[str, _TableCache] = {}      # guarded_by: _lock
        # rolling (hits, total) per lookup — recent hit rate
        self._recent: "deque" = deque(maxlen=RECENT_WINDOW)  # guarded_by: _lock
        self.hits = 0          # occurrence-weighted, cumulative
        self.misses = 0
        self.stale_evictions = 0

    def _table_locked(self, name: str, vocab: int,
                      dim: int) -> _TableCache:  # holds: _lock
        tc = self._tables.get(name)
        if tc is None:
            tc = _TableCache(vocab, dim, self.capacity_rows)
            self._tables[name] = tc
        return tc

    # -------------------------------------------------------------- #

    def lookup(
        self, table: str, vocab: int, dim: int, uniq: np.ndarray,
        owner_wm: np.ndarray, num_shards: int,
        counts: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Batch probe: ``(hit_mask, rows_for_hits)`` over sorted-unique
        in-range ``uniq``. ``owner_wm`` is the client's per-shard
        observed-watermark array; entries past the staleness fence are
        evicted (counted) and read as misses. ``counts`` weights the
        hit/miss accounting by raw occurrences — the cache exists to
        absorb *traffic*, so its hit rate is traffic-weighted."""
        with self._lock:
            tc = self._table_locked(table, vocab, dim)
            tc.tick += 1
            slots = tc.slot_of[uniq]
            found = slots >= 0
            hit_mask = np.zeros(uniq.shape[0], bool)
            rows = None
            if found.any():
                fidx = np.flatnonzero(found)
                fs = slots[fidx].astype(np.int64)
                shards = uniq[fidx] % num_shards
                fresh = (tc.wm[fs] + self.staleness_bound
                         >= owner_wm[shards])
                stale_slots = fs[~fresh]
                if stale_slots.size:
                    tc._evict_slots(stale_slots)
                    n_stale = int(stale_slots.size)
                    self.stale_evictions += n_stale
                    _STALE_EVICTIONS.inc(n_stale)
                hit_idx = fidx[fresh]
                hit_mask[hit_idx] = True
                hs = fs[fresh]
                rows = tc.rows[hs].copy()
                tc.tick_of[hs] = tc.tick
            if counts is None:
                h = int(hit_mask.sum())
                m = int(uniq.shape[0] - h)
            else:
                h = int(counts[hit_mask].sum())
                m = int(counts.sum()) - h
            self.hits += h
            self.misses += m
            _HITS.inc(h)
            _MISSES.inc(m)
            self._recent.append((h, h + m))
            return hit_mask, rows

    def insert(self, table: str, vocab: int, dim: int, ids: np.ndarray,
               rows: np.ndarray, wms: np.ndarray) -> None:
        """Admit freshly-pulled rows tagged with the watermark their
        serving response carried (per-id — rows from different shards
        land at different watermarks). Overwrites resident entries in
        place; over-capacity admission evicts the oldest-ticked slots."""
        if not ids.size:
            return
        with self._lock:
            tc = self._table_locked(table, vocab, dim)
            slots = tc.slot_of[ids]
            have = slots >= 0
            hs = slots[have].astype(np.int64)
            tc.rows[hs] = rows[have]
            tc.wm[hs] = wms[have]
            tc.tick_of[hs] = tc.tick
            need_idx = np.flatnonzero(~have)
            n = need_idx.size
            if not n:
                return
            if n > tc.capacity:
                # admit only the LAST capacity rows (arbitrary but
                # deterministic); a batch larger than the whole cache
                # cannot be fully resident anyway
                need_idx = need_idx[-tc.capacity:]
                n = tc.capacity
            short = n - len(tc.free)
            if short > 0:
                occupied = np.flatnonzero(tc.ids >= 0)
                oldest = occupied[np.argpartition(
                    tc.tick_of[occupied], short - 1)[:short]]
                tc._evict_slots(oldest)
            # C-speed bulk pop off the free stack (a per-slot .pop()
            # loop measured 2.6 ms per batch — the cache must not cost
            # what it saves)
            take = np.asarray(tc.free[len(tc.free) - n:], np.int64)
            del tc.free[len(tc.free) - n:]
            tc.ids[take] = ids[need_idx]
            tc.rows[take] = rows[need_idx]
            tc.wm[take] = wms[need_idx]
            tc.tick_of[take] = tc.tick
            tc.slot_of[ids[need_idx]] = take.astype(np.int32)

    def write_through(
        self, table: str, ids: np.ndarray, deltas: np.ndarray,
        num_shards: int, prev_wm: np.ndarray, new_wm: np.ndarray,
    ) -> None:
        """The worker's own push landed: patch pushed rows in place.

        Sound only for entries that were fresh as of the pre-push
        watermark AND whose shard advanced by exactly our one push
        (``new_wm == prev_wm + 1``): then ``cached + delta`` IS the row
        at ``new_wm``. Anything else — an interleaved foreign push, an
        entry fetched before other writes — is dropped, not patched; it
        would otherwise be re-tagged fresh while missing writes."""
        if not ids.size:
            return
        with self._lock:
            tc = self._tables.get(table)
            if tc is None:
                return
            slots = tc.slot_of[ids]
            have = slots >= 0
            if not have.any():
                return
            hidx = np.flatnonzero(have)
            hs = slots[hidx].astype(np.int64)
            shards = ids[hidx] % num_shards
            clean = ((new_wm[shards] == prev_wm[shards] + 1)
                     & (tc.wm[hs] == prev_wm[shards]))
            cs = hs[clean]
            tc.rows[cs] += deltas[hidx[clean]]
            tc.wm[cs] = new_wm[shards[clean]]
            tc.tick_of[cs] = tc.tick
            tc._evict_slots(hs[~clean])

    def invalidate_all(self) -> None:
        """Shard-map change: ownership and watermark history re-keyed —
        drop everything (reshard commit / map epoch bump / promotion)."""
        with self._lock:
            self._tables.clear()
            self._recent.clear()
        _INVALIDATIONS.inc()

    # -------------------------------------------------------------- #

    def hit_rate(self) -> float:
        """Traffic-weighted hit rate over the recent lookup window (the
        heartbeat/alert signal: a hot-set migration collapses THIS, even
        hours into a job whose lifetime counters look fine)."""
        with self._lock:
            h = sum(x for x, _ in self._recent)
            t = sum(x for _, x in self._recent)
        return (h / t) if t else 0.0

    def stats(self) -> Dict[str, float]:
        with self._lock:
            resident = sum(
                int((tc.ids >= 0).sum()) for tc in self._tables.values())
        total = self.hits + self.misses
        return {
            "hits": self.hits, "misses": self.misses,
            "stale_evictions": self.stale_evictions,
            "resident_rows": resident,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "recent_hit_rate": round(self.hit_rate(), 4),
        }
