"""Space-Saving heavy-hitter sketch: bounded top-K over an id stream.

The embedding tier's whole economics ride the id distribution — the
bench's zipf(1.3) stream dedupes to 0.11 of its raw traffic, which means
a small head of hot ids absorbs most pulls. The hot-row cache and read
replicas (ROADMAP 1) need that skew MEASURED, not assumed: which ids are
hot, and what share of traffic they carry, at bounded memory.

This is Metwally et al.'s Space-Saving algorithm (the same structure the
Google ads training-infra paper's hot-id caching presupposes): k
counters; a hit increments its counter; a miss on a full sketch evicts
the minimum counter and inherits its count as the new entry's ERROR
bound. Guarantees, for any stream of total weight N:

- every id with true count > N/k is in the sketch;
- each tracked count overestimates by at most its recorded `error`
  (so `count - error` is a guaranteed lower bound on the true count).

`hot_share()` therefore reports a LOWER bound on the share of traffic
the top-K ids carry — the conservative number to size a cache from.

Implementation notes: updates are O(1) amortized via a lazy min-heap
(stale entries skipped at eviction, compacted when the heap outgrows
4x the sketch); `update_batch` takes the (unique ids, counts) arrays the
tier's pull path already computes, so the per-pull cost is one dict op
per UNIQUE id — off the jit path, and gated by `bench.py obs_overhead`.
Thread-safe under one leaf lock.
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, List, Tuple

#: default tracked heads — enough to cover the zipf head that matters
#: for caching, small enough that the sketch is a few KB
K_DEFAULT = 128


class SpaceSaving:
    """Bounded top-K counter sketch with guaranteed error bounds."""

    def __init__(self, k: int = K_DEFAULT):
        self.k = max(1, int(k))
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}   # id -> count   guarded_by: _lock
        self._errors: Dict[int, int] = {}   # id -> error   guarded_by: _lock
        self._heap: List[Tuple[int, int]] = []  # (count, id) lazy min-heap
        self.total = 0                       # stream weight  guarded_by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._counts)

    # ------------------------------------------------------------------ #

    def update(self, key: int, inc: int = 1) -> None:
        if inc <= 0:
            return
        with self._lock:
            self._update_locked(int(key), int(inc))

    def update_batch(self, ids, counts=None) -> None:
        """Feed (unique) ids with optional per-id counts — the shapes the
        tier's `np.unique(..., return_counts=True)` already produces.
        `.tolist()` converts the whole array to native ints in C (a
        per-element int() would triple the loop's cost — this path runs
        once per pull and is gated by bench.py obs_overhead)."""
        if hasattr(ids, "tolist"):
            ids = ids.tolist()
        if counts is not None and hasattr(counts, "tolist"):
            counts = counts.tolist()
        with self._lock:
            # the HIT path is inlined: on a skewed stream most weight
            # lands on already-tracked heads, and a per-id method call
            # would dominate the loop (obs_overhead-gated)
            counts_d = self._counts
            total = 0
            if counts is None:
                for key in ids:
                    cur = counts_d.get(key)
                    if cur is not None:
                        counts_d[key] = cur + 1
                        total += 1
                    else:
                        self._update_locked(key, 1)
            else:
                for key, inc in zip(ids, counts):
                    if inc <= 0:
                        continue
                    cur = counts_d.get(key)
                    if cur is not None:
                        counts_d[key] = cur + inc
                        total += inc
                    else:
                        self._update_locked(key, inc)
            self.total += total

    def _update_locked(self, key: int, inc: int) -> None:
        """O(1) dict bump on a HIT (the common case on a skewed stream —
        the heap entry goes stale and now under-states the true count, a
        lower bound eviction repairs lazily); O(log k) on insert/evict.
        The heap never exceeds k entries: hits push nothing, inserts
        push one, evictions pop one and push one, refreshes are
        heapreplace (size-neutral)."""
        self.total += inc
        cur = self._counts.get(key)
        if cur is not None:
            self._counts[key] = cur + inc
        elif len(self._counts) < self.k:
            self._counts[key] = inc
            self._errors[key] = 0
            heapq.heappush(self._heap, (inc, key))
        else:
            # find the true minimum: every heap entry is a LOWER bound on
            # its key's current count, so a top entry matching its live
            # count IS the global min (all other keys' counts >= their
            # own heap entries >= this one)
            heap = self._heap
            while True:
                c, k2 = heap[0]
                live = self._counts.get(k2)
                if live == c:
                    break
                # stale bound: refresh in place and re-examine the top
                heapq.heapreplace(heap, (live, k2))
            heapq.heappop(heap)
            del self._counts[k2]
            del self._errors[k2]
            self._counts[key] = c + inc
            self._errors[key] = c
            heapq.heappush(heap, (c + inc, key))

    # ------------------------------------------------------------------ #

    def top(self, n: int = 0) -> List[Tuple[int, int, int]]:
        """[(id, count, error)] sorted by count descending; n=0 = all
        tracked. `count` overestimates by at most `error`."""
        with self._lock:
            items = sorted(
                ((i, c, self._errors[i]) for i, c in self._counts.items()),
                key=lambda t: (-t[1], t[0]),
            )
        return items[:n] if n else items

    def hot_share(self, n: int = 0) -> float:
        """Guaranteed LOWER bound on the share of stream weight carried
        by the top-n tracked ids (n=0 = all k): sum(count - error) /
        total. 0.0 on an empty stream."""
        with self._lock:
            if self.total <= 0:
                return 0.0
            guaranteed = sorted(
                (c - self._errors[i] for i, c in self._counts.items()),
                reverse=True,
            )
            take = guaranteed[:n] if n else guaranteed
            return max(0.0, min(1.0, sum(take) / self.total))

    def decay(self, factor: float) -> None:
        """Scale every tracked count (and error bound, and the stream
        total) by ``factor`` in [0, 1) — the exponential-decay variant a
        POPULARITY FLIP needs (ISSUE 20): a job-lifetime cumulative
        sketch lets yesterday's head dominate `hot_share` for hours
        after the distribution moved, and a layout controller chasing
        that ghost would replicate cold shards. Halving preserves both
        guarantees on the decayed stream: counts and errors scale
        together, so `count - error` stays a lower bound on the decayed
        true count, and the share ratio is scale-invariant. Entries
        decayed to zero are dropped (they carry no information and would
        pin heap slots)."""
        with self._lock:
            self._decay_locked(min(0.999, max(0.0, float(factor))))

    def _decay_locked(self, factor: float) -> None:  # holds: _lock
        dead = []
        for key, c in self._counts.items():
            nc = int(c * factor)
            if nc <= 0:
                dead.append(key)
            else:
                self._counts[key] = nc
                self._errors[key] = int(self._errors[key] * factor)
        for key in dead:
            del self._counts[key]
            del self._errors[key]
        # every heap bound went stale at once: rebuild instead of paying
        # k lazy repairs on the next k evictions
        self._heap = [(c, i) for i, c in self._counts.items()]
        heapq.heapify(self._heap)
        self.total = int(self.total * factor)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._errors.clear()
            self._heap = []
            self.total = 0


class DecayingSpaceSaving(SpaceSaving):
    """A SpaceSaving sketch that halves itself every `window` stream
    weight — the RECENT view of the hot set (ISSUE 20). With the stream
    total capped near ``2 * window``, an id that stops appearing loses
    half its tracked weight per window of new traffic: after a
    popularity flip the new head overtakes the old one within a couple
    of windows instead of hours (pinned by the flip-then-converge test).
    The decayed sketch keeps the Space-Saving guarantees relative to the
    decayed stream, so `hot_share()` stays a conservative cache-sizing
    bound — now of recent traffic rather than the job's whole life."""

    def __init__(self, k: int = K_DEFAULT, window: int = 1 << 16):
        super().__init__(k)
        self.window = max(1, int(window))

    def _update_locked(self, key: int, inc: int) -> None:
        super()._update_locked(key, inc)
        if self.total > 2 * self.window:
            self._decay_locked(0.5)

    def update_batch(self, ids, counts=None) -> None:
        super().update_batch(ids, counts)
        with self._lock:
            if self.total > 2 * self.window:
                self._decay_locked(0.5)
