"""The owner side of the embedding tier: dense per-shard tables served
with one fused gather per pull and one deduped scatter-add per push.

Reference parity: the Go PS's per-pod embedding hash map + row-by-row
sparse optimizer (elasticdl/pkg/ps/embedding.go, optimizer.go). Rebuilt
dense: shard s of table T is ONE (rows, dim) array addressed by
`local = id // num_shards`, so a pull is a single take and a push is one
scatter-add routed through the SAME strategy menu as the training
backward (ops/embedding.scatter_add_dense — pallas placement kernel with
the skew-dedupe middle path, tiled fast-zone scan, ...). Per-shard
outputs are `vocab/num_shards` rows, which is what keeps the scatter
inside the measured fast zone at production vocab sizes — the sharding
is itself the perf fix, not just capacity (BASELINE.md round-5 scatter
cliff).

Two serving modes, selected once per store (EDL_EMB_TIER_DEVICE
overrides; default = device on TPU backends, host elsewhere):

- **device**: shard rows live as jax Arrays; pull is the jitted fused
  gather (ops/embedding.gather_rows) and push routes the dense delta
  through `scatter_add_dense` — the pallas placement kernel's lane on
  real chips, where the dense-blocked formulation IS the fast path
  (BASELINE.md round-5). Request shapes are POW2-PADDED by the client
  (tier.py) so the jitted programs stay in a handful of compile-cache
  entries per table; the cache is the process-global one
  (training/compile_cache), so a shard migrating onto a new owner in
  the same process class finds its programs already compiled — warm
  resharding rides the compile cache.
- **host**: shard rows live as numpy; pull is one `take`, push is one
  in-place deduped scatter-add (sorted segment reduce, then a unique-
  index fancy add) — cost scales with TOUCHED rows, not shard size,
  which is what host-memory serving needs (a functional device update
  would copy the whole shard per push).

Exactly-once pushes: every push carries ``(client_id, seq)`` with seq
strictly increasing per client; the store keeps the last applied seq per
(table, shard, client) and re-sends (client retries after a lost ack, or
requeues after an interrupted resharding) come back ``applied=False``
without touching the table. The seq watermarks TRAVEL with the shard
(`extract_shard` / `install_shard` / checkpoint files), so migration and
restore preserve the fence.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.embedding import sharding
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_PULLED = _reg.counter(
    "edl_embedding_store_pulled_rows_total",
    "rows served by owner stores", labels=("table",))
_PUSHED = _reg.counter(
    "edl_embedding_store_pushed_rows_total",
    "deduped update rows applied by owner stores", labels=("table",))
_DUP_PUSHES = _reg.counter(
    "edl_embedding_store_duplicate_pushes_total",
    "pushes deduplicated by the exactly-once sequence fence")
_STALE = _reg.counter(
    "edl_embedding_store_stale_map_rejects_total",
    "pulls/pushes rejected for a stale shard-map version")
_SHARDS = _reg.gauge(
    "edl_embedding_store_shards", "shards resident in this process's store")
# per-shard skew telemetry (ISSUE 11): label cardinality is bounded by
# --embedding_shards x registered tables x {pull,push} — a config
# constant, not data (the EDL405 boundary)
_SHARD_ROWS = _reg.counter(
    "edl_embedding_store_shard_load_rows_total",
    "rows served (pull) / applied (push) per resident shard",
    labels=("table", "shard", "op"))
_OP_S = _reg.histogram(
    "edl_embedding_store_op_seconds",
    "owner-side serve wall time per call", labels=("op",))


class StaleShardMapError(RuntimeError):
    """The caller's shard-map version does not match the store's (or the
    shard is not resident here) — refresh the map and re-route."""


class _Shard:
    """One resident shard: the dense local table + the exactly-once
    per-client sequence watermarks (mutations guarded by the store lock
    at the serving layer; the apply itself runs outside it)."""

    __slots__ = ("rows", "applied", "lock")

    def __init__(self, rows, applied: Optional[Dict[str, int]] = None):
        self.rows = rows                      # jax.Array (num_rows, dim)
        self.applied: Dict[str, int] = dict(applied or {})
        # per-shard leaf lock: pull/push on DIFFERENT shards never
        # serialize behind each other (the store lock only guards the
        # shard directory)
        self.lock = threading.Lock()


def _init_shard_rows(spec: sharding.TableSpec, shard: int,
                     num_shards: int) -> np.ndarray:
    """Deterministic shard materialization: bit-identical wherever it is
    built (fresh bootstrap needs no transfer; a dead owner's shard can be
    re-materialized only if it was never pushed to — otherwise the
    checkpoint is the source of truth)."""
    rows = sharding.shard_row_count(spec.vocab, num_shards)
    # crc32, NOT hash(): Python's str hash is salted per process
    # (PYTHONHASHSEED), and shard materialization must be bit-identical
    # ACROSS processes — the same pitfall EDL204 documents for set order
    rng = np.random.default_rng(
        np.random.SeedSequence(
            [spec.seed, zlib.crc32(spec.name.encode()), shard]))
    out = rng.uniform(-spec.init_scale, spec.init_scale,
                      (rows, spec.dim)).astype(np.float32)
    # rows past the padded vocab's tail never map to a real id but are
    # part of the dense shard; zero them so accounting sums stay honest
    first_dead = -(-max(0, spec.vocab - shard) // num_shards)
    out[first_dead:] = 0.0
    return out


def _default_device_mode() -> Optional[bool]:
    env = os.environ.get("EDL_EMB_TIER_DEVICE", "")
    if env in ("0", "1"):
        return env == "1"
    return None


class EmbeddingShardStore:
    """Shards this worker owns, served to tier clients via a transport."""

    def __init__(self, owner: int, compile_cache=None,
                 device: Optional[bool] = None):
        self.owner = owner
        self._lock = threading.Lock()
        self._tables: Dict[str, sharding.TableSpec] = {}  # guarded_by: _lock
        self._num_shards = 0                              # guarded_by: _lock
        self._map_version = 0                             # guarded_by: _lock
        self._shards: Dict[Tuple[str, int], _Shard] = {}  # guarded_by: _lock
        if device is None:
            device = _default_device_mode()
        # None = decide lazily at the first shard materialization (the
        # jax import / backend probe must not be paid by stores that are
        # constructed but never used)
        self._device_mode = device
        if compile_cache is None:
            from elasticdl_tpu.training import compile_cache as cc

            compile_cache = cc.global_cache()
        self._cache = compile_cache

    def _use_device(self) -> bool:
        if self._device_mode is None:
            import jax

            self._device_mode = jax.default_backend() == "tpu"
        return self._device_mode

    def _place(self, rows: np.ndarray):
        """Host array -> the store's serving format: a device-resident
        jax.Array in device mode, a mutable owned numpy array in host
        mode (the in-place scatter must never write a caller's buffer)."""
        if self._use_device():
            import jax

            return jax.device_put(rows)
        return np.array(rows, np.float32, copy=True)

    # -------------------------------------------------------------- #
    # map adoption / shard lifecycle

    def attach(self, view: sharding.ShardMapView,
               checkpoint_dir: str = "") -> List[int]:
        """Adopt a shard-map view: register its tables, materialize every
        owned-but-missing shard (from the tier checkpoint when present,
        else deterministically from the table seed), and adopt the map
        version. Shards this view assigns elsewhere are NOT dropped here —
        the donor keeps them until the migration commits (reshard.py
        releases them). Returns the shard ids freshly materialized."""
        created: List[int] = []
        with self._lock:
            self._num_shards = view.num_shards
            self._map_version = view.version
            for spec in view.tables:
                self._tables[spec.name] = spec
            owned = [s for s, o in enumerate(view.owners)
                     if o == self.owner]
            for spec in view.tables:
                for s in owned:
                    if (spec.name, s) in self._shards:
                        continue
                    rows = None
                    if checkpoint_dir:
                        payload = load_shard_file(
                            checkpoint_dir, spec.name, s)
                        if payload is not None:
                            self._shards[(spec.name, s)] = _Shard(
                                self._place(payload["rows"]),
                                payload["applied"],
                            )
                            created.append(s)
                            continue
                    rows = _init_shard_rows(spec, s, view.num_shards)
                    self._shards[(spec.name, s)] = _Shard(self._place(rows))
                    created.append(s)
            _SHARDS.set(len(self._shards))
        return created

    def adopt_version(self, version: int) -> None:
        with self._lock:
            self._map_version = version

    @property
    def map_version(self) -> int:
        with self._lock:
            return self._map_version

    def resident_shards(self, table: Optional[str] = None) -> List[Tuple[str, int]]:
        with self._lock:
            return [k for k in self._shards
                    if table is None or k[0] == table]

    def _get_shard(self, table: str, shard: int,
                   map_version: Optional[int]) -> _Shard:
        with self._lock:
            if (map_version is not None
                    and map_version != self._map_version):
                _STALE.inc()
                raise StaleShardMapError(
                    f"shard map v{map_version} (store at "
                    f"v{self._map_version})"
                )
            sh = self._shards.get((table, shard))
        if sh is None:
            _STALE.inc()
            raise StaleShardMapError(
                f"shard {table}/{shard} not resident on owner {self.owner}"
            )
        return sh

    # -------------------------------------------------------------- #
    # data plane

    def pull(self, table: str, shard: int, local_ids: np.ndarray,
             map_version: Optional[int] = None) -> np.ndarray:
        """One fused gather: (n,) local row ids -> (n, dim) rows.
        Out-of-range ids (the client's pow2 padding sentinels) return
        zero rows."""
        t0 = time.perf_counter()
        sh = self._get_shard(table, shard, map_version)
        ids = np.ascontiguousarray(np.asarray(local_ids, np.int32))
        with sh.lock:
            rows = sh.rows
        if self._use_device():
            out = np.asarray(
                self._pull_fn(rows.shape, ids.shape[0])(rows, ids))
        else:
            in_range = (ids >= 0) & (ids < rows.shape[0])
            out = rows.take(np.where(in_range, ids, 0), axis=0)
            out[~in_range] = 0.0
        # REAL rows only: the request is pow2-padded with -1 sentinels
        # (min bucket 256), and counting the padding would inflate the
        # traffic counters operators size capacity from
        real = int((ids >= 0).sum())
        _PULLED.inc(real, table=table)
        _SHARD_ROWS.inc(real, table=table, shard=str(shard), op="pull")
        _OP_S.observe(time.perf_counter() - t0, op="pull")
        return out

    def push(self, table: str, shard: int, local_ids: np.ndarray,
             rows: np.ndarray, *, client_id: str, seq: int,
             map_version: Optional[int] = None,
             scale: float = 1.0) -> bool:
        """One deduped scatter-add: ``shard_table += scale * sum(rows at
        local_ids)``. Returns False (without touching the table) when the
        exactly-once fence says ``(client_id, seq)`` was already applied
        — the ack a retried/requeued push gets."""
        t0 = time.perf_counter()
        sh = self._get_shard(table, shard, map_version)
        ids = np.ascontiguousarray(np.asarray(local_ids, np.int32))
        vals = np.ascontiguousarray(np.asarray(rows, np.float32))
        with sh.lock:
            last = sh.applied.get(client_id, -1)
            if seq <= last:
                _DUP_PUSHES.inc()
                return False
            if self._use_device():
                sh.rows = self._apply_fn(sh.rows.shape, ids.shape[0])(
                    sh.rows, ids, vals, np.float32(scale))
            else:
                self._host_apply(sh.rows, ids, vals, scale)
            sh.applied[client_id] = seq
        # real (non-sentinel) rows only — see the pull counter note
        real = int((ids >= 0).sum())
        _PUSHED.inc(real, table=table)
        _SHARD_ROWS.inc(real, table=table, shard=str(shard), op="push")
        _OP_S.observe(time.perf_counter() - t0, op="push")
        return True

    @staticmethod
    def _host_apply(tab: np.ndarray, ids: np.ndarray, vals: np.ndarray,
                    scale: float) -> None:
        """In-place scatter-add, O(touched rows). Out-of-range ids
        (padding sentinels) drop. Two regimes:

        - UNIQUE ids (a deduping client — tier.py sums duplicates before
          sending): one vectorized fancy-index add. This is the fast
          path the client-side dedupe exists to unlock.
        - duplicate ids (a non-deduping client): ``np.add.at`` — the
          row-serial accumulate that is numpy's honest general primitive
          for colliding indices, and the faithful stand-in for the
          reference PS's per-row hash-map apply
          (elasticdl/pkg/ps/optimizer.go). Its cost IS the per-row
          traffic the deduped protocol removes; the bench's single-host
          baseline measures it on purpose.
        """
        keep = (ids >= 0) & (ids < tab.shape[0])
        ids, vals = ids[keep], vals[keep]
        if not ids.shape[0]:
            return
        # sorted-unique probe without a full unique(): the deduping
        # client sends SORTED unique ids, so one vectorized monotonicity
        # check identifies the fast path
        sorted_unique = bool(np.all(ids[1:] > ids[:-1]))
        if sorted_unique:
            tab[ids] += scale * vals
        else:
            np.add.at(tab, ids, scale * vals)

    # -------------------------------------------------------------- #
    # jitted programs (compile-cache keyed: warm resharding finds them)

    def _pull_fn(self, table_shape, n):
        key = ("emb_tier_pull", table_shape, int(n))

        def build():
            import jax
            import jax.numpy as jnp

            from elasticdl_tpu.ops import embedding as emb_ops

            def f(tab, ids):
                in_range = (ids >= 0) & (ids < tab.shape[0])
                safe = jnp.where(in_range, ids, 0)
                out = emb_ops.gather_rows(tab, safe)
                return jnp.where(in_range[:, None], out, 0.0)

            return jax.jit(f)

        return self._cache.get_or_build(key, build)

    def _apply_fn(self, table_shape, n):
        key = ("emb_tier_apply", table_shape, int(n))

        def build():
            import jax

            from elasticdl_tpu.ops import embedding as emb_ops

            def f(tab, ids, vals, scale):
                delta = emb_ops.scatter_add_dense(
                    ids, vals, tab.shape[0], dtype=tab.dtype)
                return tab + scale * delta

            # NOT donated: a concurrent pull on the same shard may still
            # hold the old rows array (the per-shard lock scopes the
            # rows SWAP, not the gather's execution) — donation would
            # invalidate the buffer under it
            return jax.jit(f)

        return self._cache.get_or_build(key, build)

    # -------------------------------------------------------------- #
    # migration / checkpoint payloads

    def extract_shard(self, table: str, shard: int) -> Dict[str, Any]:
        """The migration payload: rows + exactly-once watermarks. The
        shard stays resident (the donor serves reads until the move
        commits); `release_shard` drops it afterwards."""
        sh = self._get_shard(table, shard, None)
        with sh.lock:
            return {
                # copy, not a view: in host mode the live array mutates
                # in place under later pushes — a payload must be a
                # point-in-time snapshot
                "rows": np.array(sh.rows, np.float32, copy=True),
                "applied": dict(sh.applied),
            }

    def install_shard(self, table: str, shard: int,
                      payload: Dict[str, Any]) -> None:
        with self._lock:
            self._shards[(table, shard)] = _Shard(
                self._place(np.asarray(payload["rows"], np.float32)),
                {str(k): int(v) for k, v in payload["applied"].items()},
            )
            _SHARDS.set(len(self._shards))

    def release_shard(self, table: str, shard: int) -> None:
        with self._lock:
            self._shards.pop((table, shard), None)
            _SHARDS.set(len(self._shards))

    # -------------------------------------------------------------- #
    # sharded save/restore (training/checkpoint.py delegates here)

    def save(self, directory: str, tables: Optional[List[str]] = None) -> int:
        """Write every resident shard (of `tables`, default all) as one
        atomic file each; returns how many were written. Layout:
        ``<dir>/emb/<table>-shard<id>.npz`` with the rows and the
        exactly-once watermarks — a restore resumes the fence, so a push
        replayed from before the save still dedupes."""
        written = 0
        for table, shard in self.resident_shards():
            if tables is not None and table not in tables:
                continue
            payload = self.extract_shard(table, shard)
            save_shard_file(directory, table, shard, payload)
            written += 1
        return written

    def restore_missing(self, directory: str) -> int:
        """Install any checkpointed shard for this owner's current map
        that is not yet resident (kill-worker recovery path); returns how
        many were restored. Shards with no file stay absent — attach()
        decides whether to re-materialize from seed."""
        restored = 0
        with self._lock:
            tables = dict(self._tables)
            num_shards = self._num_shards
        for table in tables:
            for shard in range(num_shards):
                with self._lock:
                    if (table, shard) in self._shards:
                        continue
                payload = load_shard_file(directory, table, shard)
                if payload is not None:
                    self.install_shard(table, shard, payload)
                    restored += 1
        return restored




# ------------------------------------------------------------------ #
# shard files (atomic tmp+replace; EDL305 discipline)


def _shard_path(directory: str, table: str, shard: int) -> str:
    return os.path.join(directory, "emb", f"{table}-shard{shard:05d}.npz")


def save_shard_file(directory: str, table: str, shard: int,
                    payload: Dict[str, Any]) -> str:
    path = _shard_path(directory, table, shard)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    buf = io.BytesIO()
    np.savez(
        buf, rows=np.asarray(payload["rows"], np.float32),
        applied=np.frombuffer(
            json.dumps(payload["applied"]).encode(), np.uint8),
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
        f.flush()
        # a torn shard file would restore silently-wrong rows; fsync +
        # atomic replace, same contract as the control-plane journal:
        # edl-lint: disable=EDL403
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_shard_file(directory: str, table: str,
                    shard: int) -> Optional[Dict[str, Any]]:
    path = _shard_path(directory, table, shard)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            rows = z["rows"]
            applied = json.loads(bytes(z["applied"]).decode())
    except (OSError, ValueError, KeyError):
        logger.exception("embedding shard file %s unreadable; ignored", path)
        return None
    return {"rows": rows, "applied": {str(k): int(v)
                                      for k, v in applied.items()}}
