"""The owner side of the embedding tier: dense per-shard tables served
with one fused gather per pull and one deduped scatter-add per push.

Reference parity: the Go PS's per-pod embedding hash map + row-by-row
sparse optimizer (elasticdl/pkg/ps/embedding.go, optimizer.go). Rebuilt
dense: shard s of table T is ONE (rows, dim) array addressed by
`local = id // num_shards`, so a pull is a single take and a push is one
scatter-add routed through the SAME strategy menu as the training
backward (ops/embedding.scatter_add_dense — pallas placement kernel with
the skew-dedupe middle path, tiled fast-zone scan, ...). Per-shard
outputs are `vocab/num_shards` rows, which is what keeps the scatter
inside the measured fast zone at production vocab sizes — the sharding
is itself the perf fix, not just capacity (BASELINE.md round-5 scatter
cliff).

Two serving modes, selected once per store (EDL_EMB_TIER_DEVICE
overrides; default = device on TPU backends, host elsewhere):

- **device**: shard rows live as jax Arrays; pull is the jitted fused
  gather (ops/embedding.gather_rows) and push routes the dense delta
  through `scatter_add_dense` — the pallas placement kernel's lane on
  real chips, where the dense-blocked formulation IS the fast path
  (BASELINE.md round-5). Request shapes are POW2-PADDED by the client
  (tier.py) so the jitted programs stay in a handful of compile-cache
  entries per table; the cache is the process-global one
  (training/compile_cache), so a shard migrating onto a new owner in
  the same process class finds its programs already compiled — warm
  resharding rides the compile cache.
- **host**: shard rows live as numpy; pull is one `take`, push is one
  in-place deduped scatter-add (sorted segment reduce, then a unique-
  index fancy add) — cost scales with TOUCHED rows, not shard size,
  which is what host-memory serving needs (a functional device update
  would copy the whole shard per push).

Exactly-once pushes: every push carries ``(client_id, seq)`` with seq
strictly increasing per client; the store keeps the last applied seq per
(table, shard, client) and re-sends (client retries after a lost ack, or
requeues after an interrupted resharding) come back ``applied=False``
without touching the table. The seq watermarks TRAVEL with the shard
(`extract_shard` / `install_shard` / checkpoint files), so migration and
restore preserve the fence.

Push watermarks (ISSUE 13, the read path): every APPLIED push also bumps
a per-(table, shard) **watermark** — a dense counter of writes the shard
has absorbed. Pulls and push acks can carry it (``with_watermark=True``),
which is what fences the worker-local hot-row cache (tier.py: a cached
row tagged with watermark W is a miss once the owner is known to be past
``W + staleness_bound``) and what tags the **delta log**: the store keeps
a bounded log of recent applied pushes so a read replica can sync by
fetching only the deltas past its own watermark (`fetch_delta` /
`apply_replica_delta`) instead of re-copying the shard. Replica copies
are resident in a SEPARATE namespace (`install_replica`): they serve
pulls (``replica=True``) but reject pushes — writes stay primary-only —
and can be promoted to primary wholesale (`promote_replica`) when the
owner dies, watermark and exactly-once seq fence included.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.embedding import sharding
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_PULLED = _reg.counter(
    "edl_embedding_store_pulled_rows_total",
    "rows served by owner stores", labels=("table",))
_PUSHED = _reg.counter(
    "edl_embedding_store_pushed_rows_total",
    "deduped update rows applied by owner stores", labels=("table",))
_DUP_PUSHES = _reg.counter(
    "edl_embedding_store_duplicate_pushes_total",
    "pushes deduplicated by the exactly-once sequence fence")
_STALE = _reg.counter(
    "edl_embedding_store_stale_map_rejects_total",
    "pulls/pushes rejected for a stale shard-map version")
_SHARDS = _reg.gauge(
    "edl_embedding_store_shards", "shards resident in this process's store")
# per-shard skew telemetry (ISSUE 11): label cardinality is bounded by
# --embedding_shards x registered tables x {pull,push} — a config
# constant, not data (the EDL405 boundary)
_SHARD_ROWS = _reg.counter(
    "edl_embedding_store_shard_load_rows_total",
    "rows served (pull) / applied (push) per resident shard",
    labels=("table", "shard", "op"))
_OP_S = _reg.histogram(
    "edl_embedding_store_op_seconds",
    "owner-side serve wall time per call", labels=("op",))
_REPLICA_SYNCS = _reg.counter(
    "edl_embedding_replica_delta_syncs_total",
    "delta batches applied to resident replica shards")
_REPLICA_RESYNCS = _reg.counter(
    "edl_embedding_replica_full_resyncs_total",
    "replica syncs that fell back to a full shard copy (delta log "
    "did not reach back to the replica's watermark)")
_REPLICA_PROMOTIONS = _reg.counter(
    "edl_embedding_replica_promotions_total",
    "replica shards promoted to primary (owner death recovery)")

#: delta-log depth per resident shard: how many applied pushes a replica
#: may lag before its sync falls back to a full shard copy
DELTA_LOG = int(os.environ.get("EDL_EMB_DELTA_LOG", "64") or 64)


class StaleShardMapError(RuntimeError):
    """The caller's shard-map version does not match the store's (or the
    shard is not resident here) — refresh the map and re-route."""


class _Shard:
    """One resident shard: the dense local table + the exactly-once
    per-client sequence watermarks (mutations guarded by the store lock
    at the serving layer; the apply itself runs outside it)."""

    __slots__ = ("rows", "applied", "lock", "wm", "deltas")

    def __init__(self, rows, applied: Optional[Dict[str, int]] = None,
                 wm: int = 0):
        self.rows = rows                      # jax.Array (num_rows, dim)
        self.applied: Dict[str, int] = dict(applied or {})
        # per-shard leaf lock: pull/push on DIFFERENT shards never
        # serialize behind each other (the store lock only guards the
        # shard directory)
        self.lock = threading.Lock()
        # push watermark: +1 per APPLIED push. The hot-row cache's
        # staleness fence and the replica delta protocol both count in
        # these units — "N pushes behind", not wall time, so a quiet
        # shard never goes stale and a hot one ages fast.
        self.wm = int(wm)
        # recent applied pushes, watermark-tagged, for replica delta
        # sync (guarded by `lock`; bounded — a replica further behind
        # than the log re-copies the shard)
        self.deltas: "deque" = deque(maxlen=DELTA_LOG)


def _init_shard_rows(spec: sharding.TableSpec, shard: int,
                     num_shards: int) -> np.ndarray:
    """Deterministic shard materialization: bit-identical wherever it is
    built (fresh bootstrap needs no transfer; a dead owner's shard can be
    re-materialized only if it was never pushed to — otherwise the
    checkpoint is the source of truth)."""
    rows = sharding.shard_row_count(spec.vocab, num_shards)
    # crc32, NOT hash(): Python's str hash is salted per process
    # (PYTHONHASHSEED), and shard materialization must be bit-identical
    # ACROSS processes — the same pitfall EDL204 documents for set order
    rng = np.random.default_rng(
        np.random.SeedSequence(
            [spec.seed, zlib.crc32(spec.name.encode()), shard]))
    out = rng.uniform(-spec.init_scale, spec.init_scale,
                      (rows, spec.dim)).astype(np.float32)
    # rows past the padded vocab's tail never map to a real id but are
    # part of the dense shard; zero them so accounting sums stay honest
    first_dead = -(-max(0, spec.vocab - shard) // num_shards)
    out[first_dead:] = 0.0
    return out


def _default_device_mode() -> Optional[bool]:
    env = os.environ.get("EDL_EMB_TIER_DEVICE", "")
    if env in ("0", "1"):
        return env == "1"
    return None


class EmbeddingShardStore:
    """Shards this worker owns, served to tier clients via a transport."""

    def __init__(self, owner: int, compile_cache=None,
                 device: Optional[bool] = None):
        self.owner = owner
        self._lock = threading.Lock()
        self._tables: Dict[str, sharding.TableSpec] = {}  # guarded_by: _lock
        self._num_shards = 0                              # guarded_by: _lock
        self._map_version = 0                             # guarded_by: _lock
        self._shards: Dict[Tuple[str, int], _Shard] = {}  # guarded_by: _lock
        # read-replica copies, SEPARATE namespace: a worker may be
        # primary for shard 3 and replica for shard 5 in the same store;
        # replicas serve pulls only and are promotable wholesale
        self._replicas: Dict[Tuple[str, int], _Shard] = {}  # guarded_by: _lock
        # replica delta logging is OFF until a shard map carrying
        # replica assignments shows up (attach/set_delta_logging):
        # without replicas nothing ever consumes the log, and buffering
        # 64 pushes of gradient rows per shard is real memory + two
        # array copies per push on the hot path
        self._log_deltas = False                          # guarded_by: _lock
        if device is None:
            device = _default_device_mode()
        # None = decide lazily at the first shard materialization (the
        # jax import / backend probe must not be paid by stores that are
        # constructed but never used)
        self._device_mode = device
        if compile_cache is None:
            from elasticdl_tpu.training import compile_cache as cc

            compile_cache = cc.global_cache()
        self._cache = compile_cache

    def _use_device(self) -> bool:
        if self._device_mode is None:
            import jax

            self._device_mode = jax.default_backend() == "tpu"
        return self._device_mode

    def _place(self, rows: np.ndarray):
        """Host array -> the store's serving format: a device-resident
        jax.Array in device mode, a mutable owned numpy array in host
        mode (the in-place scatter must never write a caller's buffer)."""
        if self._use_device():
            import jax

            return jax.device_put(rows)
        return np.array(rows, np.float32, copy=True)

    # -------------------------------------------------------------- #
    # map adoption / shard lifecycle

    def attach(self, view: sharding.ShardMapView,
               checkpoint_dir: str = "") -> List[int]:
        """Adopt a shard-map view: register its tables, materialize every
        owned-but-missing shard (from the tier checkpoint when present,
        else deterministically from the table seed), and adopt the map
        version. Shards this view assigns elsewhere are NOT dropped here —
        the donor keeps them until the migration commits (reshard.py
        releases them). Returns the shard ids freshly materialized."""
        created: List[int] = []
        with self._lock:
            self._num_shards = view.num_shards
            self._map_version = view.version
            self._log_deltas = any(
                view.replicas_of(s) for s in range(view.num_shards))
            for spec in view.tables:
                self._tables[spec.name] = spec
            owned = [s for s, o in enumerate(view.owners)
                     if o == self.owner]
            for spec in view.tables:
                for s in owned:
                    if (spec.name, s) in self._shards:
                        continue
                    rows = None
                    if checkpoint_dir:
                        payload = load_shard_file(
                            checkpoint_dir, spec.name, s)
                        if payload is not None:
                            self._shards[(spec.name, s)] = _Shard(
                                self._place(payload["rows"]),
                                payload["applied"],
                                wm=int(payload.get("wm", 0)),
                            )
                            created.append(s)
                            continue
                    rows = _init_shard_rows(spec, s, view.num_shards)
                    self._shards[(spec.name, s)] = _Shard(self._place(rows))
                    created.append(s)
            _SHARDS.set(len(self._shards))
        return created

    def adopt_version(self, version: int) -> None:
        with self._lock:
            self._map_version = version

    def set_delta_logging(self, enabled: bool) -> None:
        """Replica-map reaction (WorkerTierRuntime): start/stop keeping
        the per-shard push delta log. A log that starts mid-history is
        safe — fetch_delta's contiguity check routes a too-far-behind
        replica to the full-copy path."""
        with self._lock:
            self._log_deltas = bool(enabled)

    @property
    def map_version(self) -> int:
        with self._lock:
            return self._map_version

    def resident_shards(self, table: Optional[str] = None) -> List[Tuple[str, int]]:
        with self._lock:
            return [k for k in self._shards
                    if table is None or k[0] == table]

    def _get_shard(self, table: str, shard: int,
                   map_version: Optional[int],
                   replica: bool = False) -> _Shard:
        with self._lock:
            if (map_version is not None
                    and map_version != self._map_version):
                _STALE.inc()
                raise StaleShardMapError(
                    f"shard map v{map_version} (store at "
                    f"v{self._map_version})"
                )
            pool = self._replicas if replica else self._shards
            sh = pool.get((table, shard))
        if sh is None:
            _STALE.inc()
            raise StaleShardMapError(
                f"shard {table}/{shard} not "
                f"{'replica-' if replica else ''}resident on owner "
                f"{self.owner}"
            )
        return sh

    # -------------------------------------------------------------- #
    # data plane

    def pull(self, table: str, shard: int, local_ids: np.ndarray,
             map_version: Optional[int] = None,
             with_watermark: bool = False, replica: bool = False):
        """One fused gather: (n,) local row ids -> (n, dim) rows.
        Out-of-range ids (the client's pow2 padding sentinels) return
        zero rows. ``with_watermark=True`` returns ``(rows, wm)`` — the
        shard's push watermark as of the serve, the hot-row cache's
        freshness tag. ``replica=True`` serves from this store's replica
        copy of the shard (its watermark is wherever the last delta sync
        left it — the client's staleness fence decides acceptability)."""
        t0 = time.perf_counter()
        sh = self._get_shard(table, shard, map_version, replica=replica)
        ids = np.ascontiguousarray(np.asarray(local_ids, np.int32))
        with sh.lock:
            rows = sh.rows
            wm = sh.wm
        if self._use_device():
            out = np.asarray(
                self._pull_fn(rows.shape, ids.shape[0])(rows, ids))
        else:
            in_range = (ids >= 0) & (ids < rows.shape[0])
            out = rows.take(np.where(in_range, ids, 0), axis=0)
            out[~in_range] = 0.0
        # REAL rows only: the request is pow2-padded with -1 sentinels
        # (min bucket 256), and counting the padding would inflate the
        # traffic counters operators size capacity from
        real = int((ids >= 0).sum())
        _PULLED.inc(real, table=table)
        _SHARD_ROWS.inc(real, table=table, shard=str(shard), op="pull")
        _OP_S.observe(time.perf_counter() - t0, op="pull")
        if with_watermark:
            return out, wm
        return out

    def push(self, table: str, shard: int, local_ids: np.ndarray,
             rows: np.ndarray, *, client_id: str, seq: int,
             map_version: Optional[int] = None,
             scale: float = 1.0, with_watermark: bool = False):
        """One deduped scatter-add: ``shard_table += scale * sum(rows at
        local_ids)``. Returns False (without touching the table) when the
        exactly-once fence says ``(client_id, seq)`` was already applied
        — the ack a retried/requeued push gets. ``with_watermark=True``
        returns ``(applied, wm)`` with the post-apply watermark (a
        duplicate returns the CURRENT watermark — the fence held, the
        caller's freshness knowledge still advances)."""
        t0 = time.perf_counter()
        with self._lock:
            is_replica = ((table, shard) in self._replicas
                          and (table, shard) not in self._shards)
            log_deltas = self._log_deltas
        if is_replica:
            # writes are primary-only: a client pushing here holds a map
            # that predates (or misread) the replica split — same remedy
            # as any stale-map write: refresh and re-route
            _STALE.inc()
            raise StaleShardMapError(
                f"shard {table}/{shard} on owner {self.owner} is a READ "
                "replica; pushes go to the primary"
            )
        sh = self._get_shard(table, shard, map_version)
        ids = np.ascontiguousarray(np.asarray(local_ids, np.int32))
        vals = np.ascontiguousarray(np.asarray(rows, np.float32))
        with sh.lock:
            last = sh.applied.get(client_id, -1)
            if seq <= last:
                _DUP_PUSHES.inc()
                return (False, sh.wm) if with_watermark else False
            if self._use_device():
                sh.rows = self._apply_fn(sh.rows.shape, ids.shape[0])(
                    sh.rows, ids, vals, np.float32(scale))
            else:
                self._host_apply(sh.rows, ids, vals, scale)
            sh.applied[client_id] = seq
            sh.wm += 1
            wm = sh.wm
            if log_deltas:
                # delta log (replica sync): real rows only — a replica
                # re-applies through the same sentinel-dropping path,
                # and the log should not hold the pow2 padding
                keep = ids >= 0
                sh.deltas.append({
                    "wm": wm, "ids": ids[keep].copy(),
                    "rows": vals[keep].copy(), "scale": float(scale),
                    "client_id": client_id, "seq": int(seq),
                })
        # real (non-sentinel) rows only — see the pull counter note
        real = int((ids >= 0).sum())
        _PUSHED.inc(real, table=table)
        _SHARD_ROWS.inc(real, table=table, shard=str(shard), op="push")
        _OP_S.observe(time.perf_counter() - t0, op="push")
        if with_watermark:
            return True, wm
        return True

    @staticmethod
    def _host_apply(tab: np.ndarray, ids: np.ndarray, vals: np.ndarray,
                    scale: float) -> None:
        """In-place scatter-add, O(touched rows). Out-of-range ids
        (padding sentinels) drop. Two regimes:

        - UNIQUE ids (a deduping client — tier.py sums duplicates before
          sending): one vectorized fancy-index add. This is the fast
          path the client-side dedupe exists to unlock.
        - duplicate ids (a non-deduping client): ``np.add.at`` — the
          row-serial accumulate that is numpy's honest general primitive
          for colliding indices, and the faithful stand-in for the
          reference PS's per-row hash-map apply
          (elasticdl/pkg/ps/optimizer.go). Its cost IS the per-row
          traffic the deduped protocol removes; the bench's single-host
          baseline measures it on purpose.
        """
        keep = (ids >= 0) & (ids < tab.shape[0])
        ids, vals = ids[keep], vals[keep]
        if not ids.shape[0]:
            return
        # sorted-unique probe without a full unique(): the deduping
        # client sends SORTED unique ids, so one vectorized monotonicity
        # check identifies the fast path
        sorted_unique = bool(np.all(ids[1:] > ids[:-1]))
        if sorted_unique:
            tab[ids] += scale * vals
        else:
            np.add.at(tab, ids, scale * vals)

    # -------------------------------------------------------------- #
    # jitted programs (compile-cache keyed: warm resharding finds them)

    def _pull_fn(self, table_shape, n):
        key = ("emb_tier_pull", table_shape, int(n))

        def build():
            import jax
            import jax.numpy as jnp

            from elasticdl_tpu.ops import embedding as emb_ops

            def f(tab, ids):
                in_range = (ids >= 0) & (ids < tab.shape[0])
                safe = jnp.where(in_range, ids, 0)
                out = emb_ops.gather_rows(tab, safe)
                return jnp.where(in_range[:, None], out, 0.0)

            return jax.jit(f)

        return self._cache.get_or_build(key, build)

    def _apply_fn(self, table_shape, n):
        key = ("emb_tier_apply", table_shape, int(n))

        def build():
            import jax

            from elasticdl_tpu.ops import embedding as emb_ops

            def f(tab, ids, vals, scale):
                delta = emb_ops.scatter_add_dense(
                    ids, vals, tab.shape[0], dtype=tab.dtype)
                return tab + scale * delta

            # NOT donated: a concurrent pull on the same shard may still
            # hold the old rows array (the per-shard lock scopes the
            # rows SWAP, not the gather's execution) — donation would
            # invalidate the buffer under it
            return jax.jit(f)

        return self._cache.get_or_build(key, build)

    # -------------------------------------------------------------- #
    # migration / checkpoint payloads

    def extract_shard(self, table: str, shard: int,
                      replica: bool = False) -> Dict[str, Any]:
        """The migration payload: rows + exactly-once watermarks + push
        watermark. The shard stays resident (the donor serves reads until
        the move commits); `release_shard` drops it afterwards."""
        sh = self._get_shard(table, shard, None, replica=replica)
        with sh.lock:
            return {
                # copy, not a view: in host mode the live array mutates
                # in place under later pushes — a payload must be a
                # point-in-time snapshot
                "rows": np.array(sh.rows, np.float32, copy=True),
                "applied": dict(sh.applied),
                "wm": int(sh.wm),
            }

    def install_shard(self, table: str, shard: int,
                      payload: Dict[str, Any]) -> None:
        with self._lock:
            self._shards[(table, shard)] = _Shard(
                self._place(np.asarray(payload["rows"], np.float32)),
                {str(k): int(v) for k, v in payload["applied"].items()},
                wm=int(payload.get("wm", 0)),
            )
            _SHARDS.set(len(self._shards))

    def release_shard(self, table: str, shard: int) -> None:
        with self._lock:
            self._shards.pop((table, shard), None)
            _SHARDS.set(len(self._shards))

    # -------------------------------------------------------------- #
    # shard split / merge (ISSUE 20): local re-key, no cross-host copy

    def split_resident(self, view: sharding.ShardMapView) -> List[int]:
        """Re-key every resident shard for a DOUBLED shard count: parent
        s's row j (global id s + j*n) lands in child s when j is even,
        child s + n when j is odd, at child-local row j // 2 — a pure
        interleave, no id changes hosts. The exactly-once fence must
        survive the re-key, so each child inherits a full COPY of the
        parent's per-client seq watermarks (a push retried across the
        split dedupes at whichever child its ids now route to) and the
        parent's push watermark; the delta log is re-keyed per child
        with one entry per parent entry — possibly with zero rows — so
        watermark contiguity holds and a replica syncing across the
        split never sees a gap. Replica copies are dropped (their
        keyspace just changed); the controller re-fans them out.
        Returns the child shard ids now resident (confirm_moves
        payload)."""
        created: List[int] = []
        with self._lock:
            old_n = self._num_shards
            if view.num_shards != old_n * 2:
                raise ValueError(
                    f"split view has {view.num_shards} shards; store at "
                    f"{old_n}"
                )
            for spec in view.tables:
                self._tables[spec.name] = spec
            for (table, s), sh in sorted(self._shards.items()):
                spec = self._tables[table]
                child_rows = sharding.shard_row_count(
                    spec.vocab, view.num_shards)
                with sh.lock:
                    rows = np.array(sh.rows, np.float32, copy=True)
                    applied = dict(sh.applied)
                    wm = int(sh.wm)
                    deltas = list(sh.deltas)
                for child, parity in ((s, 0), (s + old_n, 1)):
                    out = np.zeros((child_rows, rows.shape[1]), np.float32)
                    part = rows[parity::2]
                    out[: part.shape[0]] = part
                    csh = _Shard(self._place(out), dict(applied), wm=wm)
                    for d in deltas:
                        mask = (d["ids"] % 2) == parity
                        csh.deltas.append(dict(
                            d, ids=(d["ids"][mask] // 2).astype(np.int32),
                            rows=d["rows"][mask].copy(),
                        ))
                    self._shards[(table, child)] = csh
                    if child != s:
                        created.append(child)
                if s not in created:
                    created.append(s)
            self._replicas.clear()
            self._num_shards = view.num_shards
            self._map_version = view.version
            self._log_deltas = any(
                view.replicas_of(s2) for s2 in range(view.num_shards))
            _SHARDS.set(len(self._shards))
        return sorted(set(created))

    def merge_resident(self, view: sharding.ShardMapView) -> List[int]:
        """Inverse of `split_resident` for a HALVED shard count: children
        s and s + new_n interleave back into parent s (legal only when
        both are resident here — the owner enforces co-ownership before
        planning the merge). The parent's exactly-once fence is the
        per-client MAX over both children and its push watermark the max
        of theirs; the delta log is CLEARED — child entry watermarks
        don't compose into one parent sequence, so replicas full-resync
        (they were dropped by the layout transition anyway). Returns the
        parent shard ids now resident."""
        created: List[int] = []
        with self._lock:
            old_n = self._num_shards
            new_n = view.num_shards
            if old_n != new_n * 2:
                raise ValueError(
                    f"merge view has {new_n} shards; store at {old_n}"
                )
            for spec in view.tables:
                self._tables[spec.name] = spec
            parents = sorted({
                (t, s if s < new_n else s - new_n)
                for (t, s) in self._shards
            })
            for table, s in parents:
                ev = self._shards.pop((table, s), None)
                od = self._shards.pop((table, s + new_n), None)
                if ev is None or od is None:
                    raise StaleShardMapError(
                        f"merge of {table}/{s}: both children must be "
                        f"resident on owner {self.owner}"
                    )
                spec = self._tables[table]
                p_cnt = sharding.shard_row_count(spec.vocab, new_n)
                with ev.lock:
                    ev_rows = np.array(ev.rows, np.float32, copy=True)
                    ev_applied = dict(ev.applied)
                    ev_wm = int(ev.wm)
                with od.lock:
                    od_rows = np.array(od.rows, np.float32, copy=True)
                    od_applied = dict(od.applied)
                    od_wm = int(od.wm)
                out = np.zeros((p_cnt, ev_rows.shape[1]), np.float32)
                out[0::2] = ev_rows[: (p_cnt + 1) // 2]
                out[1::2] = od_rows[: p_cnt // 2]
                applied = dict(ev_applied)
                for cid, seq in od_applied.items():
                    applied[cid] = max(applied.get(cid, -1), seq)
                self._shards[(table, s)] = _Shard(
                    self._place(out), applied, wm=max(ev_wm, od_wm))
                if s not in created:
                    created.append(s)
            self._replicas.clear()
            self._num_shards = new_n
            self._map_version = view.version
            self._log_deltas = any(
                view.replicas_of(s2) for s2 in range(view.num_shards))
            _SHARDS.set(len(self._shards))
        return sorted(created)

    # -------------------------------------------------------------- #
    # read replicas (ISSUE 13): pull-only copies + delta sync

    def install_replica(self, table: str, shard: int,
                        payload: Dict[str, Any]) -> None:
        """Adopt a replica copy of a shard this store does NOT own
        (payload = the primary's `extract_shard`). Serves pulls with
        ``replica=True``; never pushes."""
        with self._lock:
            self._replicas[(table, shard)] = _Shard(
                self._place(np.asarray(payload["rows"], np.float32)),
                {str(k): int(v) for k, v in payload["applied"].items()},
                wm=int(payload.get("wm", 0)),
            )

    def release_replica(self, table: str, shard: int) -> None:
        with self._lock:
            self._replicas.pop((table, shard), None)

    def resident_replicas(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._replicas)

    def replica_watermark(self, table: str, shard: int) -> int:
        sh = self._get_shard(table, shard, None, replica=True)
        with sh.lock:
            return sh.wm

    def promote_replica(self, table: str, shard: int) -> int:
        """Owner-death recovery: this store's replica copy BECOMES the
        primary — rows, exactly-once seq fence, and push watermark move
        wholesale, so a client push retried across the promotion still
        dedupes. Returns the promoted copy's watermark."""
        with self._lock:
            sh = self._replicas.pop((table, shard), None)
            if sh is None:
                raise StaleShardMapError(
                    f"no replica of {table}/{shard} resident on owner "
                    f"{self.owner} to promote"
                )
            self._shards[(table, shard)] = sh
            _SHARDS.set(len(self._shards))
        _REPLICA_PROMOTIONS.inc()
        with sh.lock:
            return sh.wm

    def shard_watermark(self, table: str, shard: int,
                        replica: bool = False) -> int:
        """The shard's push watermark — the hot-row cache's freshness
        probe (a fully-cache-served client must still learn the owner
        moved on; tier.py probes this on a lookup cadence).
        ``replica=True`` reads the replica copy's watermark: a LOWER
        bound on the primary's, which is what the degraded-mode ladder
        probes when the primary has partitioned away (tier.py
        _maybe_probe_watermarks)."""
        sh = self._get_shard(table, shard, None, replica=replica)
        with sh.lock:
            return sh.wm

    def fetch_delta(self, table: str, shard: int,
                    since_wm: int) -> Optional[Dict[str, Any]]:
        """Primary side of replica sync: every applied push past
        ``since_wm``, watermark-tagged and in order — or None when the
        bounded delta log no longer reaches back that far (the replica
        falls back to a full `extract_shard` copy)."""
        sh = self._get_shard(table, shard, None)
        with sh.lock:
            wm = sh.wm
            if since_wm >= wm:
                return {"wm": wm, "entries": []}
            entries = [d for d in sh.deltas if d["wm"] > since_wm]
            # contiguity: the log must hold EVERY watermark in
            # (since_wm, wm] or the replica would silently skip pushes
            if len(entries) != wm - since_wm:
                return None
            return {
                "wm": wm,
                "entries": [dict(d, ids=d["ids"].copy(),
                                 rows=d["rows"].copy())
                            for d in entries],
            }

    def apply_replica_delta(self, table: str, shard: int,
                            delta: Dict[str, Any]) -> int:
        """Replica side of sync: apply the primary's delta batch in
        watermark order (idempotent — entries at or below the replica's
        watermark are skipped). Returns the replica's new watermark."""
        sh = self._get_shard(table, shard, None, replica=True)
        with sh.lock:
            for e in sorted(delta["entries"], key=lambda d: d["wm"]):
                if e["wm"] <= sh.wm:
                    continue
                if e["wm"] != sh.wm + 1:
                    raise StaleShardMapError(
                        f"replica {table}/{shard} delta gap: at wm "
                        f"{sh.wm}, next entry {e['wm']} — full resync "
                        "required"
                    )
                raw_ids = np.asarray(e["ids"], np.int32)
                raw_vals = np.asarray(e["rows"], np.float32)
                # pow2-pad like the client's push protocol (sentinel -1
                # rows drop in the apply) so device-mode replicas land on
                # the same handful of compiled programs as primaries
                n = 256
                while n < raw_ids.shape[0]:
                    n <<= 1
                ids = np.full((n,), -1, np.int32)
                ids[: raw_ids.shape[0]] = raw_ids
                vals = np.zeros((n, raw_vals.shape[1]
                                 if raw_vals.ndim == 2 else sh.rows.shape[1]),
                                np.float32)
                vals[: raw_vals.shape[0]] = raw_vals
                if self._use_device():
                    sh.rows = self._apply_fn(sh.rows.shape, ids.shape[0])(
                        sh.rows, ids, vals, np.float32(e["scale"]))
                else:
                    self._host_apply(sh.rows, ids, vals, e["scale"])
                sh.wm = e["wm"]
                cid = str(e.get("client_id", ""))
                if cid:
                    sh.applied[cid] = max(
                        sh.applied.get(cid, -1), int(e.get("seq", -1)))
            new_wm = sh.wm
        _REPLICA_SYNCS.inc()
        return new_wm

    def sync_replica_from(self, transport, primary: int, table: str,
                          shard: int) -> int:
        """One replica sync round against the primary over the
        transport: delta when the log reaches, full copy otherwise.
        Returns the replica's post-sync watermark."""
        try:
            since = self.replica_watermark(table, shard)
        except StaleShardMapError:
            since = -1
        if since >= 0:
            if hasattr(transport, "fetch_delta_stream"):
                # streaming lane (ISSUE 18): apply chunk by chunk so a
                # mid-stream drop leaves the replica consistently at
                # whatever watermark the applied prefix reached — the
                # next round resumes from there, and any re-sent
                # entries fall to apply_replica_delta's idempotent
                # watermark fence (no double-apply)
                found = True
                wm = since
                for frame in transport.fetch_delta_stream(
                        primary, table, shard, since):
                    if not frame.get("found", True):
                        found = False
                        break
                    if frame["entries"]:
                        wm = self.apply_replica_delta(
                            table, shard,
                            {"wm": frame["wm"],
                             "entries": frame["entries"]})
                    else:
                        wm = max(wm, int(frame.get("wm", wm)))
                if found:
                    return wm
            else:
                delta = transport.fetch_delta(
                    primary, table, shard, since)
                if delta is not None:
                    return self.apply_replica_delta(table, shard, delta)
            _REPLICA_RESYNCS.inc()
        payload = transport.fetch_shard(primary, table, shard)
        self.install_replica(table, shard, payload)
        return int(payload.get("wm", 0))

    # -------------------------------------------------------------- #
    # sharded save/restore (training/checkpoint.py delegates here)

    def save(self, directory: str, tables: Optional[List[str]] = None) -> int:
        """Write every resident shard (of `tables`, default all) as one
        atomic file each; returns how many were written. Layout:
        ``<dir>/emb/<table>-shard<id>.npz`` with the rows and the
        exactly-once watermarks — a restore resumes the fence, so a push
        replayed from before the save still dedupes."""
        written = 0
        for table, shard in self.resident_shards():
            if tables is not None and table not in tables:
                continue
            payload = self.extract_shard(table, shard)
            save_shard_file(directory, table, shard, payload)
            written += 1
        return written

    def restore_missing(self, directory: str) -> int:
        """Install any checkpointed shard for this owner's current map
        that is not yet resident (kill-worker recovery path); returns how
        many were restored. Shards with no file stay absent — attach()
        decides whether to re-materialize from seed."""
        restored = 0
        with self._lock:
            tables = dict(self._tables)
            num_shards = self._num_shards
        for table in tables:
            for shard in range(num_shards):
                with self._lock:
                    if (table, shard) in self._shards:
                        continue
                payload = load_shard_file(directory, table, shard)
                if payload is not None:
                    self.install_shard(table, shard, payload)
                    restored += 1
        return restored




# ------------------------------------------------------------------ #
# shard files (atomic tmp+replace; EDL305 discipline)


def _shard_path(directory: str, table: str, shard: int) -> str:
    return os.path.join(directory, "emb", f"{table}-shard{shard:05d}.npz")


def save_shard_file(directory: str, table: str, shard: int,
                    payload: Dict[str, Any]) -> str:
    path = _shard_path(directory, table, shard)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    buf = io.BytesIO()
    np.savez(
        buf, rows=np.asarray(payload["rows"], np.float32),
        applied=np.frombuffer(
            json.dumps(payload["applied"]).encode(), np.uint8),
        wm=np.asarray(int(payload.get("wm", 0)), np.int64),
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
        f.flush()
        # a torn shard file would restore silently-wrong rows; fsync +
        # atomic replace, same contract as the control-plane journal:
        # edl-lint: disable=EDL403
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def peek_shard_watermark(directory: str, table: str,
                         shard: int) -> Optional[int]:
    """The checkpoint file's push watermark WITHOUT materializing the
    rows (npz members load lazily) — the replica-vs-checkpoint
    freshness arbitration on the recovery critical path must not pay a
    full shard read per candidate."""
    path = _shard_path(directory, table, shard)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            return int(z["wm"]) if "wm" in z.files else 0
    except (OSError, ValueError, KeyError):
        logger.exception("embedding shard file %s unreadable; ignored", path)
        return None


def load_shard_file(directory: str, table: str,
                    shard: int) -> Optional[Dict[str, Any]]:
    path = _shard_path(directory, table, shard)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            rows = z["rows"]
            applied = json.loads(bytes(z["applied"]).decode())
            # pre-watermark files (PR 10) load at wm 0 — conservative:
            # every cached row fetched before the restore reads stale
            wm = int(z["wm"]) if "wm" in z.files else 0
    except (OSError, ValueError, KeyError):
        logger.exception("embedding shard file %s unreadable; ignored", path)
        return None
    return {"rows": rows, "wm": wm,
            "applied": {str(k): int(v) for k, v in applied.items()}}
