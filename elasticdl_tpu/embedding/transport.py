"""Transport between tier clients and owning stores.

The data plane is pluggable: `LocalTransport` serves the in-process
deployments this repo can actually run (single-process workers, the
thread-cohort bench swarm) and is the reference implementation of the
call contract; the cross-host `GrpcTransport` (embedding/data_plane.py)
slots in behind the same methods without touching client or store, and
`SimWireTransport` puts a deterministic simulated wire in front of any
inner transport so the bench's read-layer legs and the real gRPC legs
are interchangeable runs of the same scenario.

Every call crosses a REAL boundary even in-process: requests and
responses are numpy arrays (never shared jax buffers), and the
fault-injection sites wrap each call so chaos schedules can drop or
delay tier traffic deterministically — the exactly-once tests ride
these. Each method fires a REQUEST-side site (``emb.pull``,
``emb.push``, ``emb.fetch_shard``, ``emb.fetch_delta``,
``emb.watermark``) before the owner serves, and a RESPONSE-side
``.recv`` twin after it (``emb.pull.recv``, ``emb.push.recv``,
``emb.fetch_shard.recv``, ``emb.fetch_delta.recv``): a ``.recv`` drop
models a reply lost AFTER the owner applied — the hard case for a
non-idempotent push, which the per-(client, seq) fence must absorb
(the caller re-sends under the same seq and the store acks the
duplicate without touching the table).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import reqtrace
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

#: the degraded-mode ladder's honesty counter (ISSUE 15), shared by the
#: robustness layer (data_plane.ResilientTransport: mode="replica" when
#: a hedge served because the primary FAILED, mode="blocked" when every
#: rung failed) and the tier client (tier.py: mode="cache" for hits
#: served while the owner's breaker is open — freshness is then running
#: on the last observed watermark, beyond wm_probe reach). Registered
#: here because the ladder spans both modules and the registry rejects
#: duplicate names.
DEGRADED_READS = default_registry().counter(
    "edl_emb_degraded_reads_total",
    "reads served (or refused) by the degraded-mode ladder while an "
    "owner was partitioned away, by rung",
    labels=("mode",))


class OwnerUnavailableError(ConnectionError):
    """The owner is not reachable (dead worker / not yet registered)."""


class LocalTransport:
    """In-process owner registry: owner id -> EmbeddingShardStore.

    Thread-safe; `deregister` models worker death (subsequent calls to
    that owner raise OwnerUnavailableError, exactly what a dead remote
    peer looks like to the client's retry path)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stores: Dict[int, Any] = {}     # guarded_by: _lock

    def register(self, store) -> None:
        with self._lock:
            self._stores[store.owner] = store

    def deregister(self, owner: int) -> None:
        with self._lock:
            self._stores.pop(owner, None)

    def owners(self):
        with self._lock:
            return sorted(self._stores)

    def store_of(self, owner: int):
        """The live store (reshard.py uses this for local migrations)."""
        with self._lock:
            store = self._stores.get(owner)
        if store is None:
            raise OwnerUnavailableError(f"embedding owner {owner} is gone")
        return store

    # -------------------------------------------------------------- #
    # the call contract (a remote transport implements exactly these)

    def pull(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray,
             map_version: Optional[int] = None,
             with_watermark: bool = False, replica: bool = False):
        faults.fire("emb.pull")
        store = self.store_of(owner)
        with reqtrace.stage("store"):
            out = store.pull(
                table, shard, local_ids, map_version=map_version,
                with_watermark=with_watermark, replica=replica)
        # response-side injection: the owner DID serve; the reply is lost
        # on the way back (reads are idempotent — the caller re-pulls)
        faults.fire("emb.pull.recv")
        return out

    def push(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray, rows: np.ndarray, *, client_id: str,
             seq: int, map_version: Optional[int] = None,
             scale: float = 1.0, with_watermark: bool = False):
        faults.fire("emb.push")
        store = self.store_of(owner)
        with reqtrace.stage("store"):
            applied = store.push(
                table, shard, local_ids, rows, client_id=client_id,
                seq=seq, map_version=map_version, scale=scale,
                with_watermark=with_watermark,
            )
        # lost-ack injection: the store DID apply; the caller never hears
        # back and must re-send — the store's seq fence absorbs the dup
        faults.fire("emb.push.recv")
        return applied

    def fetch_shard(self, owner: int, table: str,
                    shard: int) -> Dict[str, Any]:
        faults.fire("emb.fetch_shard")
        payload = self.store_of(owner).extract_shard(table, shard)
        faults.fire("emb.fetch_shard.recv")
        return payload

    def shard_watermark(self, owner: int, table: str, shard: int,
                        replica: bool = False) -> int:
        """Watermark-only freshness probe (no rows cross the wire) —
        what bounds a fully-cache-served client's staleness.
        ``replica=True`` probes the owner's replica copy: a lower bound
        on the primary's watermark, the degraded ladder's fallback when
        the primary has partitioned away."""
        faults.fire("emb.watermark")
        return self.store_of(owner).shard_watermark(
            table, shard, replica=replica)

    def fetch_delta(self, owner: int, table: str, shard: int,
                    since_wm: int) -> Optional[Dict[str, Any]]:
        """Replica sync: the primary's applied pushes past ``since_wm``
        (watermark-tagged, contiguous) or None when its bounded delta log
        no longer reaches back — the replica then re-copies the shard."""
        faults.fire("emb.fetch_delta")
        delta = self.store_of(owner).fetch_delta(table, shard, since_wm)
        faults.fire("emb.fetch_delta.recv")
        return delta

    # -------------------------------------------------------------- #
    # wire-speed lanes (ISSUE 18) — optional contract extensions a
    # client feature-detects with hasattr; the unary methods above stay
    # the floor every transport must provide

    def pull_multi(self, owner: int, requests,
                   map_version: Optional[int] = None,
                   replica: bool = False):
        """One fused call serving every (table, shard, local_ids)
        sub-pull in ``requests`` against one owner. Returns
        ``(results, owner_wms)``: ``results`` is a list of ``(rows,
        wm)`` parallel to ``requests``; ``owner_wms`` maps EVERY
        resident primary ``(table, shard)`` on the owner to its push
        watermark — the piggyback that keeps steady-state freshness
        probes off the wire. One request-side and one response-side
        fault site fire per FUSED call (the wire sees one call), so a
        chaos drop loses every sub-pull together, exactly like the
        real fused RPC."""
        faults.fire("emb.pull")
        store = self.store_of(owner)
        with reqtrace.stage("store"):
            results = []
            for table, shard, local_ids in requests:
                results.append(store.pull(
                    table, shard, local_ids, map_version=map_version,
                    with_watermark=True, replica=replica))
            owner_wms = {
                key: store.shard_watermark(*key)
                for key in store.resident_shards()
            }
        faults.fire("emb.pull.recv")
        return results, owner_wms

    def watermark_multi(self, owner: int, pairs,
                        replica: bool = False):
        """Batched freshness probe: one call returns the watermark of
        every ``(table, shard)`` in ``pairs`` (parallel list) — the
        residual probe lane for clients so fully cache-served that no
        pull piggyback refreshes them."""
        faults.fire("emb.watermark")
        store = self.store_of(owner)
        return [store.shard_watermark(t, s, replica=replica)
                for t, s in pairs]

    def fetch_delta_stream(self, owner: int, table: str, shard: int,
                           since_wm: int, chunk_entries: int = 64):
        """Streaming replica sync: yields delta CHUNKS (each a
        ``{"found", "wm", "entries", "last"}`` frame, fence fields in
        the first) so the replica applies incrementally and a
        mid-stream drop resumes from wherever the applied watermark
        got to — re-sent entries fall to the idempotent wm fence."""
        faults.fire("emb.fetch_delta")
        delta = self.store_of(owner).fetch_delta(table, shard, since_wm)
        faults.fire("emb.fetch_delta.recv")
        return _delta_frames(delta, chunk_entries)


def _delta_frames(delta: Optional[Dict[str, Any]],
                  chunk_entries: int):
    """Chunk one fetch_delta payload into stream frames (the reference
    framing GrpcTransport's server stream mirrors on the real wire)."""
    if delta is None:
        yield {"found": False, "wm": 0, "entries": [], "last": True}
        return
    entries = delta["entries"]
    wm = delta["wm"]
    if not entries:
        yield {"found": True, "wm": wm, "entries": [], "last": True}
        return
    for off in range(0, len(entries), chunk_entries):
        batch = entries[off:off + chunk_entries]
        yield {
            "found": True, "wm": wm, "entries": batch,
            "last": off + chunk_entries >= len(entries),
        }


class SimWireTransport:
    """Any transport behind a deterministic simulated wire: every
    data-plane call sleeps ``base + real_rows * per_row`` before
    serving. sleep() releases the GIL, so pipeline overlap and replica
    fan-out compose exactly as against a real network peer — which is
    what the read layers exist for; in-process the serve is free and
    there is nothing to cache or overlap.

    Folded behind the shared transport contract (ISSUE 15) so the
    bench's sim-wire legs and the real gRPC transport are
    interchangeable runs of the same scenario — and so the model's
    constants (`bench.py` ET_WIRE_US / ET_WIRE_ROW_US) can be
    CALIBRATED against the measured loopback RPC cost the `data_plane`
    leg reports (`wire_truth`). Wire constants ride the bench record;
    0/0 disables the model entirely (pure delegation)."""

    def __init__(self, inner, call_us: float, row_us: float):
        self._inner = inner
        self._call_s = call_us * 1e-6
        self._row_s = row_us * 1e-6

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _wire(self, rows: int) -> None:
        if self._call_s or self._row_s:
            with reqtrace.stage("wire"):
                time.sleep(self._call_s + rows * self._row_s)

    def pull(self, owner, table, shard, local_ids, **kw):
        self._wire(int((local_ids >= 0).sum()))
        return self._inner.pull(owner, table, shard, local_ids, **kw)

    def push(self, owner, table, shard, local_ids, rows, **kw):
        self._wire(int((local_ids >= 0).sum()))
        return self._inner.push(owner, table, shard, local_ids, rows, **kw)

    def shard_watermark(self, owner, table, shard, replica=False):
        self._wire(0)
        return self._inner.shard_watermark(
            owner, table, shard, replica=replica)

    def fetch_shard(self, owner, table, shard):
        payload = self._inner.fetch_shard(owner, table, shard)
        self._wire(int(payload["rows"].shape[0]))
        return payload

    def fetch_delta(self, owner, table, shard, since_wm):
        delta = self._inner.fetch_delta(owner, table, shard, since_wm)
        if delta is None:
            self._wire(0)
        else:
            self._wire(sum(int(e["ids"].shape[0])
                           for e in delta["entries"]))
        return delta

    # wire-speed lanes (ISSUE 18): ONE per-call cost per fused call —
    # the whole point of coalescing under a per-call-dominated wire

    def pull_multi(self, owner, requests, **kw):
        self._wire(sum(int((ids >= 0).sum())
                       for _, _, ids in requests))
        return self._inner.pull_multi(owner, requests, **kw)

    def watermark_multi(self, owner, pairs, replica=False):
        self._wire(0)
        return self._inner.watermark_multi(owner, pairs, replica=replica)

    def fetch_delta_stream(self, owner, table, shard, since_wm,
                           chunk_entries: int = 64):
        # one per-call cost up front (one streaming call), then the
        # per-row cost lands frame by frame as chunks are consumed
        self._wire(0)
        for frame in self._inner.fetch_delta_stream(
                owner, table, shard, since_wm,
                chunk_entries=chunk_entries):
            if self._row_s and frame["entries"]:
                time.sleep(self._row_s * sum(
                    int(e["ids"].shape[0]) for e in frame["entries"]))
            yield frame
