"""Transport between tier clients and owning stores.

The data plane is pluggable: `LocalTransport` serves the in-process
deployments this repo can actually run (single-process workers, the
thread-cohort bench swarm) and is the reference implementation of the
call contract; a cross-host gRPC transport slots in behind the same
three methods without touching client or store (the wire schema is the
shard-map RPCs' sibling — see docs/architecture.md "Embedding tier").

Every call crosses a REAL boundary even in-process: requests and
responses are numpy arrays (never shared jax buffers), and the
fault-injection sites ``emb.pull`` / ``emb.push`` / ``emb.fetch_shard``
(common/faults.py) wrap each call so chaos schedules can drop or delay
tier traffic deterministically — the exactly-once tests ride these.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


class OwnerUnavailableError(ConnectionError):
    """The owner is not reachable (dead worker / not yet registered)."""


class LocalTransport:
    """In-process owner registry: owner id -> EmbeddingShardStore.

    Thread-safe; `deregister` models worker death (subsequent calls to
    that owner raise OwnerUnavailableError, exactly what a dead remote
    peer looks like to the client's retry path)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stores: Dict[int, Any] = {}     # guarded_by: _lock

    def register(self, store) -> None:
        with self._lock:
            self._stores[store.owner] = store

    def deregister(self, owner: int) -> None:
        with self._lock:
            self._stores.pop(owner, None)

    def owners(self):
        with self._lock:
            return sorted(self._stores)

    def store_of(self, owner: int):
        """The live store (reshard.py uses this for local migrations)."""
        with self._lock:
            store = self._stores.get(owner)
        if store is None:
            raise OwnerUnavailableError(f"embedding owner {owner} is gone")
        return store

    # -------------------------------------------------------------- #
    # the call contract (a remote transport implements exactly these)

    def pull(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray,
             map_version: Optional[int] = None,
             with_watermark: bool = False, replica: bool = False):
        faults.fire("emb.pull")
        store = self.store_of(owner)
        return store.pull(table, shard, local_ids, map_version=map_version,
                          with_watermark=with_watermark, replica=replica)

    def push(self, owner: int, table: str, shard: int,
             local_ids: np.ndarray, rows: np.ndarray, *, client_id: str,
             seq: int, map_version: Optional[int] = None,
             scale: float = 1.0, with_watermark: bool = False):
        faults.fire("emb.push")
        store = self.store_of(owner)
        applied = store.push(
            table, shard, local_ids, rows, client_id=client_id, seq=seq,
            map_version=map_version, scale=scale,
            with_watermark=with_watermark,
        )
        # lost-ack injection: the store DID apply; the caller never hears
        # back and must re-send — the store's seq fence absorbs the dup
        faults.fire("emb.push.recv")
        return applied

    def fetch_shard(self, owner: int, table: str,
                    shard: int) -> Dict[str, Any]:
        faults.fire("emb.fetch_shard")
        return self.store_of(owner).extract_shard(table, shard)

    def shard_watermark(self, owner: int, table: str, shard: int) -> int:
        """Watermark-only freshness probe (no rows cross the wire) —
        what bounds a fully-cache-served client's staleness."""
        faults.fire("emb.watermark")
        return self.store_of(owner).shard_watermark(table, shard)

    def fetch_delta(self, owner: int, table: str, shard: int,
                    since_wm: int) -> Optional[Dict[str, Any]]:
        """Replica sync: the primary's applied pushes past ``since_wm``
        (watermark-tagged, contiguous) or None when its bounded delta log
        no longer reaches back — the replica then re-copies the shard."""
        faults.fire("emb.fetch_delta")
        return self.store_of(owner).fetch_delta(table, shard, since_wm)
