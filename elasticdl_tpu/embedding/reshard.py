"""Shard migration: executing a ShardMapOwner move plan.

Rides the same announce -> quiesce -> handoff shape as mesh rescale
(docs/elasticity.md): the master journals `emb_reshard_begin` with the
move plan, the shards move device-to-device through the live-handoff
staging path (parallel/elastic.stage_leaf + reshard_state — the donor's
rows are staged exactly like a TrainState leaf whose owner set changes),
recipients confirm via `ShardMapOwner.confirm_moves`, and the commit is
journaled before the new map is considered current. Exactly-once update
accounting travels WITH the shard: the per-client seq watermarks are
part of the migration payload, so a push retried across the move still
dedupes at the new owner.

Dead-donor moves (`src < 0` — kill-worker recovery) restore from the
tier checkpoint when one exists and fall back to deterministic seed
materialization (store._init_shard_rows) for never-pushed shards.

The whole plan execution is spanned (`embedding.reshard` with one
`embedding.shard_move` child per move) so the trace analyzer can put
resharding on the recovery critical path — CI runs it --strict over the
bench leg's spans.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.embedding import sharding
from elasticdl_tpu.embedding.transport import OwnerUnavailableError
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_MOVE_S = _reg.histogram(
    "edl_embedding_shard_move_seconds", "per-shard migration wall time")
_RESTORED = _reg.counter(
    "edl_embedding_shards_restored_total",
    "dead-donor shards rebuilt from checkpoint/seed", labels=("source",))


def apply_moves(
    view: sharding.ShardMapView,
    moves: Sequence[sharding.ShardMove],
    transport,
    checkpoint_dir: str = "",
    mesh=None,
    confirm=None,
) -> Dict[str, Any]:
    """Execute a move plan against a (local) transport's stores.

    For every move and every table: fetch the payload from the donor
    (live transfer) or from the checkpoint/seed (dead donor), stage it
    through the live-handoff path onto `mesh` when one is given (the
    device-to-device lane mesh rescale uses), install at the recipient,
    and `confirm(version, [shard])` toward the master. Donors release
    their copy only AFTER the confirm round — a crash mid-move leaves
    the committed map (and the donor's copy) intact.

    Returns stats: moved/restored counts and wall time.
    """
    from elasticdl_tpu.parallel import elastic

    t0 = time.perf_counter()
    moved = restored = 0
    with tracing.span("embedding.reshard", version=view.version,
                      moves=len(moves)) as sp:
        for mv in moves:
            t_mv = time.perf_counter()
            with tracing.span("embedding.shard_move", shard=mv.shard,
                              src=mv.src, dst=mv.dst):
                dst_store = transport.store_of(mv.dst)
                resident = set(dst_store.resident_shards())
                for spec in view.tables:
                    if (spec.name, mv.shard) in resident:
                        # idempotent re-execution (a retried plan, or a
                        # recovery install where only SOME tables are
                        # missing): a live resident shard — possibly
                        # carrying pushes newer than any checkpoint —
                        # must never be clobbered by a stale payload
                        continue
                    payload = _fetch_payload(
                        transport, spec, mv, view.num_shards,
                        checkpoint_dir)
                    if payload.pop("_restored", False):
                        restored += 1
                    else:
                        moved += 1
                    if mesh is not None:
                        # the live-handoff lane: stage the donor rows and
                        # lay them out on the recipient's mesh exactly as
                        # a rescale lays out a TrainState leaf
                        staged = elastic.stage_leaf(payload["rows"])
                        payload["rows"] = elastic.reshard_state(
                            staged, mesh)
                    dst_store.install_shard(spec.name, mv.shard, payload)
            _MOVE_S.observe(time.perf_counter() - t_mv)
        if confirm is not None:
            confirm(view.version, [mv.shard for mv in moves])
        # only after the plan is confirmed (committed by the master) do
        # live donors drop their copy — an uncommitted resharding must
        # leave every donor able to keep serving the old map
        for mv in moves:
            if mv.src < 0:
                continue
            try:
                src_store = transport.store_of(mv.src)
            except OwnerUnavailableError:
                logger.info(
                    "donor %d gone before releasing shard %d (already "
                    "dead or deregistered) — nothing to release", mv.src,
                    mv.shard,
                )
                continue
            for spec in view.tables:
                src_store.release_shard(spec.name, mv.shard)
        for _, st in _stores_by_owner(transport, view).items():
            st.adopt_version(view.version)
        sp.set(moved=moved, restored=restored)
    stats = {
        "moves": len(moves), "payloads_transferred": moved,
        "payloads_restored": restored,
        "seconds": round(time.perf_counter() - t0, 4),
    }
    return stats


def _fetch_payload(transport, spec, mv: sharding.ShardMove,
                   num_shards: int, checkpoint_dir: str) -> Dict[str, Any]:
    from elasticdl_tpu.embedding import store as store_lib

    if mv.src >= 0:
        try:
            payload = dict(
                transport.fetch_shard(mv.src, spec.name, mv.shard))
            payload["_restored"] = False
            return payload
        except Exception:
            # the planned donor died between plan and execution: same
            # recovery as a dead-donor move — checkpoint, then seed
            logger.warning(
                "shard %s/%d donor %d unreachable; restoring instead",
                spec.name, mv.shard, mv.src,
            )
    if checkpoint_dir:
        payload = store_lib.load_shard_file(
            checkpoint_dir, spec.name, mv.shard)
        if payload is not None:
            _RESTORED.inc(source="checkpoint")
            payload["_restored"] = True
            return payload
    logger.warning(
        "shard %s/%d lost its owner with no checkpoint; re-materializing "
        "from seed (any un-checkpointed pushes to it are gone — size "
        "checkpoint cadence accordingly, docs/performance.md)",
        spec.name, mv.shard,
    )
    _RESTORED.inc(source="seed")
    return {
        "rows": store_lib._init_shard_rows(spec, mv.shard, num_shards),
        "applied": {},
        "_restored": True,
    }


def _stores_by_owner(transport, view: sharding.ShardMapView):
    out = {}
    for owner in sorted(set(view.owners)):
        try:
            out[owner] = transport.store_of(owner)
        except OwnerUnavailableError:
            continue   # a dead owner has no store to version-stamp
    return out


def drain_to_checkpoint(store, checkpoint_dir: str,
                        tables: Optional[List[str]] = None) -> int:
    """Preemption-drain hook: persist every resident shard (rows + seq
    watermarks) so a planned kill loses nothing — the tier twin of the
    worker's drain checkpoint. Returns shards written."""
    n = store.save(checkpoint_dir, tables)
    tracing.event("embedding.drain", shards=n)
    return n
