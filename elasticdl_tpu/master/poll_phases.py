"""Wait-poll phase attribution: where does one master poll pass spend
its wall?

The master's wait loop runs a fixed pipeline every ``poll_s`` —
membership reap, dispatcher poke, health scoring, goodput rollup,
time-series sampling, alert evaluation, autoscale decision. At 64
workers the whole pass is sub-millisecond and nobody cares; at
thousands of cohorts any one phase can quietly eat the poll budget and
starve the rest (the control-plane cliff the fleet soak exists to
find). ``edl_master_poll_phase_seconds{phase}`` breaks the pass down so
a slow poll names its culprit instead of being one opaque number.

Shared by the production wait loop (master/main.py) and the fleet
simulator's virtual poll (fleetsim/sim.py) so both report through the
same series. Phase timing is REAL wall (perf_counter) even under a
virtual clock — the whole point is to measure what the master's own
code costs, which no amount of time compression changes.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict

from elasticdl_tpu.observability.registry import default_registry

#: bounded phase vocabulary (metric label values)
PHASES = (
    "membership", "dispatcher", "health", "goodput", "timeseries",
    "alerts", "autoscaler",
)

_reg = default_registry()
_POLL_PHASE = _reg.histogram(
    "edl_master_poll_phase_seconds",
    "wall seconds one master wait-poll pass spent in each phase "
    "(membership reap / dispatcher poke / health / goodput / "
    "timeseries / alerts / autoscaler)",
    labels=("phase",))


@contextmanager
def poll_phase(phase: str):
    """Time one phase of a poll pass into the labeled histogram."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        # phase values come from the bounded PHASES vocabulary at every
        # call site: edl-lint: disable=EDL405
        _POLL_PHASE.observe(time.perf_counter() - t0, phase=phase)


def phase_wall_summary() -> Dict[str, Dict[str, float]]:
    """Per-phase {count, p50_ms, p99_ms} — the soak's poll-wall
    breakdown artifact."""
    out: Dict[str, Dict[str, float]] = {}
    for phase in PHASES:
        n = _POLL_PHASE.count(phase=phase)
        if not n:
            continue
        out[phase] = {
            "count": n,
            "p50_ms": round(_POLL_PHASE.quantile(0.5, phase=phase) * 1e3, 4),
            "p99_ms": round(_POLL_PHASE.quantile(0.99, phase=phase) * 1e3, 4),
        }
    return out
