"""Durable control-plane journal: the master's crash-recovery WAL.

The master is the last single point of failure in the stack — the
dispatcher's todo/doing queues, the membership registry, and the process
manager's world version live only in memory, so a master crash used to
lose exactly-once task accounting and strand every worker even though
their model state and compile caches survived. This module makes the
control plane durable the same way the data plane already is (orbax
checkpoints): an append-only, fsync-on-commit journal of state
*transitions*, replayed on the next master boot.

Layout (under ``<checkpoint_dir>/control/``):

    journal.jsonl       one JSON record per line:
        line 1          {"t": "header", "v": 1, "generation": G}
        line 2 (opt)    {"t": "snapshot", ...}   compacted prior state
        line 3..        incremental transition records

Records (appended by TaskDispatcher / Membership / ProcessManager inside
their own ``_lock`` critical sections, so the journal order IS the
mutation order):

    task_create / task_lease / task_finish / task_requeue / task_drop /
    task_fail / epoch_advance / epoch_end / training_done / job_end /
    stop_training                      — dispatcher task lifecycle
    member_join / member_death         — membership transitions
    world_version                      — cohort world-version bumps
    autoscale                          — every closed-loop rescale decision
                                         (master/autoscaler.py), APPLIED and
                                         SUPPRESSED alike; applied actions
                                         replay into AutoscaleState so a
                                         restarted master inherits cooldown
                                         and budget instead of re-firing
    emb_table / emb_shard_map /
    emb_reshard_begin / emb_reshard_commit
                                       — embedding tier shard-map
                                         transitions (embedding/sharding.py;
                                         a begin without its commit rolls
                                         back at replay — see
                                         EmbeddingState.reshard_interrupted)
    emb_replica_map / emb_hot_ids      — single-phase layout transitions
                                         (per-shard replica fan-out and the
                                         ultra-hot id set; pull-only effects,
                                         so no begin/commit fence)
    layout                             — every layout-controller decision
                                         (master/layout_controller.py),
                                         APPLIED and SUPPRESSED alike;
                                         applied actions replay into
                                         LayoutState so a restarted master
                                         inherits cooldowns and never
                                         double-fires a layout change

Durability contract: a transition the master *acted on* (a lease granted,
a report accepted) is on disk before the effect is observable — a crash
can lose at most a transition that no one was told about yet. HOW that is
achieved depends on the commit mode:

- **per-commit** (``group_commit_ms == 0``, the PR 5 behavior): ``append``
  writes + flushes + fsyncs before returning, inside the journal lock.
- **group-commit** (``group_commit_ms > 0``): mutators only ENQUEUE their
  records onto an ordered in-memory commit queue (still inside their own
  owning lock, so queue order — and therefore disk order — IS mutation
  order), and a committer thread flushes the whole queue under ONE
  write + fsync within the bounded window. ``append``/``append_many``
  return a :class:`Commit` handle; the caller releases its owning lock
  and then ``wait()``s on the handle *before* acknowledging anything to a
  worker (ack-after-fsync). Nothing acknowledged can be lost; what a
  crash CAN lose is a queued-but-unflushed suffix no one was told about —
  exactly per-commit mode's lost-response window, so crash-replay
  accounting is identical across both modes. A whole flushed group rides
  ONE ``batch`` journal line: a torn group write drops the group whole at
  replay, never a parseable prefix of a multi-record commit.

Recovery contract: opening an existing journal replays it to the final
state, **bumps the master generation**, and atomically rotates the file
(tmp + ``os.replace``) to a fresh header + compacted snapshot. In-flight
leases are conservatively requeued at the FRONT of todo (the crashed
master cannot know whether the worker finished; the report, if it ever
arrives, carries a pre-crash generation and is fenced — proto/service.py).
A torn tail line (crash mid-append) is dropped, not fatal.

What is and isn't replayed: task accounting, membership, epoch/job flags,
and the world version are; evaluation-service aggregation state, mean-loss
accumulators and summary streams are NOT (they are derived/advisory —
an eval job interrupted by a master crash re-reports or re-runs).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

JOURNAL_VERSION = 1
JOURNAL_DIRNAME = "control"
JOURNAL_FILENAME = "journal.jsonl"

_reg = default_registry()
_APPENDS = _reg.counter(
    "edl_journal_appends_total", "control-plane journal records committed")
_REPLAYED = _reg.counter(
    "edl_journal_replayed_records_total",
    "journal records replayed at master boot")
_ROTATIONS = _reg.counter(
    "edl_journal_rotations_total",
    "atomic journal rotations (every recovery compacts)")
_DROPPED = _reg.counter(
    "edl_journal_dropped_lines_total",
    "unparseable journal lines skipped during replay (torn tail)")
_RECOVERIES = _reg.counter(
    "edl_master_recoveries_total", "master boots that replayed a journal")
_GENERATION = _reg.gauge(
    "edl_master_generation", "current master generation")
_GROUP_FLUSHES = _reg.counter(
    "edl_journal_group_commit_flushes_total",
    "group-commit flushes (one write+fsync each)")
_GROUP_RECORDS = _reg.counter(
    "edl_journal_group_commit_records_total",
    "records committed through the group-commit queue")
_GROUP_BATCH = _reg.histogram(
    "edl_journal_group_commit_batch_records",
    "records per group-commit flush")
_COMMIT_LATENCY = _reg.histogram(
    "edl_journal_commit_latency_seconds",
    "enqueue-to-durable latency per commit (both modes)")
_QUEUE_DEPTH = _reg.gauge(
    "edl_journal_commit_queue_depth",
    "records sitting in the open group-commit batch (saturation signal: "
    "a depth that grows across windows means offered commit rate exceeds "
    "flush throughput)")
_BACKPRESSURE = _reg.counter(
    "edl_journal_backpressure_warnings_total",
    "group-commit windows whose queue depth crossed the backpressure "
    "warning threshold")


@dataclass
class DispatcherState:
    """Replayed dispatcher state (what TaskDispatcher restores from)."""

    todo: List[Dict[str, Any]] = field(default_factory=list)
    next_task_id: int = 1
    epoch: int = -1
    num_epochs: Optional[int] = None
    finished_training: int = 0
    failed_permanently: int = 0
    completed_versions: int = 0
    epoch_end_fired: bool = False
    job_end_fired: bool = False
    stop_training: bool = False
    training_done: bool = False
    save_model_created: bool = False
    requeued_leases: int = 0
    # goodput accounting (observability/goodput.py): completed training
    # records (task_finish carries `records` since ISSUE 12; absent in
    # older journals -> 0) and the wasted-work ledger totals replayed
    # from `wasted_work` records — the bill survives a master restart.
    records_completed: int = 0
    wasted_records: int = 0
    wasted_events: int = 0
    wasted_by_reason: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # the CURRENT replay's conservatively-requeued in-flight leases
    # ({task_id, records} per TRAINING lease): the successor journals
    # these as `crash_requeue` wasted-work entries at restore. Always
    # overwritten by the replay's end block (a snapshot-carried list from
    # a prior generation must not re-journal).
    requeued: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class MembershipState:
    """Replayed membership registry (liveness clocks restart at takeover)."""

    workers: List[Dict[str, Any]] = field(default_factory=list)
    next_id: int = 0
    version: int = 0


@dataclass
class EmbeddingState:
    """Replayed embedding-tier shard map (ShardMapOwner restores from
    this — embedding/sharding.py). The invariant the replay enforces:
    `owners`/`version` are always the last COMMITTED map. A master
    killed between `emb_reshard_begin` and `emb_reshard_commit` replays
    with the pre-move assignment and `reshard_interrupted=True` — the
    successor re-plans against live membership, and clients
    conservatively requeue in-flight pushes (the stores' per-client
    sequence watermarks dedupe any that actually landed, so exactly-once
    holds across the rollback)."""

    version: int = 0
    num_shards: int = 0
    owners: List[int] = field(default_factory=list)
    # shard id -> read-replica worker ids (ISSUE 13): committed beside
    # the primaries in the same records, replayed with the same
    # begin-without-commit rollback semantics
    replicas: List[List[int]] = field(default_factory=list)
    # per-shard replica TARGETS set by the layout controller (empty =
    # uniform config default) — distinct from `replicas`, which is the
    # current assignment; targets persist across later reshardings
    replica_counts: List[int] = field(default_factory=list)
    # the worker-replicated ultra-hot id set (ISSUE 20)
    hot_ids: List[int] = field(default_factory=list)
    tables: List[Dict[str, Any]] = field(default_factory=list)
    reshard_interrupted: bool = False


@dataclass
class AutoscaleState:
    """Replayed closed-loop autoscaler state (master/autoscaler.py
    restores from this). The invariant: `last_action_ts` (wall clock —
    the only clock that survives a process restart) and
    `actions_applied` reflect every APPLIED action ever journaled, so a
    successor master inherits the cooldown window and the spent action
    budget instead of immediately re-firing on the same signal its
    predecessor just acted on. Suppressed decisions replay into
    `records` only — they are forensic, not state."""

    actions_applied: int = 0
    last_action_ts: float = 0.0
    by_kind: Dict[str, int] = field(default_factory=dict)
    records: int = 0


@dataclass
class LayoutState:
    """Replayed layout-controller state (master/layout_controller.py
    restores from this) — same invariant as AutoscaleState, but with
    per-KIND cooldown clocks: a replica fan-out five minutes ago must
    not cool down a pending split, and vice versa. `last_action_ts` is
    the overall max (budget accounting); `last_ts_by_kind` is what the
    cooldown gate actually reads."""

    actions_applied: int = 0
    last_action_ts: float = 0.0
    by_kind: Dict[str, int] = field(default_factory=dict)
    last_ts_by_kind: Dict[str, float] = field(default_factory=dict)
    records: int = 0


@dataclass
class ReplayResult:
    prior_generation: int = 0
    records: int = 0
    dropped_lines: int = 0
    dispatcher: Optional[DispatcherState] = None
    membership: Optional[MembershipState] = None
    world_version: int = 0
    embedding: Optional[EmbeddingState] = None
    autoscale: Optional[AutoscaleState] = None
    layout: Optional[LayoutState] = None


def _replay_dispatcher(
    state: DispatcherState, doing: Dict[int, Dict[str, Any]],
    rtype: str, rec: Dict[str, Any],
) -> None:
    """Apply one dispatcher transition record to the replay state."""

    def take_todo(task_id: int) -> Optional[Dict[str, Any]]:
        for i, t in enumerate(state.todo):
            if t["task_id"] == task_id:
                return state.todo.pop(i)
        return None

    if rtype == "task_create":
        task = dict(rec["task"])
        if rec.get("front"):
            state.todo.insert(0, task)
        else:
            state.todo.append(task)
        state.next_task_id = max(state.next_task_id, task["task_id"] + 1)
        if task.get("type") == _SAVE_MODEL_TYPE:
            state.save_model_created = True
    elif rtype == "task_lease":
        task = take_todo(rec["task_id"])
        if task is not None:
            doing[rec["task_id"]] = task
    elif rtype == "task_finish":
        doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        if rec.get("training"):
            state.finished_training += 1
            state.completed_versions += 1
            state.records_completed += int(rec.get("records", 0) or 0)
    elif rtype == "wasted_work":
        records = int(rec.get("records", 0) or 0)
        state.wasted_events += 1
        state.wasted_records += records
        ent = state.wasted_by_reason.setdefault(
            str(rec.get("reason", "?")), {"events": 0, "records": 0})
        ent["events"] += 1
        ent["records"] += records
    elif rtype == "task_requeue":
        task = doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        if task is not None:
            task["start"] = rec.get("start", task["start"])
            task["retries"] = rec.get("retries", task.get("retries", 0))
            state.todo.insert(0, task)
        # a drain requeue retires its `completed` prefix (covered by the
        # worker's drain checkpoint) — replay parity for the live
        # records_completed counter
        state.records_completed += int(rec.get("completed", 0) or 0)
    elif rtype == "task_drop":
        doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        state.records_completed += int(rec.get("completed", 0) or 0)
    elif rtype == "task_fail":
        doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        state.failed_permanently += 1
    elif rtype == "epoch_advance":
        state.epoch = rec["epoch"]
        state.epoch_end_fired = False
    elif rtype == "epoch_end":
        if rec.get("epoch", state.epoch) == state.epoch:
            state.epoch_end_fired = True
    elif rtype == "training_done":
        state.training_done = True
    elif rtype == "job_end":
        state.job_end_fired = True
    elif rtype == "stop_training":
        state.stop_training = True
        state.num_epochs = rec.get("num_epochs", state.num_epochs)
        state.todo = [t for t in state.todo if t.get("type") != _TRAINING_TYPE]


# pb.TRAINING / pb.EVALUATION / pb.SAVE_MODEL without importing protobuf
# here (the journal must stay importable in protobuf-free tooling
# contexts); a test pins these to the generated enum values.
_TRAINING_TYPE = 0
_EVALUATION_TYPE = 1
_SAVE_MODEL_TYPE = 3

_DISPATCHER_RECORDS = frozenset({
    "task_create", "task_lease", "task_finish", "task_requeue", "task_drop",
    "task_fail", "epoch_advance", "epoch_end", "training_done", "job_end",
    "stop_training", "wasted_work",
})


def replay_lines(lines: List[str]) -> ReplayResult:
    """Replay journal lines to a final state (tolerant of a torn tail)."""
    result = ReplayResult()
    dispatcher: Optional[DispatcherState] = None
    membership: Optional[MembershipState] = None
    embedding: Optional[EmbeddingState] = None
    autoscale: Optional[AutoscaleState] = None
    layout: Optional[LayoutState] = None
    # an emb_reshard_begin whose commit has not replayed yet:
    # {"version": v, "owners": [...]} — promoted to the committed map by
    # emb_reshard_commit, rolled back (reshard_interrupted) at the end
    pending_reshard: Optional[Dict[str, Any]] = None
    doing: Dict[int, Dict[str, Any]] = {}
    lease_order: List[int] = []

    def emb() -> EmbeddingState:
        nonlocal embedding
        if embedding is None:
            embedding = EmbeddingState()
        return embedding

    def apply(rec: Dict[str, Any]) -> None:
        nonlocal dispatcher, membership, embedding, pending_reshard
        nonlocal autoscale, layout
        rtype = rec["t"]
        result.records += 1
        if rtype == "header":
            result.prior_generation = int(rec.get("generation", 0))
        elif rtype == "snapshot":
            if rec.get("dispatcher") is not None:
                dispatcher = DispatcherState(**rec["dispatcher"])
            if rec.get("membership") is not None:
                membership = MembershipState(**rec["membership"])
            if rec.get("embedding") is not None:
                embedding = EmbeddingState(**rec["embedding"])
            if rec.get("autoscale") is not None:
                autoscale = AutoscaleState(**rec["autoscale"])
            if rec.get("layout") is not None:
                layout = LayoutState(**rec["layout"])
            result.world_version = int(rec.get("world_version", 0))
        elif rtype in _DISPATCHER_RECORDS:
            if dispatcher is None:
                dispatcher = DispatcherState()
            if rtype == "task_lease":
                lease_order.append(rec.get("task_id"))
            _replay_dispatcher(dispatcher, doing, rtype, rec)
        elif rtype == "member_join":
            if membership is None:
                membership = MembershipState()
            wid = int(rec["worker_id"])
            for w in membership.workers:
                if w["worker_id"] == wid:
                    membership.workers.remove(w)
                    break
            membership.workers.append(
                {"worker_id": wid, "name": rec.get("name", ""), "alive": True,
                 "led_by": rec.get("led_by"),
                 # embedding data-plane endpoint (ISSUE 15): replays so a
                 # successor master serves the same owner address book
                 "data_addr": rec.get("data_addr") or ""}
            )
            membership.next_id = max(membership.next_id, wid + 1)
            membership.version = max(membership.version, int(rec.get("version", 0)))
        elif rtype == "member_death":
            if membership is None:
                membership = MembershipState()
            for w in membership.workers:
                if w["worker_id"] == int(rec["worker_id"]):
                    w["alive"] = False
            membership.version = max(membership.version, int(rec.get("version", 0)))
        elif rtype == "world_version":
            result.world_version = max(result.world_version, int(rec["version"]))
        elif rtype == "autoscale":
            if autoscale is None:
                autoscale = AutoscaleState()
            autoscale.records += 1
            if rec.get("decision") == "applied":
                autoscale.actions_applied += 1
                autoscale.last_action_ts = max(
                    autoscale.last_action_ts, float(rec.get("ts") or 0.0)
                )
                kind = str(rec.get("kind", "?"))
                autoscale.by_kind[kind] = autoscale.by_kind.get(kind, 0) + 1
        elif rtype == "layout":
            if layout is None:
                layout = LayoutState()
            layout.records += 1
            if rec.get("decision") == "applied":
                layout.actions_applied += 1
                ts = float(rec.get("ts") or 0.0)
                layout.last_action_ts = max(layout.last_action_ts, ts)
                kind = str(rec.get("kind", "?"))
                layout.by_kind[kind] = layout.by_kind.get(kind, 0) + 1
                layout.last_ts_by_kind[kind] = max(
                    layout.last_ts_by_kind.get(kind, 0.0), ts)
        elif rtype == "emb_table":
            e = emb()
            if not any(t["name"] == rec["name"] for t in e.tables):
                e.tables.append({
                    k: rec[k] for k in
                    ("name", "vocab", "dim", "seed", "init_scale")
                    if k in rec
                })
        elif rtype == "emb_shard_map":
            e = emb()
            e.version = int(rec["version"])
            e.num_shards = int(rec["num_shards"])
            e.owners = [int(o) for o in rec["owners"]]
            e.replicas = [[int(o) for o in r]
                          for r in rec.get("replicas", [])]
            e.reshard_interrupted = False
            pending_reshard = None
        elif rtype == "emb_replica_map":
            e = emb()
            e.version = int(rec["version"])
            e.replicas = [[int(o) for o in r]
                          for r in rec.get("replicas", [])]
            e.replica_counts = [int(c)
                                for c in rec.get("replica_counts", [])]
        elif rtype == "emb_hot_ids":
            e = emb()
            e.version = int(rec["version"])
            e.hot_ids = [int(i) for i in rec.get("hot_ids", [])]
        elif rtype == "emb_reshard_begin":
            pending_reshard = {
                "version": int(rec["version"]),
                # splits/merges ride the same begin→commit fence and
                # change the shard COUNT; a plain reshard journals the
                # unchanged count (older journals omit the field)
                "num_shards": int(rec.get("num_shards", 0)),
                "owners": [int(o) for o in rec["owners"]],
                "replicas": [[int(o) for o in r]
                             for r in rec.get("replicas", [])],
            }
        elif rtype == "emb_reshard_commit":
            e = emb()
            if (pending_reshard is not None
                    and pending_reshard["version"] == int(rec["version"])):
                e.version = pending_reshard["version"]
                if pending_reshard["num_shards"]:
                    if pending_reshard["num_shards"] != e.num_shards:
                        # a committed split/merge drops replica targets
                        # and the hot set's SHARD routing is unaffected
                        # (hot ids are global); targets re-derive from
                        # the controller's next pass
                        e.replica_counts = []
                    e.num_shards = pending_reshard["num_shards"]
                e.owners = pending_reshard["owners"]
                e.replicas = pending_reshard["replicas"]
                e.reshard_interrupted = False
                pending_reshard = None
            else:
                logger.warning(
                    "emb_reshard_commit v%s without a matching begin; "
                    "ignored", rec.get("version"),
                )
        else:
            logger.warning("unknown journal record type %r ignored", rtype)

    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
            if rec.get("t") == "batch":
                # a multi-record commit rides ONE line (append_many): it is
                # applied whole here or dropped whole below — validate
                # before applying so a corrupt batch can't half-apply
                subrecs = rec["records"]
                if not isinstance(subrecs, list) or not all(
                    isinstance(s, dict) and "t" in s for s in subrecs
                ):
                    raise ValueError("malformed batch record")
            else:
                rec["t"]                   # KeyError -> dropped below
                subrecs = [rec]
        except (ValueError, KeyError, TypeError):
            # torn tail (crash mid-append) is expected; a garbled line in
            # the middle is not, but dropping it beats refusing to recover
            result.dropped_lines += 1
            _DROPPED.inc()
            if i < len(lines) - 1:
                logger.warning(
                    "journal line %d unparseable (not the tail); skipped", i + 1
                )
            continue
        for sub in subrecs:
            apply(sub)
    if dispatcher is not None:
        # conservative lease recovery: the crashed master cannot know
        # whether leased work finished — requeue every in-flight lease at
        # the FRONT (oldest first), exactly once; pre-crash reports are
        # generation-fenced so nothing is double-counted. dict.fromkeys
        # dedupes a task that was leased, requeued, and re-leased before
        # the crash (lease_order carries it twice but it must come back
        # exactly once, or its records train twice after recovery).
        requeued = [doing[t] for t in dict.fromkeys(lease_order) if t in doing]
        if dispatcher.stop_training:
            # the live dispatcher drops in-flight TRAINING work after an
            # early stop (its requeue path journals task_drop); replay must
            # not resurrect a training lease the stop already condemned
            requeued = [t for t in requeued if t.get("type") != _TRAINING_TYPE]
        # EVALUATION tasks do NOT survive a crash: EvaluationService state
        # (job ids, metric aggregation) is volatile by contract, so a
        # replayed eval task would report into a dead eval job id — or
        # worse, into a post-recovery job that REUSED the id, corrupting
        # its metrics. The successor re-triggers evaluation fresh instead
        # (the dispatcher restore re-fires the epoch-end callbacks).
        requeued = [t for t in requeued if t.get("type") != _EVALUATION_TYPE]
        dispatcher.todo = [
            t for t in dispatcher.todo if t.get("type") != _EVALUATION_TYPE
        ]
        dispatcher.todo = requeued + dispatcher.todo
        dispatcher.requeued_leases = len(requeued)
        # the wasted-work view of the conservative requeue: every
        # requeued TRAINING lease's span re-trains whole. The successor
        # dispatcher journals these as `crash_requeue` entries at restore
        # (this list is replay-LOCAL — always overwritten here, so a
        # snapshot-carried copy from a prior generation never
        # re-journals).
        dispatcher.requeued = [
            {"task_id": t.get("task_id", -1),
             "records": max(0, int(t.get("end", 0)) - int(t.get("start", 0)))}
            for t in requeued if t.get("type") == _TRAINING_TYPE
        ]
    if pending_reshard is not None:
        # master died mid-resharding: the moves may be partially executed
        # but were never committed — roll back to the committed map (the
        # donors still hold every uncommitted shard by protocol) and flag
        # the interruption so the successor re-plans and clients requeue
        # in-flight pushes (store seq fencing dedupes re-sends)
        e = emb()
        e.reshard_interrupted = True
        logger.warning(
            "journal replay: resharding v%d was begun but never committed; "
            "rolled back to shard map v%d", pending_reshard["version"],
            e.version,
        )
    result.dispatcher = dispatcher
    result.membership = membership
    result.embedding = embedding
    result.autoscale = autoscale
    result.layout = layout
    return result


def _render(recs: List[Dict[str, Any]]) -> str:
    """Serialize one commit (or one group flush) as ONE journal line —
    multi-record payloads ride a ``batch`` wrapper so a torn write drops
    them whole at replay, never as a parseable prefix."""
    if len(recs) == 1:
        return json.dumps(recs[0]) + "\n"
    return json.dumps({"t": "batch", "records": recs}) + "\n"


class JournalCommitError(RuntimeError):
    """A group commit could not be made durable (flush failed or timed
    out). Callers must NOT acknowledge the transition they enqueued."""


# shared pre-completed event for per-commit / no-journal commits — wait()
# on these returns immediately
_DONE_EVENT = threading.Event()
_DONE_EVENT.set()


class Commit:
    """Durability handle for one journal commit.

    ``wait()`` blocks until the commit's records are flushed + fsynced
    (a no-op in per-commit mode, where ``append`` already did the fsync).
    The ack-after-fsync contract: release your owning lock, ``wait()``,
    THEN send the RPC response that acknowledges the transition."""

    __slots__ = ("_event", "_batch")

    def __init__(self, event: threading.Event = _DONE_EVENT, batch=None):
        self._event = event
        self._batch = batch

    def wait(self, timeout_s: float = 30.0) -> None:
        if not self._event.wait(timeout_s):
            raise JournalCommitError(
                f"journal group commit not durable after {timeout_s:.0f}s "
                "(committer wedged or disk stalled)"
            )
        err = getattr(self._batch, "error", None)
        if err is not None:
            raise JournalCommitError(f"journal group commit failed: {err!r}")


class CommitGate:
    """Mixin: the ack-after-fsync plumbing shared by journal-owning
    control-plane components (TaskDispatcher, Membership).

    The owning class declares ``self._journal`` (or None) and
    ``self._pending_commit = None  # guarded_by: _lock`` in its own
    ``__init__``. The protocol: mutators call :meth:`_j` (or assign
    ``self._pending_commit`` from ``append_many`` directly) INSIDE their
    ``_lock`` critical section, take the parked commit with
    :meth:`_take_commit_locked` in the SAME lock hold, and
    :meth:`_await` it after release — before sending any RPC response
    that acknowledges the journaled transition. In per-commit mode the
    wait is a no-op (append already fsynced)."""

    _journal = None
    _pending_commit = None

    def _j(self, rtype: str, **fields: Any) -> None:  # holds: _lock
        """Enqueue one journal record (no-op without a journal); the
        Commit parks on ``_pending_commit`` for the take-and-await."""
        if self._journal is not None:
            self._pending_commit = self._journal.append(rtype, **fields)

    def _take_commit_locked(self):  # holds: _lock
        """The last commit this critical section enqueued (None if none).
        Flush order is enqueue order, so waiting on the LAST commit also
        covers every earlier record of the same critical section (a lost
        earlier window poisons the journal, failing later waits too)."""
        commit, self._pending_commit = self._pending_commit, None
        return commit

    @staticmethod
    def _await(commit: Optional[Commit]) -> None:
        """Ack-after-fsync barrier: block (outside the lock) until the
        critical section's journal records are durable. A commit that
        cannot be made durable raises — the caller's RPC fails instead of
        acknowledging a transition the disk never saw."""
        if commit is not None:
            commit.wait()


class _OpenBatch:
    """The commit queue between two flushes: records land here in mutation
    order (enqueued under the mutator's owning lock), the committer swaps
    the whole batch out and flushes it under one fsync."""

    __slots__ = ("records", "enqueued_at", "opened_at", "event", "error")

    def __init__(self):
        self.records: List[Dict[str, Any]] = []
        self.enqueued_at: List[float] = []   # perf_counter per commit
        self.opened_at = 0.0                 # monotonic, first enqueue
        self.event = threading.Event()
        self.error: Optional[BaseException] = None


class ControlPlaneJournal:
    """Append-only WAL with atomic rotation and a persisted generation.

    Thread-safe; appends are called from inside the dispatcher's and
    membership's ``_lock`` critical sections (lock order: owner lock ->
    journal ``_lock``/``_qcv``; the journal never calls back out, so no
    cycle). With ``group_commit_ms > 0`` appends only enqueue (no I/O
    under the owning lock) and a committer thread owns the write+fsync —
    callers wait on the returned :class:`Commit` AFTER releasing their
    lock, before acknowledging the transition.
    """

    def __init__(self, checkpoint_dir: str, fsync: bool = True,
                 group_commit_ms: float = 0.0):
        self.dir = os.path.join(checkpoint_dir, JOURNAL_DIRNAME)
        self.path = os.path.join(self.dir, JOURNAL_FILENAME)
        self._fsync = fsync
        self._window_s = max(0.0, group_commit_ms) / 1000.0
        if self._window_s > 10.0:
            # config.validate rejects this at submit time; direct
            # constructions (tests, bench) get the clamp so a window can
            # never approach Commit.wait's 30s wedge deadline
            logger.warning(
                "journal group-commit window clamped %.0fms -> 10000ms",
                self._window_s * 1000,
            )
            self._window_s = 10.0
        self._lock = threading.Lock()
        self._fh = None                      # guarded_by: _lock
        # group-commit queue state: _qcv (a Condition) guards the open
        # batch; NEVER held during I/O, so enqueuers — who hold their own
        # control-plane lock — never block behind an fsync
        self._qcv = threading.Condition(threading.Lock())
        self._queue = _OpenBatch()           # guarded_by: _qcv
        self._closing = False                # guarded_by: _qcv
        # flush(): ask the committer to cut its window NOW (the file
        # handle stays single-writer; a foreign-thread write could land a
        # NEWER batch before an older already-swapped one, inverting the
        # disk-order == mutation-order replay invariant)
        self._flush_now = False              # guarded_by: _qcv
        # First flush failure poisons the journal: a failed write can
        # leave a PARTIAL line, and appending past it would fuse the next
        # flush into one unparseable line — silently dropping acknowledged
        # records at replay. Worse, a later window's successful fsync
        # would let its waiters ack while an EARLIER window's records are
        # not on disk (flush order == ack-validity order only while every
        # flush succeeds). Once poisoned, every queued and future commit
        # fails its wait() — no ack ever leaves for an undurable record.
        self._poisoned: Optional[BaseException] = None   # guarded_by: _qcv
        # saturation observability: deepest open batch seen (records), and
        # a once-per-window backpressure-warning edge trigger
        self._queue_high_water = 0           # guarded_by: _qcv
        self._bp_warned = False              # guarded_by: _qcv
        self._committer: Optional[threading.Thread] = None
        self.generation = 1
        self.recovered = False
        self.replay: Optional[ReplayResult] = None
        self._open()
        if self._window_s > 0:
            self._committer = threading.Thread(
                target=self._committer_loop,
                name="journal-committer",
                daemon=True,
            )
            self._committer.start()

    #: open-batch depth past which the journal logs a backpressure
    #: warning (once per window): the queue is unbounded by design — the
    #: committer always drains it — but a window this deep means the
    #: offered commit rate is outrunning flush throughput and commit
    #: latency is about to climb toward Commit.wait's deadline
    COMMIT_QUEUE_WARN_DEPTH = 4096

    @property
    def group_commit(self) -> bool:
        return self._window_s > 0

    @property
    def commit_queue_high_water(self) -> int:
        """Deepest open group-commit batch observed (records) — the soak
        harness's journal-saturation cliff metric."""
        with self._qcv:
            return self._queue_high_water

    # -------------------------------------------------------------- #
    # open / rotate / replay

    def _open(self) -> None:  # holds: _lock (construction)
        os.makedirs(self.dir, exist_ok=True)
        if os.path.exists(self.path):
            # boot-time replay read: single-threaded (no mutator exists
            # yet), the lock is held only for construction-ordering
            # reasons: edl-lint: disable=EDL103
            with open(self.path, encoding="utf-8") as f:
                lines = f.readlines()
            self.replay = replay_lines(lines)
            _REPLAYED.inc(self.replay.records)
            self.generation = self.replay.prior_generation + 1
            self.recovered = True
            _RECOVERIES.inc()
            logger.warning(
                "control journal replayed: %d records (%d dropped), prior "
                "generation %d -> %d, %d in-flight lease(s) requeued",
                self.replay.records, self.replay.dropped_lines,
                self.replay.prior_generation, self.generation,
                (self.replay.dispatcher.requeued_leases
                 if self.replay.dispatcher else 0),
            )
        self._rotate_locked()
        # boot-time append-handle open, same single-threaded window:
        # edl-lint: disable=EDL103
        self._fh = open(self.path, "a", encoding="utf-8")
        _GENERATION.set(self.generation)

    def _fsync_dir(self) -> None:
        """Make the directory entry durable: file-level fsync alone does
        not persist a newly created or os.replace'd NAME on POSIX — a host
        crash could drop the whole journal despite every append having
        been fsynced, and the successor would rebuild from scratch."""
        try:
            fd = os.open(self.dir, os.O_RDONLY)
        except OSError:
            return
        try:
            # directory-entry durability is part of the journal's leaf I/O
            # contract — only journal.file is ever held here:
            # edl-lint: disable=EDL103
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def _rotate_locked(self) -> None:
        """Atomically (re)write the journal as header + compacted snapshot.
        Runs before the append handle opens (single-threaded boot)."""
        tmp = self.path + ".tmp"
        # boot-time rotation write — see the fsync note below:
        # edl-lint: disable=EDL103
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"t": "header", "v": JOURNAL_VERSION,
                 "generation": self.generation}
            ) + "\n")
            if self.replay is not None and (
                self.replay.dispatcher is not None
                or self.replay.membership is not None
                or self.replay.embedding is not None
                or self.replay.autoscale is not None
                or self.replay.layout is not None
                or self.replay.world_version
            ):
                f.write(json.dumps({
                    "t": "snapshot",
                    "dispatcher": (
                        asdict(self.replay.dispatcher)
                        if self.replay.dispatcher is not None else None
                    ),
                    "membership": (
                        asdict(self.replay.membership)
                        if self.replay.membership is not None else None
                    ),
                    "embedding": (
                        asdict(self.replay.embedding)
                        if self.replay.embedding is not None else None
                    ),
                    "autoscale": (
                        asdict(self.replay.autoscale)
                        if self.replay.autoscale is not None else None
                    ),
                    "layout": (
                        asdict(self.replay.layout)
                        if self.replay.layout is not None else None
                    ),
                    "world_version": self.replay.world_version,
                }) + "\n")
            f.flush()
            # boot-time rotation: single-threaded (the append handle is
            # not open yet), so no mutator can queue behind this fsync:
            # edl-lint: disable=EDL403,EDL103
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fsync_dir()
        _ROTATIONS.inc()

    # -------------------------------------------------------------- #
    # replayed-state accessors (None = nothing to restore)

    def dispatcher_snapshot(self) -> Optional[DispatcherState]:
        if self.replay is None:
            return None
        return self.replay.dispatcher

    def membership_snapshot(self) -> Optional[MembershipState]:
        if self.replay is None:
            return None
        return self.replay.membership

    def embedding_snapshot(self) -> Optional[EmbeddingState]:
        if self.replay is None:
            return None
        return self.replay.embedding

    def autoscale_snapshot(self) -> Optional[AutoscaleState]:
        if self.replay is None:
            return None
        return self.replay.autoscale

    def layout_snapshot(self) -> Optional[LayoutState]:
        if self.replay is None:
            return None
        return self.replay.layout

    @property
    def world_version(self) -> int:
        return self.replay.world_version if self.replay is not None else 0

    # -------------------------------------------------------------- #
    # append path

    def append(self, rtype: str, **fields: Any) -> Commit:
        """Commit one transition record; see :meth:`append_many`."""
        return self.append_many([(rtype, fields)])

    def append_many(self, records: List[Tuple[str, Dict[str, Any]]]) -> Commit:
        """Commit a batch of records under ONE fsync (bulk task creation,
        batched lease grants).

        A multi-record batch is serialized as ONE ``batch`` line: a large
        batch can span several write(2) syscalls, and a crash between them
        must not persist a parseable prefix (an ``epoch_advance`` with only
        some of its ``task_create`` lines would replay a partial epoch).
        One line is either whole at replay or a torn tail dropped whole —
        the batch commits all-or-nothing.

        Per-commit mode: the records are durable when this returns (the
        returned Commit is pre-completed). Group-commit mode: the records
        are ENQUEUED in call order; ``wait()`` the returned Commit (after
        releasing your owning lock) before acknowledging the transition."""
        if not records:
            return Commit()
        recs = [{"t": rtype, **fields} for rtype, fields in records]
        if self._window_s > 0:
            return self._enqueue(recs)
        data = _render(recs)
        t0 = time.perf_counter()
        with self._lock:
            if self._fh is None:
                # post-close append (a component outliving its master after
                # crash_stop): dropping is correct — a NEW master owns the
                # file now, and interleaving two writers would corrupt it
                logger.warning(
                    "journal append after close dropped (%d record(s))",
                    len(records),
                )
                return Commit()
            self._fh.write(data)
            self._fh.flush()
            if self._fsync:
                # the one sanctioned per-commit fsync site: the journal
                # lock is a leaf I/O lock, not a control-plane lock — the
                # group-commit committer is the scalable path
                os.fsync(self._fh.fileno())  # edl-lint: disable=EDL403,EDL103
        _APPENDS.inc(len(records))
        _COMMIT_LATENCY.observe(time.perf_counter() - t0)
        return Commit()

    # -------------------------------------------------------------- #
    # group-commit pipeline

    def _enqueue(self, recs: List[Dict[str, Any]]) -> Commit:
        """Queue one commit's records onto the open batch (called under the
        mutator's owning lock — cheap: list appends, no I/O). Queue order
        is mutation order, and the committer flushes in queue order, so
        disk order stays mutation order exactly as in per-commit mode."""
        with self._qcv:
            if self._poisoned is not None:
                return self._failed_commit(self._poisoned)
            if self._closing:
                logger.warning(
                    "journal append after close dropped (%d record(s))",
                    len(recs),
                )
                return Commit()
            batch = self._queue
            if not batch.records:
                batch.opened_at = time.monotonic()
            batch.records.extend(recs)
            batch.enqueued_at.append(time.perf_counter())
            depth = len(batch.records)
            _QUEUE_DEPTH.set(depth)
            if depth > self._queue_high_water:
                self._queue_high_water = depth
            if depth > self.COMMIT_QUEUE_WARN_DEPTH and not self._bp_warned:
                # edge-triggered per window (the committer resets the
                # flag on swap): one warning per saturated window, not
                # one per commit
                self._bp_warned = True
                _BACKPRESSURE.inc()
                logger.warning(
                    "journal group-commit BACKPRESSURE: %d records queued "
                    "in the open window (warn threshold %d) — offered "
                    "commit rate exceeds flush throughput",
                    depth, self.COMMIT_QUEUE_WARN_DEPTH,
                )
            self._qcv.notify_all()
            return Commit(batch.event, batch)

    def _committer_loop(self) -> None:
        """The single committer: waits for the open batch to fill its
        bounded window (``--journal_group_commit_ms``), swaps it out, and
        flushes it under one write+fsync. Only this thread (and close())
        touches the file handle in group-commit mode."""
        while True:
            with self._qcv:
                while not self._queue.records and not self._closing:
                    self._qcv.wait()
                if self._closing:
                    # close() drains or aborts the remaining queue itself
                    return
                deadline = self._queue.opened_at + self._window_s
                while not self._closing and not self._flush_now:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._qcv.wait(remaining)
                batch, self._queue = self._queue, _OpenBatch()
                self._flush_now = False
                self._bp_warned = False
                _QUEUE_DEPTH.set(0)
            if batch.records:
                # a close() racing the window wait can hand us a freshly
                # swapped EMPTY batch — flushing it would write a spurious
                # empty batch line and count a zero-record flush
                self._flush_batch(batch)
            else:
                batch.event.set()

    @staticmethod
    def _failed_commit(err: BaseException) -> Commit:
        batch = _OpenBatch()
        batch.error = err
        batch.event.set()
        return Commit(batch.event, batch)

    def _flush_batch(self, batch: _OpenBatch) -> None:
        """One write + flush + fsync for everything queued since the last
        flush, serialized as ONE line (all-or-nothing at replay), then
        release every commit waiting on it. Never raises: a flush failure
        parks the error on the batch, POISONS the journal (the failed
        write may have torn the tail — writing past it would fuse lines
        and drop acknowledged records at replay; and a later successful
        flush must not release acks ordered after a lost window), and
        every ``wait()`` re-raises — no gated ack ever goes out."""
        with self._qcv:
            poisoned = self._poisoned
        if poisoned is not None:
            batch.error = poisoned
            batch.event.set()
            return
        t_flush = time.perf_counter()
        try:
            data = _render(batch.records)
            with self._lock:
                if self._fh is None:
                    raise JournalCommitError("journal closed under committer")
                self._fh.write(data)
                self._fh.flush()
                if self._fsync:
                    # the group-commit fsync: ONE syscall for the whole
                    # window's commits, on the committer thread — never
                    # under a control-plane lock (the EDL403 idiom)
                    os.fsync(self._fh.fileno())  # edl-lint: disable=EDL403,EDL103
        except BaseException as e:
            batch.error = e
            with self._qcv:
                self._poisoned = e
                self._qcv.notify_all()
            logger.exception(
                "journal group-commit flush FAILED (%d record(s)); their "
                "acks will not be released and the journal is POISONED — "
                "every further commit fails until a new master takes over",
                len(batch.records),
            )
        finally:
            batch.event.set()
        if batch.error is None:
            _APPENDS.inc(len(batch.records))
            _GROUP_FLUSHES.inc()
            _GROUP_RECORDS.inc(len(batch.records))
            _GROUP_BATCH.observe(len(batch.records))
            now = time.perf_counter()
            for t0 in batch.enqueued_at:
                _COMMIT_LATENCY.observe(now - t0)
            if now - t_flush > 1.0:
                logger.warning(
                    "slow journal group-commit flush: %.2fs for %d records",
                    now - t_flush, len(batch.records),
                )

    def _stop_committer(self, drain: bool) -> None:
        """Wind the committer down. ``drain=True`` (orderly close) flushes
        whatever is still queued; ``drain=False`` (simulated crash) drops
        it — exactly what SIGKILL would lose: queued records whose acks
        were never released — and fails any waiters."""
        with self._qcv:
            self._closing = True
            batch, self._queue = self._queue, _OpenBatch()
            _QUEUE_DEPTH.set(0)
            self._qcv.notify_all()
        if self._committer is not None:
            self._committer.join(timeout=10.0)
            self._committer = None
        if not batch.records:
            return
        if drain:
            self._flush_batch(batch)
        else:
            batch.error = JournalCommitError(
                "journal crashed with the commit queued but not flushed"
            )
            batch.event.set()
            logger.warning(
                "journal crash-close dropped %d queued record(s) "
                "(unacknowledged by construction)", len(batch.records),
            )

    def flush(self, timeout_s: float = 30.0) -> None:
        """Make the OPEN group-commit batch durable now, without closing
        (no-op in per-commit mode, where appends are already durable, and
        on an empty queue). The clean-shutdown hook for owners whose last
        record may still be riding the committer's window — e.g. the
        ProcessManager's newest ``world_version`` record at a clean stop.

        The flush itself runs on the COMMITTER thread (this method only
        signals it to cut the window early and waits for the batch's
        event): a foreign-thread write could land a newer batch before an
        older already-swapped one and invert the disk-order == mutation-
        order replay invariant. Failures park on the batch exactly as a
        committer flush failure would (waiters raise; the journal
        poisons); a wedged committer bounds this wait at `timeout_s`."""
        if self._window_s <= 0:
            return
        with self._qcv:
            if self._closing or not self._queue.records:
                # nothing queued — or the committer already swapped the
                # batch out and is flushing it as we speak
                return
            batch = self._queue
            self._flush_now = True
            self._qcv.notify_all()
        batch.event.wait(timeout_s)

    def close(self) -> None:
        """Orderly close: drain the commit queue, then fsync + close."""
        self._close(drain=True)

    def abort(self) -> None:
        """Simulated-crash close (Master.crash): queued-but-unflushed
        commits are DROPPED, as SIGKILL would — nothing they gated was
        acknowledged, so the successor's replay accounting is identical
        to a real kill."""
        self._close(drain=False)

    def _close(self, drain: bool) -> None:
        if self._window_s > 0:
            self._stop_committer(drain)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    if self._fsync:
                        # teardown: the committer is already stopped and
                        # mutators' post-close appends drop — nothing can
                        # queue behind this final fsync:
                        # edl-lint: disable=EDL403,EDL103
                        os.fsync(self._fh.fileno())
                finally:
                    self._fh.close()
                    self._fh = None

    def discard(self) -> None:
        """Clean-completion teardown: close the journal and retire it to
        ``journal.jsonl.completed``. Only for a job that actually finished —
        a live journal whose replay says training_done/job_end would make a
        later re-submission with the same checkpoint_dir come up
        born-finished and silently no-op. The rename (not a delete) keeps
        the final generation + accounting on disk for forensics. Crash and
        abort paths never call this; they keep the journal live so the
        successor recovers from it."""
        self.close()
        try:
            os.replace(self.path, self.path + ".completed")
            self._fsync_dir()
        except OSError:
            # the journal survived with job_end on it: the next submission
            # reusing this checkpoint_dir will replay it and no-op — that
            # MUST be diagnosable from the logs
            logger.exception(
                "journal retirement failed; a re-submission against %s will "
                "replay a finished job", self.path,
            )
