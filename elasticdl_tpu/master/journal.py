"""Durable control-plane journal: the master's crash-recovery WAL.

The master is the last single point of failure in the stack — the
dispatcher's todo/doing queues, the membership registry, and the process
manager's world version live only in memory, so a master crash used to
lose exactly-once task accounting and strand every worker even though
their model state and compile caches survived. This module makes the
control plane durable the same way the data plane already is (orbax
checkpoints): an append-only, fsync-on-commit journal of state
*transitions*, replayed on the next master boot.

Layout (under ``<checkpoint_dir>/control/``):

    journal.jsonl       one JSON record per line:
        line 1          {"t": "header", "v": 1, "generation": G}
        line 2 (opt)    {"t": "snapshot", ...}   compacted prior state
        line 3..        incremental transition records

Records (appended by TaskDispatcher / Membership / ProcessManager inside
their own ``_lock`` critical sections, so the journal order IS the
mutation order):

    task_create / task_lease / task_finish / task_requeue / task_drop /
    task_fail / epoch_advance / epoch_end / training_done / job_end /
    stop_training                      — dispatcher task lifecycle
    member_join / member_death         — membership transitions
    world_version                      — cohort world-version bumps

Durability contract: ``append`` returns only after the record is flushed
and fsynced, so any transition the master *acted on* (a lease granted, a
report accepted) is on disk before the effect is observable — a crash can
lose at most a transition that no one was told about yet.

Recovery contract: opening an existing journal replays it to the final
state, **bumps the master generation**, and atomically rotates the file
(tmp + ``os.replace``) to a fresh header + compacted snapshot. In-flight
leases are conservatively requeued at the FRONT of todo (the crashed
master cannot know whether the worker finished; the report, if it ever
arrives, carries a pre-crash generation and is fenced — proto/service.py).
A torn tail line (crash mid-append) is dropped, not fatal.

What is and isn't replayed: task accounting, membership, epoch/job flags,
and the world version are; evaluation-service aggregation state, mean-loss
accumulators and summary streams are NOT (they are derived/advisory —
an eval job interrupted by a master crash re-reports or re-runs).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

JOURNAL_VERSION = 1
JOURNAL_DIRNAME = "control"
JOURNAL_FILENAME = "journal.jsonl"

_reg = default_registry()
_APPENDS = _reg.counter(
    "edl_journal_appends_total", "control-plane journal records committed")
_REPLAYED = _reg.counter(
    "edl_journal_replayed_records_total",
    "journal records replayed at master boot")
_ROTATIONS = _reg.counter(
    "edl_journal_rotations_total",
    "atomic journal rotations (every recovery compacts)")
_DROPPED = _reg.counter(
    "edl_journal_dropped_lines_total",
    "unparseable journal lines skipped during replay (torn tail)")
_RECOVERIES = _reg.counter(
    "edl_master_recoveries_total", "master boots that replayed a journal")
_GENERATION = _reg.gauge(
    "edl_master_generation", "current master generation")


@dataclass
class DispatcherState:
    """Replayed dispatcher state (what TaskDispatcher restores from)."""

    todo: List[Dict[str, Any]] = field(default_factory=list)
    next_task_id: int = 1
    epoch: int = -1
    num_epochs: Optional[int] = None
    finished_training: int = 0
    failed_permanently: int = 0
    completed_versions: int = 0
    epoch_end_fired: bool = False
    job_end_fired: bool = False
    stop_training: bool = False
    training_done: bool = False
    save_model_created: bool = False
    requeued_leases: int = 0


@dataclass
class MembershipState:
    """Replayed membership registry (liveness clocks restart at takeover)."""

    workers: List[Dict[str, Any]] = field(default_factory=list)
    next_id: int = 0
    version: int = 0


@dataclass
class ReplayResult:
    prior_generation: int = 0
    records: int = 0
    dropped_lines: int = 0
    dispatcher: Optional[DispatcherState] = None
    membership: Optional[MembershipState] = None
    world_version: int = 0


def _replay_dispatcher(
    state: DispatcherState, doing: Dict[int, Dict[str, Any]],
    rtype: str, rec: Dict[str, Any],
) -> None:
    """Apply one dispatcher transition record to the replay state."""

    def take_todo(task_id: int) -> Optional[Dict[str, Any]]:
        for i, t in enumerate(state.todo):
            if t["task_id"] == task_id:
                return state.todo.pop(i)
        return None

    if rtype == "task_create":
        task = dict(rec["task"])
        if rec.get("front"):
            state.todo.insert(0, task)
        else:
            state.todo.append(task)
        state.next_task_id = max(state.next_task_id, task["task_id"] + 1)
        if task.get("type") == _SAVE_MODEL_TYPE:
            state.save_model_created = True
    elif rtype == "task_lease":
        task = take_todo(rec["task_id"])
        if task is not None:
            doing[rec["task_id"]] = task
    elif rtype == "task_finish":
        doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        if rec.get("training"):
            state.finished_training += 1
            state.completed_versions += 1
    elif rtype == "task_requeue":
        task = doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        if task is not None:
            task["start"] = rec.get("start", task["start"])
            task["retries"] = rec.get("retries", task.get("retries", 0))
            state.todo.insert(0, task)
    elif rtype == "task_drop":
        doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
    elif rtype == "task_fail":
        doing.pop(rec["task_id"], None) or take_todo(rec["task_id"])
        state.failed_permanently += 1
    elif rtype == "epoch_advance":
        state.epoch = rec["epoch"]
        state.epoch_end_fired = False
    elif rtype == "epoch_end":
        if rec.get("epoch", state.epoch) == state.epoch:
            state.epoch_end_fired = True
    elif rtype == "training_done":
        state.training_done = True
    elif rtype == "job_end":
        state.job_end_fired = True
    elif rtype == "stop_training":
        state.stop_training = True
        state.num_epochs = rec.get("num_epochs", state.num_epochs)
        state.todo = [t for t in state.todo if t.get("type") != _TRAINING_TYPE]


# pb.TRAINING / pb.EVALUATION / pb.SAVE_MODEL without importing protobuf
# here (the journal must stay importable in protobuf-free tooling
# contexts); a test pins these to the generated enum values.
_TRAINING_TYPE = 0
_EVALUATION_TYPE = 1
_SAVE_MODEL_TYPE = 3

_DISPATCHER_RECORDS = frozenset({
    "task_create", "task_lease", "task_finish", "task_requeue", "task_drop",
    "task_fail", "epoch_advance", "epoch_end", "training_done", "job_end",
    "stop_training",
})


def replay_lines(lines: List[str]) -> ReplayResult:
    """Replay journal lines to a final state (tolerant of a torn tail)."""
    result = ReplayResult()
    dispatcher: Optional[DispatcherState] = None
    membership: Optional[MembershipState] = None
    doing: Dict[int, Dict[str, Any]] = {}
    lease_order: List[int] = []

    def apply(rec: Dict[str, Any]) -> None:
        nonlocal dispatcher, membership
        rtype = rec["t"]
        result.records += 1
        if rtype == "header":
            result.prior_generation = int(rec.get("generation", 0))
        elif rtype == "snapshot":
            if rec.get("dispatcher") is not None:
                dispatcher = DispatcherState(**rec["dispatcher"])
            if rec.get("membership") is not None:
                membership = MembershipState(**rec["membership"])
            result.world_version = int(rec.get("world_version", 0))
        elif rtype in _DISPATCHER_RECORDS:
            if dispatcher is None:
                dispatcher = DispatcherState()
            if rtype == "task_lease":
                lease_order.append(rec.get("task_id"))
            _replay_dispatcher(dispatcher, doing, rtype, rec)
        elif rtype == "member_join":
            if membership is None:
                membership = MembershipState()
            wid = int(rec["worker_id"])
            for w in membership.workers:
                if w["worker_id"] == wid:
                    membership.workers.remove(w)
                    break
            membership.workers.append(
                {"worker_id": wid, "name": rec.get("name", ""), "alive": True}
            )
            membership.next_id = max(membership.next_id, wid + 1)
            membership.version = max(membership.version, int(rec.get("version", 0)))
        elif rtype == "member_death":
            if membership is None:
                membership = MembershipState()
            for w in membership.workers:
                if w["worker_id"] == int(rec["worker_id"]):
                    w["alive"] = False
            membership.version = max(membership.version, int(rec.get("version", 0)))
        elif rtype == "world_version":
            result.world_version = max(result.world_version, int(rec["version"]))
        else:
            logger.warning("unknown journal record type %r ignored", rtype)

    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
            if rec.get("t") == "batch":
                # a multi-record commit rides ONE line (append_many): it is
                # applied whole here or dropped whole below — validate
                # before applying so a corrupt batch can't half-apply
                subrecs = rec["records"]
                if not isinstance(subrecs, list) or not all(
                    isinstance(s, dict) and "t" in s for s in subrecs
                ):
                    raise ValueError("malformed batch record")
            else:
                rec["t"]                   # KeyError -> dropped below
                subrecs = [rec]
        except (ValueError, KeyError, TypeError):
            # torn tail (crash mid-append) is expected; a garbled line in
            # the middle is not, but dropping it beats refusing to recover
            result.dropped_lines += 1
            _DROPPED.inc()
            if i < len(lines) - 1:
                logger.warning(
                    "journal line %d unparseable (not the tail); skipped", i + 1
                )
            continue
        for sub in subrecs:
            apply(sub)
    if dispatcher is not None:
        # conservative lease recovery: the crashed master cannot know
        # whether leased work finished — requeue every in-flight lease at
        # the FRONT (oldest first), exactly once; pre-crash reports are
        # generation-fenced so nothing is double-counted. dict.fromkeys
        # dedupes a task that was leased, requeued, and re-leased before
        # the crash (lease_order carries it twice but it must come back
        # exactly once, or its records train twice after recovery).
        requeued = [doing[t] for t in dict.fromkeys(lease_order) if t in doing]
        if dispatcher.stop_training:
            # the live dispatcher drops in-flight TRAINING work after an
            # early stop (its requeue path journals task_drop); replay must
            # not resurrect a training lease the stop already condemned
            requeued = [t for t in requeued if t.get("type") != _TRAINING_TYPE]
        # EVALUATION tasks do NOT survive a crash: EvaluationService state
        # (job ids, metric aggregation) is volatile by contract, so a
        # replayed eval task would report into a dead eval job id — or
        # worse, into a post-recovery job that REUSED the id, corrupting
        # its metrics. The successor re-triggers evaluation fresh instead
        # (the dispatcher restore re-fires the epoch-end callbacks).
        requeued = [t for t in requeued if t.get("type") != _EVALUATION_TYPE]
        dispatcher.todo = [
            t for t in dispatcher.todo if t.get("type") != _EVALUATION_TYPE
        ]
        dispatcher.todo = requeued + dispatcher.todo
        dispatcher.requeued_leases = len(requeued)
    result.dispatcher = dispatcher
    result.membership = membership
    return result


class ControlPlaneJournal:
    """Append-only WAL with atomic rotation and a persisted generation.

    Thread-safe; appends are called from inside the dispatcher's and
    membership's ``_lock`` critical sections (lock order: owner lock ->
    journal ``_lock``; the journal never calls back out, so no cycle).
    """

    def __init__(self, checkpoint_dir: str, fsync: bool = True):
        self.dir = os.path.join(checkpoint_dir, JOURNAL_DIRNAME)
        self.path = os.path.join(self.dir, JOURNAL_FILENAME)
        self._fsync = fsync
        self._lock = threading.Lock()
        self._fh = None                      # guarded_by: _lock
        self.generation = 1
        self.recovered = False
        self.replay: Optional[ReplayResult] = None
        self._open()

    # -------------------------------------------------------------- #
    # open / rotate / replay

    def _open(self) -> None:  # holds: _lock (construction)
        os.makedirs(self.dir, exist_ok=True)
        if os.path.exists(self.path):
            with open(self.path, encoding="utf-8") as f:
                lines = f.readlines()
            self.replay = replay_lines(lines)
            _REPLAYED.inc(self.replay.records)
            self.generation = self.replay.prior_generation + 1
            self.recovered = True
            _RECOVERIES.inc()
            logger.warning(
                "control journal replayed: %d records (%d dropped), prior "
                "generation %d -> %d, %d in-flight lease(s) requeued",
                self.replay.records, self.replay.dropped_lines,
                self.replay.prior_generation, self.generation,
                (self.replay.dispatcher.requeued_leases
                 if self.replay.dispatcher else 0),
            )
        self._rotate_locked()
        self._fh = open(self.path, "a", encoding="utf-8")
        _GENERATION.set(self.generation)

    def _fsync_dir(self) -> None:
        """Make the directory entry durable: file-level fsync alone does
        not persist a newly created or os.replace'd NAME on POSIX — a host
        crash could drop the whole journal despite every append having
        been fsynced, and the successor would rebuild from scratch."""
        try:
            fd = os.open(self.dir, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def _rotate_locked(self) -> None:
        """Atomically (re)write the journal as header + compacted snapshot.
        Runs before the append handle opens (single-threaded boot)."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"t": "header", "v": JOURNAL_VERSION,
                 "generation": self.generation}
            ) + "\n")
            if self.replay is not None and (
                self.replay.dispatcher is not None
                or self.replay.membership is not None
                or self.replay.world_version
            ):
                f.write(json.dumps({
                    "t": "snapshot",
                    "dispatcher": (
                        asdict(self.replay.dispatcher)
                        if self.replay.dispatcher is not None else None
                    ),
                    "membership": (
                        asdict(self.replay.membership)
                        if self.replay.membership is not None else None
                    ),
                    "world_version": self.replay.world_version,
                }) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._fsync_dir()
        _ROTATIONS.inc()

    # -------------------------------------------------------------- #
    # replayed-state accessors (None = nothing to restore)

    def dispatcher_snapshot(self) -> Optional[DispatcherState]:
        if self.replay is None:
            return None
        return self.replay.dispatcher

    def membership_snapshot(self) -> Optional[MembershipState]:
        if self.replay is None:
            return None
        return self.replay.membership

    @property
    def world_version(self) -> int:
        return self.replay.world_version if self.replay is not None else 0

    # -------------------------------------------------------------- #
    # append path

    def append(self, rtype: str, **fields: Any) -> None:
        """Commit one transition record: write + flush + fsync."""
        self.append_many([(rtype, fields)])

    def append_many(self, records: List[Tuple[str, Dict[str, Any]]]) -> None:
        """Commit a batch of records under ONE fsync (bulk task creation).

        A multi-record batch is serialized as ONE ``batch`` line: a large
        batch can span several write(2) syscalls, and a crash between them
        must not persist a parseable prefix (an ``epoch_advance`` with only
        some of its ``task_create`` lines would replay a partial epoch).
        One line is either whole at replay or a torn tail dropped whole —
        the batch commits all-or-nothing."""
        if not records:
            return
        if len(records) == 1:
            rtype, fields = records[0]
            data = json.dumps({"t": rtype, **fields}) + "\n"
        else:
            data = json.dumps({
                "t": "batch",
                "records": [
                    {"t": rtype, **fields} for rtype, fields in records
                ],
            }) + "\n"
        with self._lock:
            if self._fh is None:
                # post-close append (a component outliving its master after
                # crash_stop): dropping is correct — a NEW master owns the
                # file now, and interleaving two writers would corrupt it
                logger.warning(
                    "journal append after close dropped (%d record(s))",
                    len(records),
                )
                return
            self._fh.write(data)
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
        _APPENDS.inc(len(records))

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    if self._fsync:
                        os.fsync(self._fh.fileno())
                finally:
                    self._fh.close()
                    self._fh = None

    def discard(self) -> None:
        """Clean-completion teardown: close the journal and retire it to
        ``journal.jsonl.completed``. Only for a job that actually finished —
        a live journal whose replay says training_done/job_end would make a
        later re-submission with the same checkpoint_dir come up
        born-finished and silently no-op. The rename (not a delete) keeps
        the final generation + accounting on disk for forensics. Crash and
        abort paths never call this; they keep the journal live so the
        successor recovers from it."""
        self.close()
        try:
            os.replace(self.path, self.path + ".completed")
            self._fsync_dir()
        except OSError:
            # the journal survived with job_end on it: the next submission
            # reusing this checkpoint_dir will replay it and no-op — that
            # MUST be diagnosable from the logs
            logger.exception(
                "journal retirement failed; a re-submission against %s will "
                "replay a finished job", self.path,
            )
