"""Closed-loop embedding LAYOUT controller: skew signals drive the
tier's data layout instead of an operator.

The autoscaler (master/autoscaler.py, ISSUE 14) closed the observe→
decide loop for WORLD SIZE; this module closes it for the embedding
tier's layout — the second loop ROADMAP 4 calls for. A popularity flip
(the hourly reality of online-ads embedding traffic, 2501.10546) leaves
hot shards saturated and cold replicas wasting RAM even though every
sensor needed to react already streams through the fleet series: the
Space-Saving sketch's `hot_id_share` (PR 11), per-shard load shares,
`edl_fleet_emb_shard_imbalance`, and PR 13's cache-hit-collapse alert.
ElasWave (2510.00606) argues the reaction must be native to the
training system, not bolted on — same posture as the autoscaler, same
skeleton on purpose:

- **Signals** (subscription, never polling the sensors' internals):
  `AlertEngine.add_hook` delivers `embedding_shard_imbalance` (the
  split / replica fan-out signal), `embedding_cache_hit_collapse` and
  `embedding_pull_p99` (the hot-set-moved signals) ONSETS. Per-shard
  load shares and each worker's sketch head ride the heartbeat stats
  payload as compact strings (`emb_shard_loads` / `emb_hot_ids`,
  embedding/tier.tier_stats — decode_stats keeps strings, truncated at
  64 chars, so the exporters pre-budget). Hooks only RECORD — decisions
  happen in `evaluate()`, on the master's wait-poll cadence.

- **Actions**, through a pluggable target (`bind_target`), all via the
  ShardMapOwner's journaled mutation surface:
  * `replica_fanout` — per-shard replica counts re-derived from load
    shares: hot shards gain read replicas, cold shards drop to
    primary-only (single-phase `emb_replica_map` record — replicas are
    pull-only, so no exactly-once fence is needed);
  * `split` / `merge` — shard count doubles (or halves) through the
    existing two-phase `emb_reshard_begin→commit` fence; the stores
    re-key rows, seq watermarks, and delta logs locally
    (store.split_resident / merge_resident — the hard correctness
    case, pinned by tests/test_embedding_layout.py);
  * `hot_promote` / `hot_demote` — the aggregated sketch head becomes
    the worker-replicated ultra-hot set (`emb_hot_ids` record; clients
    pin the rows, the delta-sync lane keeps them fresh), demoted when
    the decayed sketch stops voting for it.

- **Robust by construction**, exactly like the autoscaler:
  * a COST MODEL in BLOCKED-READ-SECONDS gates every action: never
    touch the layout unless the projected read-stall relief over
    `horizon_s` exceeds the migration's projected stall (seeded from
    ``bench.py embedding_tier``'s measured reshard `recovery_s` via
    `--layout_migrate_cost_s`, EWMA-updated from real migrations);
  * PER-KIND cooldowns plus signal HOLD (hysteresis): a replica
    fan-out five minutes ago must not cool down a pending split, but
    the same kind never fires twice inside its own window;
  * shard-count bounds and a per-job ACTION BUDGET cap blast radius —
    at most ONE action per evaluate() pass;
  * every decision — including every SUPPRESSED one, with its reason —
    is a journaled ``layout`` record replayed at master takeover
    (journal.LayoutState), so a restarted master inherits cooldowns
    and never double-fires; applied decisions are durable BEFORE the
    action runs;
  * NO DATA means HOLD: a fleet whose workers stopped reporting shard
    loads gets no layout changes — absence of telemetry is never read
    as balance.

- **Observability**: `edl_layout_*` metrics, `layout.<kind>` trace
  spans, edge-triggered `layout.suppressed` events, a flight-ring
  record per action, and an incident-CLI section summarizing the
  decision history out of the journal.

Direct `ShardMapOwner` layout mutations outside this module and the
existing reshard entry points are flagged by edl-lint **EDL503**
(`layout-mutation-outside-policy`) — the mirror of EDL501: ad-hoc
layout paths must not bypass the cost gate, the cooldowns, or the
journaled decision history.

Stdlib-only and jax-free like the rest of the master's control plane.
See docs/elasticity.md ("Layout autoscaling").
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.journal import LayoutState
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

#: action kinds (bounded vocabulary; journal + metric label values)
KINDS = ("replica_fanout", "split", "merge", "hot_promote", "hot_demote")

#: suppression reasons (bounded vocabulary; journal + metric label
#: values — every suppressed decision carries exactly one of these)
SUPPRESS_REASONS = (
    "no_target", "unsupported", "resharding_in_flight", "no_data",
    "cooldown", "budget_exhausted", "at_max_shards", "at_min_shards",
    "not_co_owned", "cost_gate", "no_change", "action_failed",
)

#: the alert rules this engine subscribes to (observability/alerts.py
#: default rule set; a custom --alert_rules file keeps the loop alive
#: by keeping these names)
IMBALANCE_RULE = "embedding_shard_imbalance"
CACHE_RULE = "embedding_cache_hit_collapse"
PULL_RULE = "embedding_pull_p99"

#: heartbeat stats keys the controller aggregates (compact comma-joined
#: strings — embedding/tier.tier_stats budgets them under decode_stats'
#: 64-char string truncation so a cut never lands mid-number)
SHARD_LOADS_KEY = "emb_shard_loads"
HOT_IDS_KEY = "emb_hot_ids"

_reg = default_registry()
_LC_ACTIONS = _reg.counter(
    "edl_layout_actions_total",
    "closed-loop layout actions applied", labels=("kind",))
_LC_SUPPRESSED = _reg.counter(
    "edl_layout_suppressed_total",
    "layout decisions suppressed (edge-triggered per (kind, reason))",
    labels=("reason",))
_LC_BUDGET = _reg.gauge(
    "edl_layout_budget_remaining",
    "layout actions left in this job's budget")
_LC_COOLDOWN = _reg.gauge(
    "edl_layout_cooldown_active",
    "1 while any per-kind layout cooldown window is open")
_LC_PENDING = _reg.gauge(
    "edl_layout_pending_signals",
    "layout signals recorded by the hooks, not yet decided")
_LC_SHARDS = _reg.gauge(
    "edl_layout_num_shards", "current embedding shard count")
_LC_REPLICAS = _reg.gauge(
    "edl_layout_replica_total", "total read replicas across all shards")
_LC_HOT = _reg.gauge(
    "edl_layout_hot_ids", "size of the worker-replicated ultra-hot set")


class LayoutCostModel:
    """Projected-cost gate for layout decisions.

    The unit is BLOCKED-READ-SECONDS: a layout migration stalls the
    tier's read path roughly `migrate_cost_s` per shard it touches
    (fence + re-key + client refresh — exactly what ``bench.py
    embedding_tier`` measures as the reshard leg's `recovery_s`, which
    seeds the estimate via `--layout_migrate_cost_s`); an action's
    projected gain is the read stall it relieves per second, accrued
    over `horizon_s`. The estimate is updated online from observed
    migration durations with an EWMA, so a tier whose re-keys are warm
    gates cheaper than one paying cold installs. Thread-safe (the
    action path observes, the wait loop reads)."""

    def __init__(self, migrate_cost_s: float = 0.16,
                 horizon_s: float = 120.0, ewma: float = 0.5):
        self._lock = threading.Lock()
        self._cost_s = max(0.001, float(migrate_cost_s))  # guarded_by: _lock
        self._observed = 0                                # guarded_by: _lock
        self.horizon_s = max(1.0, float(horizon_s))
        self._ewma = min(1.0, max(0.0, float(ewma)))

    @property
    def migrate_cost_s(self) -> float:
        with self._lock:
            return self._cost_s

    @property
    def observed_migrations(self) -> int:
        with self._lock:
            return self._observed

    def observe_migration(self, seconds: float) -> None:
        """Feed one measured layout-migration duration (never raises)."""
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            return
        if seconds <= 0:
            return
        with self._lock:
            self._observed += 1
            self._cost_s = (
                (1.0 - self._ewma) * self._cost_s + self._ewma * seconds
            )

    # ------------------------------------------------------------------ #
    # per-kind projections (blocked-read-seconds over the horizon)

    def project(self, kind: str, ctx: Dict) -> Dict[str, float]:
        """{'gain_s', 'cost_s'} for one candidate action. First-order on
        purpose — the gate's job is to refuse migrations whose stall
        bill exceeds what they can plausibly relieve, not to be a
        placement optimizer:

        - replica_fanout: each ADDED replica is one shard copy's worth
          of stall; the relief is the excess load the hot shards shed —
          gain = (imbalance - 1) * horizon, cost = cost * added;
        - split: every resident shard re-keys under the fence, but the
          hottest shard's load halves — gain = (imbalance - 1) *
          horizon, cost = cost * num_shards;
        - merge: a maintenance action — bounded fixed gain (fewer
          shards to fence, sync, and checkpoint), cost = cost * new_n;
        - hot_promote: one delta-lane push; the relief is the traffic
          share the pinned head stops sending to owners — gain =
          hot_share * horizon;
        - hot_demote: near-free (clients just unpin) with a small fixed
          gain (stale pins stop masking the live distribution).
        """
        cost_unit = self.migrate_cost_s
        h = self.horizon_s
        imb = max(0.0, float(ctx.get("imbalance") or 0.0))
        if kind == "replica_fanout":
            added = max(0, int(ctx.get("replicas_added") or 0))
            return {
                "gain_s": round(max(0.0, imb - 1.0) * h, 3),
                # dropping replicas is free; only installs stall reads
                "cost_s": round(cost_unit * max(1, added), 3),
            }
        if kind == "split":
            n = max(1, int(ctx.get("num_shards") or 1))
            return {
                "gain_s": round(max(0.0, imb - 1.0) * h, 3),
                "cost_s": round(cost_unit * n, 3),
            }
        if kind == "merge":
            n = max(1, int(ctx.get("num_shards") or 1))
            return {
                "gain_s": round(0.05 * h, 3),
                "cost_s": round(cost_unit * (n // 2), 3),
            }
        if kind == "hot_promote":
            share = min(1.0, max(0.0, float(ctx.get("hot_share") or 0.0)))
            return {
                "gain_s": round(share * h, 3),
                "cost_s": round(cost_unit, 3),
            }
        if kind == "hot_demote":
            return {
                "gain_s": round(0.02 * h, 3),
                "cost_s": round(cost_unit * 0.1, 3),
            }
        return {"gain_s": 0.0, "cost_s": float("inf")}


def parse_loads(raw: object, num_shards: int) -> Optional[List[float]]:
    """Parse one worker's `emb_shard_loads` payload string ("0.42,0.01,
    ...", per-shard load shares) — None for anything malformed or
    mismatched (a mixed-version worker degrades to no-data, never to a
    crash in the master's poll loop)."""
    if not isinstance(raw, str) or not raw:
        return None
    out: List[float] = []
    for tok in raw.split(","):
        try:
            out.append(max(0.0, float(tok)))
        except ValueError:
            return None
    if len(out) != num_shards:
        return None
    return out


def parse_hot_ids(raw: object) -> List[int]:
    """Parse one worker's `emb_hot_ids` payload string ("17,3,942", the
    sketch head, hottest first). Tolerant of a truncated tail token —
    the exporter pre-budgets under 64 chars, but a foreign build may
    not."""
    if not isinstance(raw, str) or not raw:
        return []
    out: List[int] = []
    for tok in raw.split(","):
        try:
            out.append(int(tok))
        except ValueError:
            break
    return out


class StoreLayoutTarget:
    """Action adapter over in-process stores (bench, tests, local runs):
    the owner map mutates first (journaled), then every store reconciles
    synchronously — install/drop replicas, re-key splits/merges and
    confirm them so the two-phase plan commits inside the call.

    `stores` maps worker id -> EmbeddingShardStore; `pool_fn` returns
    the live worker ids replicas may land on (defaults to the store
    keys)."""

    def __init__(self, owner, stores: Dict[int, object],
                 pool_fn: Optional[Callable[[], List[int]]] = None):
        self._owner = owner
        self._stores = stores
        self._pool_fn = pool_fn or (lambda: sorted(stores))

    def view(self):
        return self._owner.view()

    def pool(self) -> List[int]:
        return list(self._pool_fn())

    def supports(self, kind: str) -> bool:
        return kind in KINDS

    # -- actions ---------------------------------------------------- #

    def apply_replicas(self, counts: Sequence[int]) -> bool:
        view = self._owner.update_replicas(counts, self.pool())
        for wid, store in self._stores.items():
            assigned = {
                (t.name, s)
                for s in view.shards_replicated_on(wid)
                for t in view.tables
            }
            for key in list(store.resident_replicas()):
                if key not in assigned:
                    store.release_replica(*key)
            for table, s in sorted(assigned):
                if (table, s) in store.resident_replicas():
                    continue
                primary = self._stores.get(view.owner_of(s))
                if primary is None:
                    continue
                store.install_replica(
                    table, s, primary.extract_shard(table, s))
            store.set_delta_logging(any(
                view.replicas_of(s) for s in range(view.num_shards)))
            store.adopt_version(view.version)
        return True

    def apply_split(self) -> bool:
        view, moves = self._owner.begin_split()
        for wid, store in self._stores.items():
            if store.resident_shards():
                created = store.split_resident(view)
                self._owner.confirm_moves(view.version, created)
            else:
                store.adopt_version(view.version)
        return not self._owner.view().resharding

    def apply_merge(self) -> bool:
        view, moves = self._owner.begin_merge()
        for wid, store in self._stores.items():
            if store.resident_shards():
                created = store.merge_resident(view)
                self._owner.confirm_moves(view.version, created)
            else:
                store.adopt_version(view.version)
        return not self._owner.view().resharding

    def apply_hot_ids(self, ids: Sequence[int]) -> bool:
        view = self._owner.set_hot_ids(ids)
        for store in self._stores.values():
            store.adopt_version(view.version)
        return True


class OwnerLayoutTarget:
    """Action adapter for the distributed (gRPC) master: mutates the
    journaled owner map only; workers adopt the new layout at their
    next map refresh (`WorkerTierRuntime.on_world_change` / a stale-map
    retry). Splits and merges are UNSUPPORTED on this path — remote
    stores re-key at task boundaries, which the two-phase fence cannot
    bound yet — so the policy suppresses them as `unsupported` instead
    of journaling an applied decision that cannot complete (same
    contract as the autoscaler's grow-on-plain-training rule)."""

    def __init__(self, owner, membership=None):
        self._owner = owner
        self._membership = membership

    def view(self):
        return self._owner.view()

    def pool(self) -> List[int]:
        if self._membership is None:
            return []
        return [
            w.worker_id for w in self._membership.alive_workers()
            if w.led_by is None
        ]

    def supports(self, kind: str) -> bool:
        return kind in ("replica_fanout", "hot_promote", "hot_demote")

    def apply_replicas(self, counts: Sequence[int]) -> bool:
        pool = self.pool()
        if not pool:
            return False
        self._owner.update_replicas(counts, pool)
        return True

    def apply_split(self) -> bool:
        return False

    def apply_merge(self) -> bool:
        return False

    def apply_hot_ids(self, ids: Sequence[int]) -> bool:
        self._owner.set_hot_ids(ids)
        return True


class LayoutController:
    """The policy engine. One instance per master; `evaluate()` runs on
    the wait-poll cadence and never raises."""

    #: a shard is "hot" past this multiple of the mean load share —
    #: each further multiple earns one more read replica
    FANOUT_HOT_FACTOR = 2.0

    #: a split needs the imbalance alert's condition to persist AND the
    #: measured imbalance to clear this floor (replica fan-out is the
    #: cheaper first response; splitting re-keys everything)
    SPLIT_IMBALANCE = 3.0

    #: merge candidate when measured imbalance stays under this and the
    #: shard count sits above its bootstrap value
    MERGE_IMBALANCE = 1.25

    #: an id must be voted hot by this fraction of reporting workers to
    #: promote (a single worker's local skew is not fleet skew)
    PROMOTE_QUORUM = 0.5

    def __init__(
        self,
        *,
        journal=None,
        cost_model: Optional[LayoutCostModel] = None,
        max_shards: int = 0,         # 0 = never split past bootstrap
        min_shards: int = 1,
        max_replicas: int = 2,
        hot_k: int = 16,
        cooldown_s: float = 60.0,
        hold_s: float = 15.0,
        action_budget: int = 16,
        clock: Callable[[], float] = time.time,
    ):
        self._journal = journal
        self.cost = cost_model or LayoutCostModel()
        self.max_shards = max(0, int(max_shards))
        self.min_shards = max(1, int(min_shards))
        self.max_replicas = max(0, int(max_replicas))
        self.hot_k = max(0, int(hot_k))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.hold_s = max(0.0, float(hold_s))
        self.action_budget = max(0, int(action_budget))
        # wall clock ON PURPOSE (not monotonic): last_ts_by_kind is
        # journaled and must survive a master restart — a monotonic
        # stamp from a dead process is meaningless to its successor
        self._clock = clock
        self._lock = threading.Lock()
        # alert onsets recorded by the hook; decided by evaluate()
        self._signals: Dict[str, Dict] = {}           # guarded_by: _lock
        # decay candidates (merge / hot_demote) have no alert onset —
        # evaluate() tracks their own first_seen for the hold window
        self._decay_seen: Dict[str, float] = {}       # guarded_by: _lock
        # latest aggregated worker telemetry (evaluate() input)
        self._loads: Optional[List[float]] = None     # guarded_by: _lock
        self._hot_votes: Dict[int, int] = {}          # guarded_by: _lock
        self._reporters = 0                           # guarded_by: _lock
        # replayed (or fresh) durable state: per-kind cooldowns + the
        # spent budget survive master takeover via `layout` records
        snap = journal.layout_snapshot() if journal is not None else None
        self._state = snap if snap is not None else LayoutState()
        if snap is not None and (snap.actions_applied or snap.records):
            logger.warning(
                "layout controller state restored from control journal: "
                "%d action(s) applied (budget %d), last action ts %.0f — "
                "per-kind cooldowns inherited",
                snap.actions_applied, self.action_budget,
                snap.last_action_ts,
            )
        # edge-trigger state for suppressed-decision journaling: one
        # record per (kind, reason) TRANSITION, not one per poll
        self._last_suppressed: Dict[str, str] = {}    # guarded_by: _lock
        self._last_decision: Optional[Dict] = None    # guarded_by: _lock
        self._target = None
        self._alerts = None
        # the shard count the tier bootstrapped with: merge never folds
        # below it (learned from the first view we see)
        self._baseline_shards = 0
        _LC_BUDGET.set(max(0, self.action_budget - self._state.actions_applied))

    # ------------------------------------------------------------------ #
    # wiring

    def subscribe(self, alerts=None) -> "LayoutController":
        """Attach to the alert seam. Hooks only record — a decision
        needs the aggregated fleet picture evaluate() assembles."""
        if alerts is not None:
            self._alerts = alerts
            alerts.add_hook(self._on_alert)
        return self

    def bind_target(self, target) -> None:
        """Attach the action surface (StoreLayoutTarget /
        OwnerLayoutTarget / a test double). Until one is bound every
        decision suppresses with `no_target` — journaled, so a
        mis-wired deployment is visible in the record stream."""
        self._target = target

    # ------------------------------------------------------------------ #
    # signal intake (hook thread; record only, never act)

    def _on_alert(self, info: Dict) -> None:
        rule = str(info.get("rule", ""))
        if rule not in (IMBALANCE_RULE, CACHE_RULE, PULL_RULE):
            return
        with self._lock:
            sig = dict(info)
            sig["first_seen"] = self._clock()
            self._signals[rule] = sig
        logger.info("layout controller: %s signal recorded "
                    "(hold %.0fs before action)", rule, self.hold_s)

    def observe_workers(self, records: Sequence[Dict],
                        num_shards: int) -> None:
        """Aggregate the fleet's per-shard load shares and sketch heads
        out of the heartbeat stats records Membership already holds
        (master/main.py passes `membership.health_snapshot()` on the
        poll cadence; fleetsim and the bench feed scripted records).
        Never raises — a malformed payload is a non-reporter."""
        loads_acc: Optional[List[float]] = None
        n_load = 0
        votes: Dict[int, int] = {}
        n_hot = 0
        for rec in records:
            loads = parse_loads(rec.get(SHARD_LOADS_KEY), num_shards)
            if loads is not None:
                if loads_acc is None:
                    loads_acc = [0.0] * num_shards
                for s, v in enumerate(loads):
                    loads_acc[s] += v
                n_load += 1
            hot = parse_hot_ids(rec.get(HOT_IDS_KEY))
            if hot:
                n_hot += 1
                for i in hot:
                    votes[i] = votes.get(i, 0) + 1
        with self._lock:
            self._loads = (
                [v / n_load for v in loads_acc]
                if loads_acc is not None and n_load else None
            )
            self._hot_votes = votes
            self._reporters = max(n_load, n_hot)

    # ------------------------------------------------------------------ #
    # the decision pass

    def evaluate(self, now: Optional[float] = None,
                 workers: Optional[Sequence[Dict]] = None) -> Optional[Dict]:
        """One decision pass; returns the applied decision (or None).
        Never raises — the master's wait loop calls this
        unconditionally. `workers` (heartbeat stats records) refreshes
        the load/hot-set aggregate before deciding."""
        try:
            return self._evaluate(now, workers)
        except Exception:
            logger.exception("layout evaluation failed; holding")
            return None

    def _evaluate(self, now: Optional[float],
                  workers: Optional[Sequence[Dict]]) -> Optional[Dict]:
        now = self._clock() if now is None else now
        target = self._target
        view = target.view() if target is not None else None
        if view is not None:
            if self._baseline_shards == 0 and view.num_shards:
                self._baseline_shards = view.num_shards
            _LC_SHARDS.set(view.num_shards)
            _LC_REPLICAS.set(sum(
                len(view.replicas_of(s)) for s in range(view.num_shards)))
            _LC_HOT.set(len(view.hot_ids))
        if workers is not None and view is not None:
            self.observe_workers(workers, view.num_shards)
        with self._lock:
            signals = dict(self._signals)
            loads = list(self._loads) if self._loads is not None else None
            votes = dict(self._hot_votes)
            reporters = self._reporters
        # re-validate against the live alert engine: a signal whose
        # condition cleared is dropped, never acted on stale — and a
        # condition that PERSISTS past an applied action re-arms (alert
        # hooks fire on onset only; an action consumes its signal, so
        # without this a still-imbalanced tier would never get a second
        # action). The re-armed signal gets a fresh first_seen: the
        # hold window runs again before the follow-up.
        if self._alerts is not None:
            active = {a.get("rule"): a for a in self._alerts.active()}
            for rule in list(signals):
                if rule not in active:
                    with self._lock:
                        self._signals.pop(rule, None)
                    signals.pop(rule, None)
                    # a NEW incident later must journal its own
                    # suppressions (edge-trigger resets with the signal)
                    self._clear_suppress_edges()
            for rule in (IMBALANCE_RULE, CACHE_RULE, PULL_RULE):
                info = active.get(rule)
                if info is not None and rule not in signals:
                    sig = dict(info)
                    sig["first_seen"] = now
                    with self._lock:
                        self._signals[rule] = sig
                    signals[rule] = sig
        _LC_PENDING.set(len(signals))
        _LC_COOLDOWN.set(
            1 if any(self._in_cooldown(k, now) for k in KINDS) else 0)
        if view is None or not view.owners:
            return None
        pool_fn = getattr(target, "pool", None)
        pool_size = len(pool_fn()) if pool_fn is not None else 0
        candidates = self._candidates(view, signals, loads, votes,
                                      reporters, now, pool_size)
        for kind, sig, ctx in candidates:
            if now - float(sig.get("first_seen") or now) < self.hold_s:
                continue   # hysteresis hold: not yet a decision
            decision = self._decide(kind, sig, ctx, view, now)
            if decision is not None:
                return decision
        return None

    # -- candidate derivation --------------------------------------- #

    def _candidates(self, view, signals, loads, votes, reporters, now,
                    pool_size=0):
        """Order matters: the cheapest adequate response first.
        replica_fanout (copy a few shards) > hot_promote (one push) >
        split (re-key everything) > the decay actions (hot_demote,
        merge) which only surface when no pressure signal is active."""
        out = []
        imb_sig = signals.get(IMBALANCE_RULE)
        hot_sig = signals.get(CACHE_RULE) or signals.get(PULL_RULE)
        imbalance = self._imbalance(loads, view.num_shards)
        if imb_sig is not None and loads is not None:
            counts = self._desired_replica_counts(loads, view, pool_size)
            current = [len(view.replicas_of(s))
                       for s in range(view.num_shards)]
            if counts != current:
                out.append(("replica_fanout", imb_sig, {
                    "imbalance": imbalance,
                    "counts": counts,
                    "replicas_added": sum(
                        max(0, c - k) for c, k in zip(counts, current)),
                }))
        if (hot_sig is not None or imb_sig is not None) and votes:
            desired = self._desired_hot_ids(votes, reporters)
            if desired and tuple(desired) != tuple(view.hot_ids):
                sig = hot_sig or imb_sig
                out.append(("hot_promote", sig, {
                    "hot_share": float(sig.get("value") or 0.0)
                    if sig is hot_sig and sig.get("rule") != CACHE_RULE
                    else 0.5,
                    "hot_ids": desired,
                }))
        if (imb_sig is not None and loads is not None
                and imbalance >= self.SPLIT_IMBALANCE):
            out.append(("split", imb_sig, {
                "imbalance": imbalance,
                "num_shards": view.num_shards,
            }))
        if not signals:
            # decay actions: only in calm weather, with their own hold
            # clocks (there is no alert onset to date them from)
            if view.hot_ids and votes is not None:
                desired = self._desired_hot_ids(votes, reporters)
                stale = [i for i in view.hot_ids if i not in desired]
                if stale:
                    sig = self._decay_signal("hot_demote", now)
                    out.append(("hot_demote", sig, {
                        "hot_ids": desired,
                        "demoted": len(stale),
                    }))
                else:
                    self._clear_decay("hot_demote")
            else:
                self._clear_decay("hot_demote")
            if (loads is not None and self._baseline_shards
                    and view.num_shards > self._baseline_shards
                    and imbalance > 0.0
                    and imbalance <= self.MERGE_IMBALANCE):
                sig = self._decay_signal("merge", now)
                out.append(("merge", sig, {
                    "imbalance": imbalance,
                    "num_shards": view.num_shards,
                }))
            else:
                self._clear_decay("merge")
        else:
            self._clear_decay("hot_demote")
            self._clear_decay("merge")
        return out

    def _decay_signal(self, kind: str, now: float) -> Dict:
        with self._lock:
            first = self._decay_seen.setdefault(kind, now)
        return {"rule": f"decay:{kind}", "first_seen": first}

    def _clear_decay(self, kind: str) -> None:
        with self._lock:
            self._decay_seen.pop(kind, None)
            self._last_suppressed.pop(kind, None)

    def _clear_suppress_edges(self) -> None:
        with self._lock:
            self._last_suppressed.clear()

    @staticmethod
    def _imbalance(loads: Optional[List[float]], num_shards: int) -> float:
        """max/mean of the aggregated per-shard load shares — the same
        definition as the tier's `emb_shard_imbalance` export, computed
        over the FLEET aggregate instead of one worker's view. 0.0 = no
        data (never reads as balanced)."""
        if not loads or num_shards < 1:
            return 0.0
        total = sum(loads)
        if total <= 0:
            return 0.0
        mean = total / num_shards
        return max(loads) / mean if mean > 0 else 0.0

    def _desired_replica_counts(self, loads: List[float], view,
                                pool_size: int = 0) -> List[int]:
        """One replica per mean-load multiple past FANOUT_HOT_FACTOR,
        capped at max_replicas AND at what the pool can host (a shard's
        owner cannot also be its replica) — cold shards drop to
        primary-only. Without the pool cap a 2-worker fleet wanting 2
        replicas would chase an unreachable assignment forever."""
        n = view.num_shards
        total = sum(loads) or 1.0
        mean = total / n
        cap = self.max_replicas
        if pool_size > 0:
            cap = min(cap, pool_size - 1)
        counts = []
        for s in range(n):
            share = loads[s] if s < len(loads) else 0.0
            if cap > 0 and mean > 0 and share >= self.FANOUT_HOT_FACTOR * mean:
                counts.append(max(0, min(cap, int(share / mean) - 1)))
            else:
                counts.append(0)
        return counts

    def _desired_hot_ids(self, votes: Dict[int, int],
                         reporters: int) -> List[int]:
        """Ids a quorum of reporting workers called hot, hottest first,
        top hot_k — fleet consensus, not one worker's local skew."""
        if not votes or reporters <= 0 or self.hot_k <= 0:
            return []
        need = max(1, int(self.PROMOTE_QUORUM * reporters))
        ranked = sorted(
            ((c, i) for i, c in votes.items() if c >= need),
            key=lambda t: (-t[0], t[1]),
        )
        return sorted(i for _, i in ranked[: self.hot_k])

    # -- gates -------------------------------------------------------- #

    def _in_cooldown(self, kind: str, now: float) -> bool:
        last = self._state.last_ts_by_kind.get(kind, 0.0)
        # wall-clock delta ON PURPOSE: last_ts_by_kind is journal-
        # replayed state from a possibly-dead process, the one clock
        # restarts share — edl-lint: disable=EDL406
        return bool(last > 0 and now - last < self.cooldown_s)

    def _decide(self, kind: str, signal: Dict, ctx: Dict, view,
                now: float) -> Optional[Dict]:
        """Run one candidate through the gates; apply or suppress.
        Returns the applied decision dict, or None when suppressed."""
        target = self._target
        if target is None:
            self._suppress(kind, signal, "no_target", now)
            return None
        supports = getattr(target, "supports", None)
        if supports is not None and not supports(kind):
            # structurally impossible on this deployment shape (e.g. a
            # split on the distributed owner-only target): suppress
            # BEFORE the budget/cooldown spend
            self._suppress(kind, signal, "unsupported", now)
            return None
        if view.resharding:
            # one two-phase plan at a time — overlapping plans would
            # break the exactly-once confirm accounting
            self._suppress(kind, signal, "resharding_in_flight", now)
            return None
        if kind == "split":
            if self.max_shards and view.num_shards * 2 > self.max_shards:
                self._suppress(kind, signal, "at_max_shards", now,
                               num_shards=view.num_shards)
                return None
            if not self.max_shards:
                self._suppress(kind, signal, "at_max_shards", now,
                               num_shards=view.num_shards)
                return None
        if kind == "merge":
            if (view.num_shards // 2 < self.min_shards
                    or view.num_shards // 2 < self._baseline_shards
                    or view.num_shards % 2 != 0):
                self._suppress(kind, signal, "at_min_shards", now,
                               num_shards=view.num_shards)
                return None
            half = view.num_shards // 2
            if any(view.owners[s] != view.owners[s + half]
                   for s in range(half)):
                # the local-interleave merge needs co-owned child pairs;
                # a reshard may later co-locate them — suppress, don't
                # pay a cross-host migration the cost model can't price
                self._suppress(kind, signal, "not_co_owned", now)
                return None
        if self._state.actions_applied >= self.action_budget:
            self._suppress(kind, signal, "budget_exhausted", now)
            return None
        if self._in_cooldown(kind, now):
            self._suppress(kind, signal, "cooldown", now)
            return None
        proj = self.cost.project(kind, ctx)
        if proj["gain_s"] <= proj["cost_s"]:
            self._suppress(kind, signal, "cost_gate", now, **proj)
            return None
        return self._apply(kind, signal, ctx, view, now, proj)

    # ------------------------------------------------------------------ #
    # outcomes

    def _signal_fields(self, kind: str, signal: Dict, ctx: Dict) -> Dict:
        out: Dict = {"kind": kind}
        rule = signal.get("rule", "")
        if str(rule).startswith("decay:"):
            out["reason"] = f"decay ({rule})"
        else:
            out["reason"] = (
                f"alert {rule} value {signal.get('value')} "
                f"{signal.get('op', '>')} threshold "
                f"{signal.get('threshold')}"
            )
        for k in ("imbalance", "replicas_added", "num_shards",
                  "hot_share", "demoted"):
            if k in ctx:
                out[k] = ctx[k]
        if "counts" in ctx:
            out["replica_counts"] = list(ctx["counts"])
        if "hot_ids" in ctx:
            out["hot_id_count"] = len(ctx["hot_ids"])
        return out

    def _journal_append(self, rec: Dict, await_commit: bool) -> None:
        if self._journal is None:
            return
        commit = self._journal.append("layout", **rec)
        if await_commit:
            # durable-before-action: the decision must survive a crash
            # landing mid-action, or the successor would re-fire it
            commit.wait()

    def _suppress(self, kind: str, signal: Dict, reason: str, now: float,
                  **extra) -> None:
        """Journal + count a suppressed decision — edge-triggered per
        (kind, reason): the record stream must say WHY the loop held,
        without one line per poll while it holds."""
        with self._lock:
            if self._last_suppressed.get(kind) == reason:
                return
            self._last_suppressed[kind] = reason
        info = self._signal_fields(kind, signal, extra)
        info.update(
            decision="suppressed", suppress_reason=reason,
            ts=round(now, 3),
        )
        # reason values come from the bounded SUPPRESS_REASONS
        # vocabulary at every call site: edl-lint: disable=EDL405
        _LC_SUPPRESSED.inc(reason=reason)
        with self._lock:
            self._state.records += 1
            self._last_decision = dict(info)
        try:
            self._journal_append(info, await_commit=False)
        except Exception:
            logger.exception("layout suppressed-decision journal failed")
        tracing.event("layout.suppressed", **{
            k: v for k, v in info.items()
            if k not in ("decision", "replica_counts")
        })
        logger.info(
            "layout %s suppressed (%s): %s",
            kind, reason, info.get("reason", ""),
        )

    def _apply(self, kind: str, signal: Dict, ctx: Dict, view, now: float,
               proj: Dict) -> Optional[Dict]:
        info = self._signal_fields(kind, signal, ctx)
        info.update(
            decision="applied", ts=round(now, 3),
            map_version=view.version, **proj,
        )
        with tracing.span(f"layout.{kind}", **{
            k: v for k, v in info.items()
            if k in ("imbalance", "num_shards", "replicas_added",
                     "hot_id_count", "gain_s", "cost_s", "map_version")
        }) as span:
            # journal FIRST, fsync-awaited: a crash between here and the
            # action replays the decision as taken (the per-kind
            # cooldown holds, no double-fire) — the same conservative
            # ordering as autoscale/world_version commits
            try:
                self._journal_append(info, await_commit=True)
            except Exception:
                logger.exception(
                    "layout decision could not be journaled; action "
                    "ABORTED (an unjournaled layout change would re-fire "
                    "after takeover)")
                span.set(outcome="journal_failed")
                return None
            with self._lock:
                self._state.actions_applied += 1
                self._state.last_action_ts = max(
                    self._state.last_action_ts, now)
                self._state.by_kind[kind] = (
                    self._state.by_kind.get(kind, 0) + 1)
                self._state.last_ts_by_kind[kind] = max(
                    self._state.last_ts_by_kind.get(kind, 0.0), now)
                self._state.records += 1
                self._last_decision = dict(info)
                self._last_suppressed.pop(kind, None)
                self._decay_seen.pop(kind, None)
                # the acted signal is consumed: a persisting condition
                # re-fires via the alert engine's next onset / the next
                # telemetry aggregation, and evaluate() re-validates
                rule = signal.get("rule")
                self._signals.pop(rule, None)
            ok = False
            t0 = time.perf_counter()
            try:
                if kind == "replica_fanout":
                    ok = bool(self._target.apply_replicas(ctx["counts"]))
                elif kind == "split":
                    ok = bool(self._target.apply_split())
                elif kind == "merge":
                    ok = bool(self._target.apply_merge())
                else:
                    ok = bool(self._target.apply_hot_ids(
                        ctx.get("hot_ids", [])))
            except Exception:
                logger.exception("layout %s action failed", kind)
            if ok and kind in ("replica_fanout", "split", "merge"):
                # feed the cost model the MEASURED migration duration —
                # the EWMA keeps the gate honest about this fleet's
                # actual re-key/install costs
                self.cost.observe_migration(time.perf_counter() - t0)
            span.set(outcome="ok" if ok else "action_failed")
        # kind values come from the bounded KINDS vocabulary:
        # edl-lint: disable=EDL405
        _LC_ACTIONS.inc(kind=kind)
        _LC_BUDGET.set(max(0, self.action_budget - self._state.actions_applied))
        _LC_COOLDOWN.set(1)
        if not ok:
            # the decision stands (the cooldown holds — hammering a
            # failing target would be its own flap mode); the failure
            # journals its own record for the postmortem, and the next
            # alert onset / telemetry pass re-derives the candidate
            self._suppress(kind, signal, "action_failed", now)
        try:
            from elasticdl_tpu.observability import flight as flight_lib

            flight_lib.get_recorder().record(
                "layout", kind, **{
                    k: v for k, v in info.items()
                    if k not in ("decision", "kind", "replica_counts")
                },
            )
        except Exception:
            logger.exception("layout flight record failed")
        logger.warning(
            "LAYOUT %s applied: %s (projected relief %.1fs > stall "
            "%.1fs; budget %d/%d)",
            kind, info.get("reason", ""), proj["gain_s"], proj["cost_s"],
            self._state.actions_applied, self.action_budget,
        )
        return info

    # ------------------------------------------------------------------ #
    # introspection

    def snapshot(self) -> Dict:
        """Cheap state view (/healthz enrichment + bench artifacts)."""
        now = self._clock()
        with self._lock:
            actions_applied = self._state.actions_applied
            by_kind = dict(self._state.by_kind)
            last_ts_by_kind = dict(self._state.last_ts_by_kind)
            records = self._state.records
            last = dict(self._last_decision) if self._last_decision else None
            pending = len(self._signals)
            loads = list(self._loads) if self._loads is not None else None
        return {
            "enabled": self._target is not None,
            "actions_applied": actions_applied,
            "action_budget": self.action_budget,
            "budget_remaining": max(
                0, self.action_budget - actions_applied),
            "by_kind": by_kind,
            "cooldown_s": self.cooldown_s,
            "cooldowns_active": {
                k: bool(t > 0 and now - t < self.cooldown_s)
                for k, t in last_ts_by_kind.items()
            },
            "hold_s": self.hold_s,
            "max_shards": self.max_shards,
            "max_replicas": self.max_replicas,
            "hot_k": self.hot_k,
            "migrate_cost_s": round(self.cost.migrate_cost_s, 4),
            "horizon_s": self.cost.horizon_s,
            "pending_signals": pending,
            "fleet_imbalance": round(self._imbalance(
                loads, len(loads) if loads else 0), 4) if loads else None,
            "last_decision": last,
            "decision_records": records,
        }


def from_config(cfg, journal=None) -> Optional[LayoutController]:
    """Build the engine from a JobConfig (None when --layout_autoscale
    is off — the default: layout stays human-operated). The caller
    subscribes and binds the target."""
    if not getattr(cfg, "layout_autoscale", False):
        return None
    return LayoutController(
        journal=journal,
        cost_model=LayoutCostModel(
            migrate_cost_s=cfg.layout_migrate_cost_s,
            horizon_s=cfg.layout_horizon_s,
        ),
        max_shards=cfg.layout_max_shards,
        max_replicas=cfg.layout_max_replicas,
        hot_k=cfg.layout_hot_k,
        cooldown_s=cfg.layout_cooldown_s,
        hold_s=cfg.layout_hold_s,
        action_budget=cfg.layout_actions_max,
    )
