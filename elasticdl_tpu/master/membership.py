"""Worker membership registry — the failure detector + rendezvous version.

Reference parity: two components merged. The reference's instance manager
watches k8s pod events to detect worker death
(elasticdl/python/master/k8s_instance_manager.py), and its rendezvous server
bumps a world version so Horovod re-forms
(elasticdl/python/master/rendezvous_server.py). Here both jobs are served by
one registry: liveness from heartbeats (works with or without k8s; the pod
watcher feeds in too), and a monotonically increasing `membership_version`
workers watch to know when to re-form the `jax.distributed` mesh.

Heartbeats optionally carry a compact stats payload (gRPC metadata,
observability/health.py): the registry keeps a ROLLING per-worker health
record — last step-time quantiles, records/s, prefetch depth, breaker
state, rescale phase — which `ClusterHealth` scores for stragglers. The
records deliberately survive re-register and even death/revival (they are
history about a worker id, not liveness state), so a reconnect after a
master hiccup does not blind the straggler detector for a full window.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.journal import CommitGate
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_MB_REGISTERED = _reg.counter(
    "edl_membership_registrations_total", "worker registrations")
_MB_DEATHS = _reg.counter(
    "edl_membership_deaths_total", "workers declared dead (any reason)")
_MB_REAPED = _reg.counter(
    "edl_membership_reaped_total",
    "workers declared dead by heartbeat-timeout reaping")
_MB_ALIVE = _reg.gauge(
    "edl_membership_alive_workers",
    "currently alive logical workers (cohort leaders + singletons)")
_MB_VERSION = _reg.gauge(
    "edl_membership_version", "current membership version")
_MB_MEMBERS = _reg.gauge(
    "edl_membership_cohort_members",
    "registered cohort member processes (telemetry entities; liveness "
    "rides their leader's beat)")
_MB_BEATS = _reg.counter(
    "edl_membership_heartbeats_total", "heartbeat RPCs applied")
_MB_COALESCED = _reg.counter(
    "edl_membership_coalesced_beats_total",
    "member beats carried inside a leader's single heartbeat")


@dataclass
class WorkerInfo:
    worker_id: int
    name: str
    last_heartbeat: float
    model_version: int = 0
    alive: bool = True
    # cohort membership: set = this entry is a member PROCESS of the
    # cohort led by that worker id. Members are telemetry entities — they
    # are skipped by reap scans (their liveness IS the leader's beat),
    # never bump the membership version, and die with their leader.
    led_by: Optional[int] = None
    # embedding data-plane endpoint this worker serves its owning shards
    # from (embedding/data_plane.py; "" = none). Journaled with the join
    # so a successor master replays the owner address book — the
    # shard-map response carries it to every tier client.
    data_addr: str = ""


class Membership(CommitGate):
    #: server-side ceiling on one cohort's member registrations — the
    #: membership twin of the servicer's MAX_LEASE_BATCH: a corrupted or
    #: hostile RegisterWorker must not allocate unbounded WorkerInfo
    #: entries, build an unbounded journal batch line, and hold the
    #: membership lock throughout, all from one RPC
    MAX_COHORT_MEMBERS = 4096

    def __init__(self, heartbeat_timeout_s: float = 30.0, journal=None,
                 clock: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        # Injectable time source: the fleet simulator (fleetsim/) drives
        # membership on a compressed virtual clock; production uses
        # time.time. Every liveness stamp and reap decision reads this.
        self._clock = clock
        # Crash durability (master/journal.py): join/death transitions are
        # committed inside the _lock critical sections that apply them, so
        # a restarted master replays the registry instead of telling every
        # reconnecting worker to shut down as an unknown. None = volatile.
        self._journal = journal
        self._workers: Dict[int, WorkerInfo] = {}    # guarded_by: _lock
        # Alive-entry indexes: the reap scan, the per-poll fleet rollup,
        # and the address book must not pay O(all entries ever seen) once
        # the registry holds thousands of dead/member rows. Invariant:
        # _alive_leaders == {id: alive, led_by is None}, _alive_members ==
        # {id: alive, led_by set}, _cohort_members[leader] == every member
        # id ever registered under that leader (alive or dead — the
        # idempotent re-register key space).        # guarded_by: _lock
        self._alive_leaders: set = set()
        self._alive_members: set = set()
        self._cohort_members: Dict[int, set] = {}
        # last journal Commit of the current critical section (see _j)
        self._pending_commit = None                  # guarded_by: _lock
        # rolling per-worker heartbeat telemetry (health.py records);
        # NEVER reset by reregister/mark_dead — see module docstring
        self._health: Dict[int, Dict] = {}           # guarded_by: _lock
        self._next_id = 0                            # guarded_by: _lock
        self._version = 0                            # guarded_by: _lock
        self._timeout = heartbeat_timeout_s
        # registration-before-start contract (wired while the master is
        # single-threaded); mark_dead iterates OUTSIDE the lock on purpose —
        # callbacks re-enter the dispatcher
        self._death_callbacks: List[Callable[[int], None]] = []
        snap = journal.membership_snapshot() if journal is not None else None
        if snap is not None:
            self._restore(snap)

    def _restore(self, snap) -> None:  # holds: _lock (construction)
        """Rebuild the registry from a replayed journal (master recovery).
        Runs during __init__ (single-threaded). Liveness clocks restart at
        takeover: every restored-alive worker gets a fresh heartbeat stamp,
        so the reaper gives reconnecting workers a full timeout window
        before declaring anyone dead under the new generation."""
        now = self._clock()
        for w in snap.workers:
            wid = int(w["worker_id"])
            led_by = w.get("led_by")
            info = WorkerInfo(
                worker_id=wid,
                name=w.get("name", ""),
                last_heartbeat=now,
                alive=bool(w.get("alive", True)),
                led_by=int(led_by) if led_by is not None else None,
                data_addr=str(w.get("data_addr") or ""),
            )
            self._workers[wid] = info
            if info.led_by is not None:
                self._cohort_members.setdefault(info.led_by, set()).add(wid)
            self._index_locked(info)
        self._next_id = snap.next_id
        self._version = snap.version
        _MB_ALIVE.set(self._alive_count_locked())
        _MB_VERSION.set(self._version)
        logger.warning(
            "membership restored from control journal: v%d, %d worker(s) "
            "(%d alive)", self._version, len(self._workers),
            self._alive_count_locked(),
        )

    def _index_locked(self, info: WorkerInfo) -> None:
        """Re-sync the alive indexes with info.alive. Must run after every
        liveness flip or entry (re)insert, inside _lock."""
        leaders, members = self._alive_leaders, self._alive_members
        if info.led_by is None:
            members.discard(info.worker_id)
            (leaders.add if info.alive else leaders.discard)(info.worker_id)
        else:
            leaders.discard(info.worker_id)
            (members.add if info.alive else members.discard)(info.worker_id)

    # _j / _take_commit_locked / _await come from CommitGate
    # (master/journal.py) — the ack-after-fsync plumbing shared with the
    # dispatcher, e.g. the RegisterWorker response that tells a worker
    # its id must not leave before the join is on disk

    def add_death_callback(self, cb: Callable[[int], None]) -> None:
        """cb(worker_id) fires when a worker is declared dead — wire this to
        TaskDispatcher.recover_tasks."""
        self._death_callbacks.append(cb)

    def register(self, name: str, preferred_id: int = -1,
                 data_addr: str = "") -> WorkerInfo:
        with self._lock:
            wid = None
            if preferred_id >= 0:
                existing = self._workers.get(preferred_id)
                if existing is None or not existing.alive:
                    wid = preferred_id
            if wid is None:
                wid = self._next_id
            self._next_id = max(self._next_id, wid + 1)
            info = WorkerInfo(worker_id=wid, name=name,
                              last_heartbeat=self._clock(),
                              data_addr=data_addr or "")
            self._workers[wid] = info
            self._index_locked(info)
            self._version += 1
            version = self._version     # the version THIS join created
            self._j(
                "member_join", worker_id=wid, name=name, version=version,
                data_addr=info.data_addr,
            )
            _MB_REGISTERED.inc()
            _MB_ALIVE.set(self._alive_count_locked())
            _MB_VERSION.set(self._version)
            logger.info(
                "worker %d (%s) joined; membership v%d, %d alive",
                wid, name, self._version, self._alive_count_locked(),
            )
            commit = self._take_commit_locked()
        # ack-after-fsync: the response hands the worker an id it will
        # lease under — the join must be durable first
        self._await(commit)
        tracing.event(
            "membership.join", worker_id=info.worker_id, worker_name=name,
            version=version,
        )
        return info

    def register_members(
        self, leader_id: int, names: Sequence[str]
    ) -> List[WorkerInfo]:
        """Register a cohort leader's member processes in ONE pass under
        the lock and ONE journal commit (cohort-aggregated membership).

        Members are telemetry entities, not rendezvous participants: the
        cohort is still ONE logical worker, so member joins bump NO
        membership version (a bump would re-form the mesh) and reap scans
        skip them (their liveness is the leader's beat). Idempotent by
        (name, leader): a leader re-registering after a master restart
        gets the same member ids back, revived if the outage reaped the
        cohort."""
        if len(names) > self.MAX_COHORT_MEMBERS:
            raise ValueError(
                f"cohort of {len(names)} members exceeds the "
                f"{self.MAX_COHORT_MEMBERS}-member registration cap"
            )
        with self._lock:
            leader = self._workers.get(leader_id)
            if leader is None or leader.led_by is not None:
                raise KeyError(
                    f"worker {leader_id} is not a registered cohort leader"
                )
            cohort = self._cohort_members.setdefault(leader_id, set())
            by_name = {
                self._workers[mid].name: self._workers[mid]
                for mid in cohort
                if self._workers[mid].led_by == leader_id
            }
            infos: List[WorkerInfo] = []
            records: List[Tuple[str, Dict]] = []
            now = self._clock()
            for name in names:
                info = by_name.get(name)
                if info is None:
                    info = WorkerInfo(
                        worker_id=self._next_id, name=name,
                        last_heartbeat=now, led_by=leader_id,
                    )
                    self._next_id += 1
                    self._workers[info.worker_id] = info
                    cohort.add(info.worker_id)
                    self._index_locked(info)
                    records.append((
                        "member_join",
                        {"worker_id": info.worker_id, "name": name,
                         "version": self._version, "led_by": leader_id},
                    ))
                else:
                    info.last_heartbeat = now
                    if not info.alive:
                        info.alive = True
                        self._index_locked(info)
                        records.append((
                            "member_join",
                            {"worker_id": info.worker_id, "name": name,
                             "version": self._version, "led_by": leader_id},
                        ))
                infos.append(info)
            commit = (
                self._journal.append_many(records)
                if self._journal is not None and records else None
            )
            _MB_MEMBERS.set(self._member_count_locked())
        self._await(commit)
        if records:
            logger.info(
                "cohort leader %d registered %d member process(es) "
                "(%d new/revived; no version bump)",
                leader_id, len(names), len(records),
            )
        return infos

    def reregister(self, worker_id: int, name: str,
                   data_addr: str = "") -> WorkerInfo:
        """Idempotent re-register of a worker that was ALREADY a member —
        the reconnect handshake after a master restart. A live worker's
        entry is refreshed in place with NO version bump (the worker set
        did not change, so the cohort must not re-form); a worker that was
        reaped during the outage is revived (that IS a membership change —
        version bumps and the join is journaled). Unknown ids fall through
        to a fresh registration, so a journal-less master still converges.
        """
        with self._lock:
            info = self._workers.get(worker_id)
            if info is not None:
                info.name = name or info.name
                info.last_heartbeat = self._clock()
                revived = not info.alive
                addr_changed = bool(data_addr) and data_addr != info.data_addr
                if data_addr:
                    info.data_addr = data_addr
                if revived:
                    info.alive = True
                    self._index_locked(info)
                    self._version += 1
                    self._j(
                        "member_join", worker_id=worker_id, name=info.name,
                        version=self._version, data_addr=info.data_addr,
                    )
                    _MB_ALIVE.set(self._alive_count_locked())
                    _MB_VERSION.set(self._version)
                elif addr_changed:
                    # no version bump (the worker set did not change) but
                    # the address book did — journal the join record so a
                    # successor's replay routes to the NEW endpoint
                    self._j(
                        "member_join", worker_id=worker_id, name=info.name,
                        version=self._version, data_addr=info.data_addr,
                    )
                version = self._version
                logger.info(
                    "worker %d (%s) re-registered%s; membership v%d",
                    worker_id, name, " (revived)" if revived else "", version,
                )
            commit = self._take_commit_locked()
        if info is not None:
            self._await(commit)
        if info is None:
            return self.register(name, preferred_id=worker_id,
                                 data_addr=data_addr)
        tracing.event(
            "membership.reregister", worker_id=worker_id, worker_name=name,
            version=version,
        )
        return info

    def heartbeat(self, worker_id: int, model_version: int = 0,
                  stats: "Dict | None" = None,
                  members: "Sequence[Tuple[int, int, Dict | None]] | None"
                  = None) -> bool:
        """Liveness stamp + (optionally) a telemetry record update. `stats`
        is the decoded heartbeat payload (observability/health.py) or None
        for a liveness-only beat — old workers mid-rolling-restart send
        none and lose nothing but the straggler detector's view of them.

        `members` is a cohort leader's coalesced beat: (member_id,
        model_version, stats) per member process, applied under the SAME
        lock acquisition and timestamp — one RPC, one lock pass, N
        telemetry records. Beats for ids this leader does not lead are
        ignored (a stale leader must not refresh someone else's member)."""
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None or not info.alive:
                return False
            now = self._clock()
            self._beat_locked(info, now, model_version, stats)
            coalesced = 0
            for mid, m_version, m_stats in members or ():
                member = self._workers.get(mid)
                if member is None or member.led_by != worker_id:
                    continue
                member.alive = True    # the leader's beat IS their liveness
                self._alive_members.add(mid)
                self._beat_locked(member, now, m_version, m_stats)
                coalesced += 1
        _MB_BEATS.inc()
        if coalesced:
            _MB_COALESCED.inc(coalesced)
        return True

    def _beat_locked(self, info: WorkerInfo, now: float,
                     model_version: int, stats: "Dict | None") -> None:
        info.last_heartbeat = now
        info.model_version = max(info.model_version, model_version)
        if stats:
            prev = self._health.get(info.worker_id)
            rec = dict(stats)
            rec.update(
                worker_id=info.worker_id,
                name=info.name,
                model_version=info.model_version,
                updated_at=now,
                updates=(prev.get("updates", 0) + 1) if prev else 1,
            )
            self._health[info.worker_id] = rec

    def mark_dead(self, worker_id: int, reason: str = "") -> bool:
        """Declare a worker dead. A cohort LEADER's death cascades to its
        member processes in the same critical section — members die with
        their leader under ONE version bump and ONE journal commit, so a
        thousand-process cohort going away costs the same as a singleton
        (O(cohorts), not O(workers))."""
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None or not info.alive:
                return False
            info.alive = False
            self._index_locked(info)
            if info.led_by is None:
                self._version += 1      # a LOGICAL worker left the world
            version = self._version
            records = [
                ("member_death", {"worker_id": worker_id, "version": version})
            ]
            cascade = []
            if info.led_by is None:
                # alive-index intersection, not a full-registry walk: a
                # thousand-cohort fleet reaps one leader in O(its members)
                cascade = [
                    self._workers[mid] for mid in sorted(
                        self._cohort_members.get(worker_id, set())
                        & self._alive_members
                    )
                    if self._workers[mid].led_by == worker_id
                ]
                for member in cascade:
                    member.alive = False
                    self._index_locked(member)
                    records.append((
                        "member_death",
                        {"worker_id": member.worker_id, "version": version},
                    ))
            if self._journal is not None:
                self._pending_commit = self._journal.append_many(records)
            commit = self._take_commit_locked()
            _MB_DEATHS.inc(1 + len(cascade))
            _MB_ALIVE.set(self._alive_count_locked())
            _MB_MEMBERS.set(self._member_count_locked())
            _MB_VERSION.set(self._version)
            logger.warning(
                "worker %d declared dead (%s)%s; membership v%d, %d alive",
                worker_id, reason or "unknown",
                f" with {len(cascade)} cohort member(s)" if cascade else "",
                self._version, self._alive_count_locked(),
            )
        self._await(commit)
        tracing.event(
            "membership.death", worker_id=worker_id, reason=reason or "",
            version=version, cascade=len(cascade),
        )
        for cb in self._death_callbacks:
            cb(worker_id)
            for member in cascade:
                cb(member.worker_id)
        return True

    def reap(self) -> List[int]:
        """Declare workers dead whose heartbeats lapsed. Returns their ids.
        Cohort members are SKIPPED — their liveness is the leader's beat
        (they die with it via the mark_dead cascade) — and the scan walks
        the alive-leader INDEX, so the cost is O(alive cohorts +
        singletons): dead rows and member processes are never touched."""
        now = self._clock()
        with self._lock:
            lapsed = sorted(
                wid
                for wid in self._alive_leaders
                if now - self._workers[wid].last_heartbeat > self._timeout
            )
        for wid in lapsed:
            if self.mark_dead(wid, reason="heartbeat timeout"):
                _MB_REAPED.inc()
        return lapsed

    def _alive_count_locked(self) -> int:
        """Alive LOGICAL workers (cohort leaders + singletons): member
        processes are not rendezvous participants and must not inflate
        num_workers (LR scaling, wait-for-workers logic)."""
        return len(self._alive_leaders)

    def _member_count_locked(self) -> int:
        return len(self._alive_members)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def alive_count(self) -> int:
        with self._lock:
            return self._alive_count_locked()

    def alive_workers(self) -> List[WorkerInfo]:
        with self._lock:
            return [
                self._workers[wid]
                for wid in sorted(self._alive_leaders | self._alive_members)
            ]

    def data_addresses(self) -> List[Tuple[int, str]]:
        """The owner address book (ISSUE 15): (worker id, data-plane
        endpoint) for every alive logical worker that registered one —
        what the shard-map response carries so tier clients can route
        pull/push over gRPC to whichever process owns a shard."""
        with self._lock:
            return sorted(
                (wid, self._workers[wid].data_addr)
                for wid in self._alive_leaders
                if self._workers[wid].data_addr
            )

    def health_snapshot(self) -> List[Dict]:
        """Telemetry records (copies) of currently-ALIVE workers — the
        straggler scorer's input. Dead workers keep their records in the
        store (revival resumes the history) but are not scored. Walks the
        alive indexes, not the full registry, so the per-poll fleet
        rollup stays O(alive) when dead history dominates."""
        with self._lock:
            return [
                dict(self._health[wid])
                for wid in sorted(self._alive_leaders | self._alive_members)
                if wid in self._health
            ]
