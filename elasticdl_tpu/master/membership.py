"""Worker membership registry — the failure detector + rendezvous version.

Reference parity: two components merged. The reference's instance manager
watches k8s pod events to detect worker death
(elasticdl/python/master/k8s_instance_manager.py), and its rendezvous server
bumps a world version so Horovod re-forms
(elasticdl/python/master/rendezvous_server.py). Here both jobs are served by
one registry: liveness from heartbeats (works with or without k8s; the pod
watcher feeds in too), and a monotonically increasing `membership_version`
workers watch to know when to re-form the `jax.distributed` mesh.

Heartbeats optionally carry a compact stats payload (gRPC metadata,
observability/health.py): the registry keeps a ROLLING per-worker health
record — last step-time quantiles, records/s, prefetch depth, breaker
state, rescale phase — which `ClusterHealth` scores for stragglers. The
records deliberately survive re-register and even death/revival (they are
history about a worker id, not liveness state), so a reconnect after a
master hiccup does not blind the straggler detector for a full window.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry

logger = default_logger(__name__)

_reg = default_registry()
_MB_REGISTERED = _reg.counter(
    "edl_membership_registrations_total", "worker registrations")
_MB_DEATHS = _reg.counter(
    "edl_membership_deaths_total", "workers declared dead (any reason)")
_MB_REAPED = _reg.counter(
    "edl_membership_reaped_total",
    "workers declared dead by heartbeat-timeout reaping")
_MB_ALIVE = _reg.gauge(
    "edl_membership_alive_workers", "currently alive workers")
_MB_VERSION = _reg.gauge(
    "edl_membership_version", "current membership version")


@dataclass
class WorkerInfo:
    worker_id: int
    name: str
    last_heartbeat: float
    model_version: int = 0
    alive: bool = True


class Membership:
    def __init__(self, heartbeat_timeout_s: float = 30.0, journal=None):
        self._lock = threading.Lock()
        # Crash durability (master/journal.py): join/death transitions are
        # committed inside the _lock critical sections that apply them, so
        # a restarted master replays the registry instead of telling every
        # reconnecting worker to shut down as an unknown. None = volatile.
        self._journal = journal
        self._workers: Dict[int, WorkerInfo] = {}    # guarded_by: _lock
        # rolling per-worker heartbeat telemetry (health.py records);
        # NEVER reset by reregister/mark_dead — see module docstring
        self._health: Dict[int, Dict] = {}           # guarded_by: _lock
        self._next_id = 0                            # guarded_by: _lock
        self._version = 0                            # guarded_by: _lock
        self._timeout = heartbeat_timeout_s
        # registration-before-start contract (wired while the master is
        # single-threaded); mark_dead iterates OUTSIDE the lock on purpose —
        # callbacks re-enter the dispatcher
        self._death_callbacks: List[Callable[[int], None]] = []
        snap = journal.membership_snapshot() if journal is not None else None
        if snap is not None:
            self._restore(snap)

    def _restore(self, snap) -> None:  # holds: _lock (construction)
        """Rebuild the registry from a replayed journal (master recovery).
        Runs during __init__ (single-threaded). Liveness clocks restart at
        takeover: every restored-alive worker gets a fresh heartbeat stamp,
        so the reaper gives reconnecting workers a full timeout window
        before declaring anyone dead under the new generation."""
        now = time.time()
        for w in snap.workers:
            wid = int(w["worker_id"])
            self._workers[wid] = WorkerInfo(
                worker_id=wid,
                name=w.get("name", ""),
                last_heartbeat=now,
                alive=bool(w.get("alive", True)),
            )
        self._next_id = snap.next_id
        self._version = snap.version
        _MB_ALIVE.set(self._alive_count_locked())
        _MB_VERSION.set(self._version)
        logger.warning(
            "membership restored from control journal: v%d, %d worker(s) "
            "(%d alive)", self._version, len(self._workers),
            self._alive_count_locked(),
        )

    def _j(self, rtype: str, **fields) -> None:  # holds: _lock
        """Commit one journal record (no-op without a journal)."""
        if self._journal is not None:
            self._journal.append(rtype, **fields)

    def add_death_callback(self, cb: Callable[[int], None]) -> None:
        """cb(worker_id) fires when a worker is declared dead — wire this to
        TaskDispatcher.recover_tasks."""
        self._death_callbacks.append(cb)

    def register(self, name: str, preferred_id: int = -1) -> WorkerInfo:
        with self._lock:
            wid = None
            if preferred_id >= 0:
                existing = self._workers.get(preferred_id)
                if existing is None or not existing.alive:
                    wid = preferred_id
            if wid is None:
                wid = self._next_id
            self._next_id = max(self._next_id, wid + 1)
            info = WorkerInfo(worker_id=wid, name=name, last_heartbeat=time.time())
            self._workers[wid] = info
            self._version += 1
            version = self._version     # the version THIS join created
            self._j(
                "member_join", worker_id=wid, name=name, version=version
            )
            _MB_REGISTERED.inc()
            _MB_ALIVE.set(self._alive_count_locked())
            _MB_VERSION.set(self._version)
            logger.info(
                "worker %d (%s) joined; membership v%d, %d alive",
                wid, name, self._version, self._alive_count_locked(),
            )
        tracing.event(
            "membership.join", worker_id=info.worker_id, worker_name=name,
            version=version,
        )
        return info

    def reregister(self, worker_id: int, name: str) -> WorkerInfo:
        """Idempotent re-register of a worker that was ALREADY a member —
        the reconnect handshake after a master restart. A live worker's
        entry is refreshed in place with NO version bump (the worker set
        did not change, so the cohort must not re-form); a worker that was
        reaped during the outage is revived (that IS a membership change —
        version bumps and the join is journaled). Unknown ids fall through
        to a fresh registration, so a journal-less master still converges.
        """
        with self._lock:
            info = self._workers.get(worker_id)
            if info is not None:
                info.name = name or info.name
                info.last_heartbeat = time.time()
                revived = not info.alive
                if revived:
                    info.alive = True
                    self._version += 1
                    self._j(
                        "member_join", worker_id=worker_id, name=info.name,
                        version=self._version,
                    )
                    _MB_ALIVE.set(self._alive_count_locked())
                    _MB_VERSION.set(self._version)
                version = self._version
                logger.info(
                    "worker %d (%s) re-registered%s; membership v%d",
                    worker_id, name, " (revived)" if revived else "", version,
                )
        if info is None:
            return self.register(name, preferred_id=worker_id)
        tracing.event(
            "membership.reregister", worker_id=worker_id, worker_name=name,
            version=version,
        )
        return info

    def heartbeat(self, worker_id: int, model_version: int = 0,
                  stats: "Dict | None" = None) -> bool:
        """Liveness stamp + (optionally) a telemetry record update. `stats`
        is the decoded heartbeat payload (observability/health.py) or None
        for a liveness-only beat — old workers mid-rolling-restart send
        none and lose nothing but the straggler detector's view of them."""
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None or not info.alive:
                return False
            info.last_heartbeat = time.time()
            info.model_version = max(info.model_version, model_version)
            if stats:
                prev = self._health.get(worker_id)
                rec = dict(stats)
                rec.update(
                    worker_id=worker_id,
                    name=info.name,
                    model_version=info.model_version,
                    updated_at=info.last_heartbeat,
                    updates=(prev.get("updates", 0) + 1) if prev else 1,
                )
                self._health[worker_id] = rec
            return True

    def mark_dead(self, worker_id: int, reason: str = "") -> bool:
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None or not info.alive:
                return False
            info.alive = False
            self._version += 1
            version = self._version     # the version THIS death created
            self._j("member_death", worker_id=worker_id, version=version)
            _MB_DEATHS.inc()
            _MB_ALIVE.set(self._alive_count_locked())
            _MB_VERSION.set(self._version)
            logger.warning(
                "worker %d declared dead (%s); membership v%d, %d alive",
                worker_id, reason or "unknown", self._version,
                self._alive_count_locked(),
            )
        tracing.event(
            "membership.death", worker_id=worker_id, reason=reason or "",
            version=version,
        )
        for cb in self._death_callbacks:
            cb(worker_id)
        return True

    def reap(self) -> List[int]:
        """Declare workers dead whose heartbeats lapsed. Returns their ids."""
        now = time.time()
        with self._lock:
            lapsed = [
                wid
                for wid, info in self._workers.items()
                if info.alive and now - info.last_heartbeat > self._timeout
            ]
        for wid in lapsed:
            if self.mark_dead(wid, reason="heartbeat timeout"):
                _MB_REAPED.inc()
        return lapsed

    def _alive_count_locked(self) -> int:
        return sum(1 for w in self._workers.values() if w.alive)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def alive_count(self) -> int:
        with self._lock:
            return self._alive_count_locked()

    def alive_workers(self) -> List[WorkerInfo]:
        with self._lock:
            return [w for w in self._workers.values() if w.alive]

    def health_snapshot(self) -> List[Dict]:
        """Telemetry records (copies) of currently-ALIVE workers — the
        straggler scorer's input. Dead workers keep their records in the
        store (revival resumes the history) but are not scored."""
        with self._lock:
            return [
                dict(self._health[wid])
                for wid, w in sorted(self._workers.items())
                if w.alive and wid in self._health
            ]
