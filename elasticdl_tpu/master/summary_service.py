"""Training summaries: JSONL event log + optional TensorBoard files.

Reference parity: elasticdl/python/master/tensorboard_service.py — the master
optionally wrote TF summaries of training loss and evaluation metrics. Here
the master always writes a machine-readable `events.jsonl` (one JSON object
per line: {"step", "wall_time", <scalars>}) under <summary_dir>/<role>/ and,
when TensorFlow is importable, mirrors the scalars into TensorBoard event
files so `tensorboard --logdir` works exactly as it did for the reference.

Control-plane metrics ride the same stream: `maybe_snapshot_registry`
periodically writes the observability registry's snapshot into a
`control/` scalar stream, so events.jsonl/TensorBoard carry compile-cache
hit rates, RPC retries, and lease churn alongside loss.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
)

logger = default_logger(__name__)


class SummaryWriter:
    """One scalar stream (e.g. 'train' or 'eval')."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        # guarded_by: _lock
        self._jsonl = open(os.path.join(directory, "events.jsonl"), "a")
        self._lock = threading.Lock()
        self._tf_writer = None               # guarded_by: _lock
        # resolve the module ONCE: the old code re-imported tensorflow
        # inside the lock on every scalars() call — sys.modules makes that
        # a dict hit, but it still serialized an import-lock acquisition
        # into every report under this writer's lock
        self._tf = None
        try:
            import tensorflow as tf

            self._tf = tf
            self._tf_writer = tf.summary.create_file_writer(directory)
        except Exception:
            # TF-less deployments still get the JSONL stream
            self._tf = None
            self._tf_writer = None

    def scalars(self, step: int, values: Dict[str, float]) -> None:
        rec = {"step": int(step), "wall_time": time.time()}
        rec.update({k: float(v) for k, v in values.items()})
        with self._lock:
            if self._jsonl.closed:
                return
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
            if self._tf_writer is not None:
                tf = self._tf
                with self._tf_writer.as_default():
                    for name, value in values.items():
                        tf.summary.scalar(name, float(value), step=int(step))
                self._tf_writer.flush()

    def close(self) -> None:
        with self._lock:
            if not self._jsonl.closed:
                # fsync before close: a worker killed right after close()
                # returns must still find every line on disk — the chaos
                # tests race exactly this window
                try:
                    self._jsonl.flush()
                    # teardown of a leaf writer lock (never held by
                    # control-plane mutators): edl-lint: disable=EDL403,EDL103
                    os.fsync(self._jsonl.fileno())
                except (OSError, ValueError):
                    logger.exception("events.jsonl fsync failed")
                self._jsonl.close()
            if self._tf_writer is not None:
                self._tf_writer.close()


class SummaryService:
    """Master-side aggregation point: training loss per task report, eval
    metrics per finished eval job, periodic control-plane registry
    snapshots."""

    def __init__(self, summary_dir: str,
                 registry: Optional[MetricsRegistry] = None,
                 snapshot_interval_s: float = 10.0):
        self._dir = os.path.abspath(summary_dir)
        self._train = SummaryWriter(os.path.join(self._dir, "train"))
        # lazily created on the first eval result, which arrives on a gRPC
        # handler thread — two eval jobs can finalize concurrently, so the
        # check-then-create must be locked (edl-lint EDL101 find: the old
        # unlocked version could build two writers and leak one)
        self._eval_lock = threading.Lock()
        self._eval: Optional[SummaryWriter] = None   # guarded_by: _eval_lock
        # control-plane registry snapshot stream (lazy, like eval)
        self._registry = registry or default_registry()
        self._snapshot_interval_s = snapshot_interval_s
        self._control_lock = threading.Lock()
        self._control: Optional[SummaryWriter] = None  # guarded_by: _control_lock
        self._last_snapshot = 0.0                      # guarded_by: _control_lock

    def on_task_report(self, model_version: int, loss_sum: float, loss_count: int,
                       step_time_sum: float = 0.0, step_count: int = 0) -> None:
        if loss_count > 0:
            scalars = {"loss": loss_sum / loss_count}
            if step_count > 0:
                # per-step wall time (ms), as measured around the worker's
                # blocking train step — SURVEY §5's "do better than the
                # reference here cheaply" observability item
                scalars["step_time_ms"] = 1e3 * step_time_sum / step_count
            self._train.scalars(model_version, scalars)

    def on_eval_results(self, model_version: int, results: Dict[str, float]) -> None:
        with self._eval_lock:
            if self._eval is None:
                self._eval = SummaryWriter(os.path.join(self._dir, "eval"))
            writer = self._eval
        writer.scalars(model_version, results)

    # ------------------------------------------------------------------ #
    # control-plane registry stream

    def snapshot_registry(self, step: int) -> None:
        """Write the registry snapshot into the `control/` scalar stream
        now (numeric series only; label braces survive as scalar names)."""
        with self._control_lock:
            if self._control is None:
                self._control = SummaryWriter(
                    os.path.join(self._dir, "control"))
            writer = self._control
            self._last_snapshot = time.monotonic()
        snap = {
            k: v for k, v in self._registry.snapshot().items()
            if isinstance(v, (int, float))
        }
        if snap:
            writer.scalars(step, snap)

    def maybe_snapshot_registry(self, step: int) -> None:
        """Rate-limited snapshot — the master's wait loop calls this every
        poll; writes land every `snapshot_interval_s`. Never raises."""
        with self._control_lock:
            due = (
                time.monotonic() - self._last_snapshot
                >= self._snapshot_interval_s
            )
        if not due:
            return
        try:
            self.snapshot_registry(step)
        except Exception:
            logger.exception("registry snapshot failed")

    def close(self) -> None:
        # EDL103 find: writer.close() fsyncs — take the reference under
        # the service lock, do the blocking close outside it, so a slow
        # disk can't convoy a concurrent eval finalizing on a handler
        # thread behind _eval_lock
        self._train.close()
        with self._eval_lock:
            ev = self._eval
        if ev is not None:
            ev.close()
        with self._control_lock:
            ctl = self._control
        if ctl is not None:
            ctl.close()
