"""Training summaries: JSONL event log + optional TensorBoard files.

Reference parity: elasticdl/python/master/tensorboard_service.py — the master
optionally wrote TF summaries of training loss and evaluation metrics. Here
the master always writes a machine-readable `events.jsonl` (one JSON object
per line: {"step", "wall_time", <scalars>}) under <summary_dir>/<role>/ and,
when TensorFlow is importable, mirrors the scalars into TensorBoard event
files so `tensorboard --logdir` works exactly as it did for the reference.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)


class SummaryWriter:
    """One scalar stream (e.g. 'train' or 'eval')."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        # guarded_by: _lock
        self._jsonl = open(os.path.join(directory, "events.jsonl"), "a")
        self._lock = threading.Lock()
        self._tf_writer = None               # guarded_by: _lock
        try:
            import tensorflow as tf

            self._tf_writer = tf.summary.create_file_writer(directory)
        except Exception:
            # TF-less deployments still get the JSONL stream
            self._tf_writer = None

    def scalars(self, step: int, values: Dict[str, float]) -> None:
        rec = {"step": int(step), "wall_time": time.time()}
        rec.update({k: float(v) for k, v in values.items()})
        with self._lock:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
            if self._tf_writer is not None:
                import tensorflow as tf

                with self._tf_writer.as_default():
                    for name, value in values.items():
                        tf.summary.scalar(name, float(value), step=int(step))
                self._tf_writer.flush()

    def close(self) -> None:
        with self._lock:
            self._jsonl.close()
            if self._tf_writer is not None:
                self._tf_writer.close()


class SummaryService:
    """Master-side aggregation point: training loss per task report, eval
    metrics per finished eval job."""

    def __init__(self, summary_dir: str):
        self._dir = os.path.abspath(summary_dir)
        self._train = SummaryWriter(os.path.join(self._dir, "train"))
        # lazily created on the first eval result, which arrives on a gRPC
        # handler thread — two eval jobs can finalize concurrently, so the
        # check-then-create must be locked (edl-lint EDL101 find: the old
        # unlocked version could build two writers and leak one)
        self._eval_lock = threading.Lock()
        self._eval: Optional[SummaryWriter] = None   # guarded_by: _eval_lock

    def on_task_report(self, model_version: int, loss_sum: float, loss_count: int,
                       step_time_sum: float = 0.0, step_count: int = 0) -> None:
        if loss_count > 0:
            scalars = {"loss": loss_sum / loss_count}
            if step_count > 0:
                # per-step wall time (ms), as measured around the worker's
                # blocking train step — SURVEY §5's "do better than the
                # reference here cheaply" observability item
                scalars["step_time_ms"] = 1e3 * step_time_sum / step_count
            self._train.scalars(model_version, scalars)

    def on_eval_results(self, model_version: int, results: Dict[str, float]) -> None:
        with self._eval_lock:
            if self._eval is None:
                self._eval = SummaryWriter(os.path.join(self._dir, "eval"))
            writer = self._eval
        writer.scalars(model_version, results)

    def close(self) -> None:
        self._train.close()
        with self._eval_lock:
            if self._eval is not None:
                self._eval.close()
