"""Master entrypoint: task service + membership + evaluation over gRPC.

Reference parity: elasticdl/python/master/main.py — parse args, create data
shards and the task dispatcher, start the gRPC servicer and services, manage
worker instances, run to job end. The instance manager half (spawning and
relaunching workers) lives in process_manager.py / k8s.py; this module wires
the control plane and blocks until the job finishes.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.journal import ControlPlaneJournal
from elasticdl_tpu.master.membership import Membership
from elasticdl_tpu.master.poll_phases import poll_phase
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.proto.service import add_master_servicer, make_server

logger = default_logger(__name__)


class Master:
    def __init__(self, cfg: JobConfig, k8s_api=None):
        cfg.validate()
        self.cfg = cfg
        # observability first: every span/log below carries the role, and
        # trace.jsonl lands under <trace_dir|summary_dir/trace>/master/
        from elasticdl_tpu.observability import flight as flight_lib
        from elasticdl_tpu.observability import tracing

        tracing.configure_from_config(cfg, role="master")
        # flight recorder (observability/flight.py): the master's black
        # box — dumps on crash/SIGUSR2//debug/flight and on straggler
        # onset (the health hook below)
        flight_lib.configure_from_config(cfg, role="master")
        flight_lib.install_crash_hooks()
        self.metrics_server = None
        # cfg.instance_manager == "k8s": this master owns worker pods
        # (created in start()); k8s_api injects a fake for tests
        self._k8s_api = k8s_api
        self.instance_manager = None

        # Bind the serving port BEFORE the journal opens: every journal
        # open replays + rotates + bumps the generation, so a lost bind
        # (the crashed predecessor's port lingering for a beat — exactly
        # what _rebuild_master retries through) must fail before any
        # generation is committed, or each retry inflates it past the real
        # restart count. add_insecure_port is legal before handlers are
        # registered; PortBindError (a RuntimeError) lets launchers that
        # picked the port via free_port() retry with a fresh one
        # (net.bind_with_retry). Depending on grpc version, a lost bind
        # returns 0 or raises.
        self.summary = None
        self.journal: Optional[ControlPlaneJournal] = None
        self.server = make_server()
        port = int(cfg.master_addr.rsplit(":", 1)[1])
        from elasticdl_tpu.common.net import PortBindError

        try:
            bound = self.server.add_insecure_port(f"[::]:{port}")
        except RuntimeError as e:
            self._release_on_bind_failure()
            raise PortBindError(f"could not bind master port {port}: {e}") from e
        if bound == 0:
            self._release_on_bind_failure()
            raise PortBindError(f"could not bind master port {port}")

        def shards_for(path: str):
            if not path:
                return []
            reader = create_data_reader(
                path, cfg.data_reader, **cfg.data_reader_params
            )
            return reader.create_shards()

        train_shards = (
            shards_for(cfg.training_data)
            if cfg.job_type
            in (JobType.TRAINING_ONLY, JobType.TRAINING_WITH_EVALUATION)
            else []
        )
        eval_shards = shards_for(cfg.validation_data)
        predict_shards = (
            shards_for(cfg.prediction_data)
            if cfg.job_type == JobType.PREDICTION_ONLY
            else []
        )

        # Control-plane durability (master/journal.py): with a checkpoint
        # dir, task/membership state transitions are journaled and a master
        # restart replays them — a crash becomes a recoverable event instead
        # of a job-killing one. Opening the journal FIRST (before dispatcher
        # and membership) means their constructors see the replayed state.
        self.journal = (
            ControlPlaneJournal(
                cfg.checkpoint_dir, fsync=cfg.journal_fsync,
                group_commit_ms=cfg.journal_group_commit_ms,
            )
            if cfg.checkpoint_dir else None
        )
        if self.journal is not None and self.journal.recovered:
            tracing.event(
                "master.recovered", generation=self.journal.generation,
            )
            # A dead master's announced resize plan must not outlive it:
            # clear the membership signal's pending world size + reform
            # trace id (workers' speculative compilers would otherwise keep
            # precompiling against the dead plan) and stamp our generation.
            from elasticdl_tpu.common import membership_signal

            signal_path = membership_signal.default_path(cfg.checkpoint_dir)
            if signal_path:
                membership_signal.clear_stale_on_takeover(
                    signal_path, master_generation=self.journal.generation
                )
        self.dispatcher = TaskDispatcher(
            training_shards=train_shards,
            evaluation_shards=eval_shards,
            prediction_shards=predict_shards,
            records_per_task=cfg.records_per_task,
            num_epochs=cfg.num_epochs,
            max_task_retries=cfg.max_task_retries,
            shuffle=cfg.shuffle,
            shuffle_seed=cfg.shuffle_seed,
            task_timeout_s=cfg.task_timeout_s,
            # end-of-job durability: one exclusive SAVE_MODEL task before
            # job-end whenever training checkpoints somewhere (SURVEY §2.1)
            final_save_model=bool(cfg.checkpoint_dir) and bool(train_shards),
            journal=self.journal,
        )
        self.membership = Membership(
            heartbeat_timeout_s=3 * cfg.worker_heartbeat_s,
            journal=self.journal,
        )
        self.membership.add_death_callback(self.dispatcher.recover_tasks)
        # Cluster health intelligence (observability/health.py): scores the
        # heartbeat-piggybacked worker telemetry for stragglers every wait
        # poll, exports the edl_cluster_* rollup (served by this process's
        # /metrics), and feeds the enriched /healthz. The hook is log-only
        # — the seam where elasticity decisions will plug in.
        from elasticdl_tpu.observability.health import ClusterHealth

        # --straggler_quorum (floor 2, validated at boot): a 2-worker
        # fleet can flag its straggler through the min_ratio gate; the
        # old hard-coded 3 stays the default
        self.health = ClusterHealth(
            self.membership, min_workers=cfg.straggler_quorum,
        )
        # the PR 6 straggler hook's first real consumer: onset cuts the
        # MASTER's black box (fleet view, journal state, recent control-
        # plane events at the moment the fleet went ragged). The OFFENDER
        # side is launcher-wired (client/local.py SIGUSR2s the worker
        # process — only the launcher knows pids).
        self.health.add_hook(self._straggler_flight_hook)
        # Observe->decide backbone (ISSUE 11, observability/timeseries.py
        # + alerts.py): the master's time-series ring additionally
        # accumulates FLEET series computed from the heartbeat stats
        # payloads it already receives (_fleet_series), and the alert
        # engine evaluates its declarative rules against that history on
        # every wait poll. The engine's hook seam is where ROADMAP 3's
        # autoscaler subscribes.
        from elasticdl_tpu.observability import alerts as alerts_lib
        from elasticdl_tpu.observability import timeseries as timeseries_lib

        self.timeseries = timeseries_lib.configure_from_config(
            cfg, role="master")
        base_dir = cfg.summary_dir or cfg.checkpoint_dir
        self.alerts = alerts_lib.AlertEngine(
            self.timeseries,
            rules=alerts_lib.rules_from_config(cfg),
            json_path=(os.path.join(base_dir, "control", "alerts.json")
                       if base_dir else None),
        )
        # Fleet goodput ledger (ISSUE 12, observability/goodput.py): the
        # rollup over heartbeat ledger payloads + the dispatcher's
        # journal-durable wasted-work bill — recomputed every wait poll,
        # exported as edl_goodput_* gauges, sampled into the time series
        # (the goodput_burn / wasted_work_ratio default rules' input),
        # served at /goodput and inside /healthz.
        from elasticdl_tpu.observability.goodput import FleetGoodput

        self.goodput = FleetGoodput(self.membership, self.dispatcher)

        # Fleet tail attribution (ISSUE 19, observability/reqtrace.py):
        # the rollup over heartbeat rt_* diary payloads — names the
        # fleet-dominant slow-request stage and pulses when it shifts
        # (the emb_attr_dominant_shift default rule's input).
        from elasticdl_tpu.observability.reqtrace import FleetAttribution

        self.attribution = FleetAttribution()

        # Closed-loop autoscaler (ISSUE 14, master/autoscaler.py): turns
        # the two decision seams above — ClusterHealth straggler onsets
        # and the backlog/data-wait alert rules — into journaled, fenced
        # rescale actions, evaluated on the wait poll below. None when
        # --autoscale is off (the default: rescales stay human-
        # initiated). The ACTION surface binds later: client/local.py
        # wires the ProcessManagerTarget (only the launcher owns worker
        # processes); start() wires the k8s flavor. Until a target is
        # bound every decision suppresses — journaled — with no_target.
        from elasticdl_tpu.master import autoscaler as autoscaler_lib

        self.autoscaler = autoscaler_lib.from_config(
            cfg, journal=self.journal,
        )
        if self.autoscaler is not None:
            self.autoscaler.subscribe(health=self.health, alerts=self.alerts)

        # Elastic sharded embedding tier (ROADMAP 1): the master owns the
        # id-sharded table map, durable through the same journal as task
        # accounting — a master crash mid-resharding replays to the last
        # COMMITTED map. Worker death triggers a minimal-movement
        # re-plan; workers execute the moves and confirm via
        # ReportEmbeddingReshard (servicer), which commits the plan.
        self.embedding = None
        if cfg.embedding_shards > 0:
            from elasticdl_tpu.embedding.sharding import ShardMapOwner

            self.embedding = ShardMapOwner(
                cfg.embedding_shards, journal=self.journal,
                replica_count=cfg.embedding_read_replicas,
            )
            if (
                self.journal is not None
                and self.journal.embedding_snapshot() is not None
            ):
                self.embedding.restore_from_replay(
                    self.journal.embedding_snapshot()
                )
            self.membership.add_death_callback(self._embedding_on_death)

        # Closed-loop LAYOUT controller (ISSUE 20,
        # master/layout_controller.py): the embedding-tier sibling of
        # the autoscaler above — skew signals (shard imbalance, cache-
        # hit collapse, hot-id share) become journaled, cost-gated
        # layout actions (replica fan-out, split/merge, hot-id
        # promotion), evaluated on the same wait poll. None when
        # --layout_autoscale is off (the default). On the distributed
        # path the target is the owner map only — workers adopt the new
        # layout at their next map refresh — so split/merge suppress as
        # `unsupported`; the in-process StoreLayoutTarget (bench,
        # fleetsim, tests) supports all five kinds.
        self.layout = None
        if self.embedding is not None:
            from elasticdl_tpu.master import layout_controller as layout_lib

            self.layout = layout_lib.from_config(cfg, journal=self.journal)
            if self.layout is not None:
                self.layout.subscribe(alerts=self.alerts)
                self.layout.bind_target(layout_lib.OwnerLayoutTarget(
                    self.embedding, membership=self.membership))

        metrics = None
        callbacks = []
        if eval_shards or cfg.model_def:
            # the master loads the model module too — it owns metric
            # finalization and job-level callbacks (reference: the master's
            # evaluation service + the zoo callbacks() contract)
            from elasticdl_tpu.common.model_utils import get_module_attr, load_module

            module, _ = load_module(cfg.model_zoo, cfg.model_def)
            metrics_fn = get_module_attr(
                module, "eval_metrics_fn", cfg.eval_metrics_fn, required=False
            )
            metrics = dict(metrics_fn()) if metrics_fn else {}
            callbacks_fn = get_module_attr(module, "callbacks", "", required=False)
            callbacks = list(callbacks_fn()) if callbacks_fn else []
        self.evaluation: Optional[EvaluationService] = (
            EvaluationService(
                self.dispatcher,
                metrics,
                evaluation_steps=cfg.evaluation_steps,
                start_delay_steps=cfg.evaluation_start_delay_steps,
            )
            if eval_shards
            else None
        )
        if cfg.summary_dir:
            from elasticdl_tpu.master.summary_service import SummaryService

            self.summary = SummaryService(cfg.summary_dir)
            if self.evaluation is not None:
                self.evaluation.add_result_callback(self.summary.on_eval_results)
        self.servicer = MasterServicer(
            self.dispatcher, self.membership, self.evaluation,
            summary_service=self.summary,
            # journaled masters fence RPCs from before their last restart
            # (0 = fencing off for volatile masters; proto/service.py)
            generation=self.journal.generation if self.journal else 0,
            embedding=self.embedding,
        )
        # Zoo callbacks observe job events and act via JobContext (round-3:
        # callbacks() was collected but never invoked — now wired).
        self.callbacks = callbacks
        if callbacks:
            from elasticdl_tpu.api.callbacks import JobContext

            ctx = JobContext(
                self.dispatcher, servicer=self.servicer,
                evaluation=self.evaluation,
            )
            for cb in callbacks:
                if hasattr(cb, "set_context"):
                    cb.set_context(ctx)
                if self.evaluation is not None and hasattr(cb, "on_eval_result"):
                    self.evaluation.add_result_callback(cb.on_eval_result)
                if hasattr(cb, "on_epoch_end"):
                    self.dispatcher.add_epoch_end_callback(cb.on_epoch_end)
                if hasattr(cb, "on_job_end"):
                    self.dispatcher.add_job_end_callback(cb.on_job_end)
            logger.info("wired %d zoo callback(s)", len(callbacks))
        # a completed eviction (or any death) prunes the sticky drain-
        # handshake bit — a revived worker id must not inherit it
        self.membership.add_death_callback(self.servicer.clear_evict)
        add_master_servicer(self.server, self.servicer)

    def _release_on_bind_failure(self) -> None:
        """A lost bind abandons this instance (bind_with_retry constructs a
        fresh Master per attempt): release what __init__ already built, or
        every failed attempt keeps its summary file handles and gRPC thread
        pool alive for the rest of the job."""
        try:
            self.server.stop(None)
        except Exception:
            logger.exception("abandoned master: server stop failed")
        if self.summary is not None:
            try:
                self.summary.close()
            except Exception:
                logger.exception("abandoned master: summary close failed")
        if self.journal is not None:
            # two live journal handles would interleave writers on the
            # same file; the retry's next Master must be the sole owner
            try:
                self.journal.close()
            except Exception:
                logger.exception("abandoned master: journal close failed")

    def start(self) -> None:
        self.server.start()
        logger.info("master serving on %s", self.cfg.master_addr)
        # /metrics + /healthz (best-effort; never a boot failure; a set
        # EDL_METRICS_PORT overrides cfg.metrics_port either way)
        from elasticdl_tpu.observability.http import start_server

        self.metrics_server = start_server(
            role="master", port=self.cfg.metrics_port,
            health_fn=self._healthz_extra,
            timeseries=self.timeseries, alerts=self.alerts,
            goodput_fn=self.goodput.snapshot,
        )
        if self.cfg.instance_manager == "k8s":
            # the reference's k8s flavor: the master creates worker pods and
            # watches their events (pod death drives task recovery directly)
            from elasticdl_tpu.master.k8s_instance_manager import (
                K8sInstanceManager,
            )

            self.instance_manager = K8sInstanceManager(
                self.cfg,
                membership=self.membership,
                api=self._k8s_api,
                job_finished_fn=self.dispatcher.finished,
            )
            self.instance_manager.start_workers()
            if self.autoscaler is not None:
                # master-owned pods: the action surface binds here (the
                # local-subprocess flavor binds in client/local.py)
                from elasticdl_tpu.master.autoscaler import K8sInstanceTarget

                self.autoscaler.bind_target(K8sInstanceTarget(
                    self.instance_manager, servicer=self.servicer,
                    membership=self.membership,
                ))
        if self.evaluation is not None and self.cfg.job_type == JobType.EVALUATION_ONLY:
            self.evaluation.trigger(0)

    def _embedding_on_death(self, worker_id: int) -> None:
        """Membership death -> minimal-movement shard re-plan. Best
        effort: with a resharding already in flight the dead owner's
        shards ride the NEXT plan (the interrupted one must commit or
        roll back first — overlapping plans would break the exactly-once
        confirm accounting)."""
        if self.embedding is None:
            return
        view = self.embedding.view()
        if not view.owners:
            return   # tier never bootstrapped; nothing to move
        alive = [
            w.worker_id for w in self.membership.alive_workers()
            if w.led_by is None
        ]
        if not alive:
            logger.warning(
                "embedding tier: last owner died; shards recover from "
                "checkpoint when workers return"
            )
            return
        try:
            self.embedding.begin_resharding(alive, dead=[worker_id])
        except RuntimeError as e:
            logger.warning(
                "embedding resharding deferred (worker %d death): %s",
                worker_id, e,
            )

    def _straggler_flight_hook(self, info: dict) -> None:
        """Straggler onset -> snapshot the master's flight ring. Hook
        exceptions are swallowed by ClusterHealth, and dump() never
        raises, so this can only ever cost a file write."""
        from elasticdl_tpu.observability import flight as flight_lib

        flight_lib.get_recorder().dump(
            f"straggler:worker-{info.get('worker_id')}"
        )

    def _healthz_extra(self) -> dict:
        """What the master's /healthz adds over the per-process base:
        which master (generation), which worker set (membership version +
        alive count), the latest cluster-health rollup (whose
        `snapshot_age_s` is stamped at serve time, so a scraper can tell
        a live rollup from one frozen at a wedge), and the active alert
        set. Reads only cached/cheap state — a scrape never triggers a
        recompute."""
        return {
            "generation": self.journal.generation if self.journal else 0,
            "membership_version": self.membership.version,
            "alive_workers": self.membership.alive_count(),
            "cluster": self.health.snapshot(),
            "alerts_active": self.alerts.active(),
            # the fleet goodput/wasted-work picture rides health
            # snapshots too, so chaos artifacts (and the incident CLI
            # reading them) carry the incident's bill
            "goodput": self.goodput.snapshot(),
            # the closed-loop rescale policy's state (budget, cooldown,
            # last decision); absent key = autoscaler off
            **(
                {"autoscale": self.autoscaler.snapshot()}
                if self.autoscaler is not None else {}
            ),
            # the closed-loop layout policy's state (budget, per-kind
            # cooldowns, last decision); absent key = controller off
            **(
                {"layout": self.layout.snapshot()}
                if self.layout is not None else {}
            ),
        }

    def _fleet_series(self) -> dict:
        """The master's extra sampler input: fleet aggregates computed
        from the heartbeat stats records Membership already holds, plus
        control-plane load shape (backlog per worker). Runs only when a
        time-series sample is actually due."""
        from elasticdl_tpu.observability.timeseries import fleet_series

        counts = self.dispatcher.counts()
        snap = self.health.snapshot()
        series = fleet_series(
            self.membership.health_snapshot(),
            straggler_count=snap.get("straggler_count", 0),
            todo_tasks=counts.get("todo", 0),
            alive_workers=self.membership.alive_count(),
        )
        # goodput series join the same sample: the fraction + wasted
        # ratio the default alert rules window over
        series.update(self.goodput.series())
        # tail-attribution series (dominant stage + shift pulse) join
        # too — emb_attr_dominant_shift reads the pulse from this store
        series.update(self.attribution.series(
            self.membership.health_snapshot()))
        return series

    def wait(
        self,
        poll_s: float = 1.0,
        timeout_s: Optional[float] = None,
        abort_fn=None,
    ) -> bool:
        """Block until all tasks are done. Returns True on completion.
        `abort_fn() -> bool` aborts the wait (e.g. every worker failed
        permanently — without it a dead job would block forever)."""
        deadline = time.time() + timeout_s if timeout_s else None
        while not self.dispatcher.finished():
            # chaos hook (common/faults.py): `crash` here is the real
            # kill-the-master shape for separate-process masters (os._exit,
            # nothing downstream runs); `drop` raises FaultInjected out of
            # wait() — the catchable in-process flavor client/local.py's
            # --master_restarts recovery path consumes
            faults.fire("master_crash")
            # every phase is timed into edl_master_poll_phase_seconds
            # (master/poll_phases.py) so a slow poll at fleet scale
            # names its culprit instead of being one opaque number
            with poll_phase("membership"):
                self.membership.reap()
            with poll_phase("dispatcher"):
                self.dispatcher.poke()
            # fleet rollup + straggler scoring (never raises; gauges and
            # edge-triggered cluster.straggler events update here)
            with poll_phase("health"):
                self.health.update()
            # fleet goodput rollup (never raises): heartbeat ledger
            # payloads + the dispatcher's wasted-work bill -> the
            # edl_goodput_* gauges the sampler below snapshots
            with poll_phase("goodput"):
                self.goodput.update()
            # time-series sample when due (fleet series computed only
            # then) + declarative alert evaluation over the history —
            # edge-triggered cluster.alert events, edl_alert_* metrics,
            # flight-ring dump on page severity. Neither ever raises.
            with poll_phase("timeseries"):
                self.timeseries.maybe_sample(extra_fn=self._fleet_series)
            with poll_phase("alerts"):
                self.alerts.evaluate()
            if self.autoscaler is not None:
                # the decision pass: pending signals (recorded by the
                # hooks above) -> at most one journaled, cost-gated,
                # cooldown-bounded rescale action. Never raises.
                with poll_phase("autoscaler"):
                    self.autoscaler.evaluate()
            if self.layout is not None:
                # the layout decision pass (ISSUE 20): skew signals +
                # the fleet's per-shard load / hot-id telemetry (riding
                # the same heartbeat stats records) -> at most one
                # journaled, cost-gated layout action. Never raises.
                with poll_phase("layout"):
                    self.layout.evaluate(
                        workers=self.membership.health_snapshot())
            if self.summary is not None:
                # control-plane metrics ride the summary stream (rate-
                # limited inside; never raises)
                self.summary.maybe_snapshot_registry(
                    self.dispatcher.completed_versions
                )
            if deadline and time.time() > deadline:
                return False
            if abort_fn is not None and abort_fn():
                logger.error("job aborted: no workers left to make progress")
                return False
            time.sleep(poll_s)
        return True

    def crash(self) -> None:
        """Simulated hard master death (the `master_crash` fault site /
        --master_restarts chaos path, for in-process masters that cannot
        os._exit). Tears the serving surface down ABRUPTLY: in-flight RPCs
        are cancelled, no shutdown flag reaches workers, no final summary or
        trace flush happens. The journal is closed without ceremony — every
        commit was already fsynced at append time, so this loses exactly
        what a SIGKILL would: nothing that was acknowledged. The successor
        master replays the journal and takes over under generation+1."""
        try:
            # wait for termination so the listener sockets are truly closed
            # — the successor binds the SAME port and must not race a
            # half-dead listener (see make_server's so_reuseport note)
            self.server.stop(None).wait(timeout=5.0)
        except Exception:
            logger.exception("crashed master: server stop failed")
        if self.metrics_server is not None:
            try:
                self.metrics_server.stop()
            except Exception:
                logger.debug("crashed master: metrics stop failed", exc_info=True)
            self.metrics_server = None
        if self.journal is not None:
            # abort, not close: queued group commits whose acks were never
            # released are dropped, exactly as SIGKILL would drop them
            self.journal.abort()
        # the black box survives the simulated kill (a real SIGKILL is
        # covered by the fault injector's pre-crash hook instead)
        from elasticdl_tpu.observability import flight as flight_lib

        flight_lib.get_recorder().dump("master_crash")
        logger.warning("master CRASHED (simulated): serving stopped abruptly")

    def shutdown(self, grace_s: float = 5.0) -> None:
        self.servicer.request_shutdown()
        if self.instance_manager is not None:
            try:
                self.instance_manager.stop(grace_s)
            except Exception:
                logger.exception("instance manager stop failed")
        counts = self.dispatcher.counts()
        mean_loss = self.servicer.mean_training_loss()
        results = self.evaluation.latest_results() if self.evaluation else {}
        logger.info(
            "job finished: %s mean_loss=%s eval=%s",
            counts, f"{mean_loss:.4f}" if mean_loss is not None else "n/a", results,
        )
        # give workers a heartbeat cycle to see the shutdown flag
        time.sleep(min(grace_s, self.cfg.worker_heartbeat_s))
        self.server.stop(grace_s)
        # only after the server stops: late reports may still hit the
        # summary writer while RPCs are in flight
        if self.summary is not None:
            try:
                # one final registry snapshot so the job-end metric state
                # is in events.jsonl, then close durably
                self.summary.snapshot_registry(
                    self.dispatcher.completed_versions
                )
            except Exception:
                logger.exception("final registry snapshot failed")
            self.summary.close()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        # terminal observe->decide state: one last fleet sample into the
        # rolling history + the alert engine's final alerts.json, so the
        # job's artifacts carry the end-of-run picture
        try:
            self.timeseries.sample(extra=self._fleet_series())
            self.alerts.write_json()
        except Exception:
            logger.exception("final timeseries/alerts persistence failed")
        if self.journal is not None:
            if self.dispatcher.finished():
                # clean completion: a journal left behind would make the
                # next submission reusing this checkpoint_dir replay
                # job_end/training_done and come up born-finished
                self.journal.discard()
            else:
                # aborted/timed-out shutdown: keep the journal — a resume
                # against the same checkpoint_dir recovers from it
                self.journal.close()
        from elasticdl_tpu.observability import tracing

        tracing.get_tracer().close()

    def run(self) -> int:
        self.start()
        abort_fn = (
            self.instance_manager.all_failed
            if self.instance_manager is not None else None
        )
        ok = self.wait(abort_fn=abort_fn)
        self.shutdown()
        return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    cfg = JobConfig.from_argv(sys.argv[1:] if argv is None else argv)
    return Master(cfg).run()


if __name__ == "__main__":
    raise SystemExit(main())
