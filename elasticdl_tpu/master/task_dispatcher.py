"""Dynamic data sharding: the task queue that makes training elastic.

Reference parity: elasticdl/python/master/task_dispatcher.py — the master
keeps a `todo` queue of data-span tasks and a `doing` map of leased tasks;
workers lease tasks, report completion explicitly, and a task is only ever
marked done on such a report. Worker death ⇒ its `doing` tasks go back to
`todo`, so elasticity is data-loss-free by construction. This design is
backend-agnostic and survives the TPU rebuild unchanged in spirit; it is
re-implemented here (not translated) with lease timeouts added — the
reference relied purely on pod-death events, which misses hung workers.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.master.journal import CommitGate
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.goodput import record_wasted
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = default_logger(__name__)

# lease-lifecycle telemetry. Counters are process-global (one dispatcher
# per master in production; concurrent test dispatchers share the series).
_reg = default_registry()
_TASKS_LEASED = _reg.counter(
    "edl_dispatcher_tasks_leased_total", "task leases handed to workers")
_TASKS_FINISHED = _reg.counter(
    "edl_dispatcher_tasks_finished_total", "training tasks retired")
_TASKS_REQUEUED = _reg.counter(
    "edl_dispatcher_tasks_requeued_total",
    "tasks requeued (failure retry, death recovery, preemption remainder)")
_TASKS_FAILED = _reg.counter(
    "edl_dispatcher_tasks_failed_total", "tasks failed permanently")
_LEASES_EXPIRED = _reg.counter(
    "edl_dispatcher_lease_expired_total", "leases reaped by timeout")
_STALE_REPORTS = _reg.counter(
    "edl_dispatcher_stale_reports_total", "stale/unknown task reports")
_QUEUE_TODO = _reg.gauge(
    "edl_dispatcher_todo_tasks", "queued tasks")
_QUEUE_DOING = _reg.gauge(
    "edl_dispatcher_doing_tasks", "leased (in-flight) tasks")
_LEASE_BATCH = _reg.histogram(
    "edl_dispatcher_lease_batch_tasks",
    "tasks leased per GetTask round-trip (batched leases)")


@dataclass
class TaskSpec:
    task_id: int
    type: int                    # pb.TaskType value
    shard_name: str
    start: int
    end: int
    epoch: int = 0
    eval_job_id: int = -1
    retries: int = 0

    def to_proto(self) -> pb.Task:
        return pb.Task(
            task_id=self.task_id,
            type=self.type,
            shard_name=self.shard_name,
            start=self.start,
            end=self.end,
            epoch=self.epoch,
            eval_job_id=max(self.eval_job_id, 0),
        )

    @property
    def num_records(self) -> int:
        return self.end - self.start


@dataclass
class _Lease:
    worker_id: int
    task: TaskSpec
    lease_time: float


Shard = Tuple[str, int, int]  # (shard_name, start, end)


class TaskDispatcher(CommitGate):
    """Thread-safe todo/doing task queue with epochs, retries and leases."""

    def __init__(
        self,
        training_shards: List[Shard],
        evaluation_shards: Optional[List[Shard]] = None,
        prediction_shards: Optional[List[Shard]] = None,
        records_per_task: int = 4096,
        num_epochs: int = 1,
        max_task_retries: int = 3,
        shuffle: bool = True,
        shuffle_seed: int = 0,
        task_timeout_s: float = 600.0,
        final_save_model: bool = False,
        journal=None,
        clock: Callable[[], float] = time.time,
    ):
        self._lock = threading.Lock()
        # Injectable time source: lease stamps and expiry reaping read
        # this, so the fleet simulator (fleetsim/) can drive lease
        # timeouts on a compressed virtual clock. Production: time.time.
        self._clock = clock
        # Crash durability (master/journal.py): every task lifecycle
        # transition below is committed to the journal INSIDE the _lock
        # critical section that applies it, so the on-disk order is the
        # mutation order and a restarted master replays to exactly this
        # state. None = volatile dispatcher (no checkpoint_dir).
        self._journal = journal
        self._training_shards = list(training_shards)
        self._evaluation_shards = list(evaluation_shards or [])
        self._prediction_shards = list(prediction_shards or [])
        self._records_per_task = max(1, records_per_task)
        self._num_epochs = num_epochs                # guarded_by: _lock
        self._max_task_retries = max_task_retries
        self._shuffle = shuffle
        self._rng = random.Random(shuffle_seed)      # guarded_by: _lock
        self._task_timeout_s = task_timeout_s

        # the last journal Commit enqueued by the current critical section
        # (group-commit ack-after-fsync; see _j/_take_commit_locked)
        self._pending_commit = None                  # guarded_by: _lock
        self._todo: deque[TaskSpec] = deque()        # guarded_by: _lock
        self._doing: Dict[int, _Lease] = {}          # guarded_by: _lock
        self._next_task_id = 1                       # guarded_by: _lock
        self._epoch = -1                             # guarded_by: _lock
        self._finished_training = 0                  # guarded_by: _lock
        self._failed_permanently = 0                 # guarded_by: _lock
        self._training_done = False                  # guarded_by: _lock
        self._stop_training = False                  # guarded_by: _lock
        self._epoch_end_fired = False                # guarded_by: _lock
        self._job_end_fired = False                  # guarded_by: _lock
        # callback lists: registration-before-start contract (wired while
        # the master is single-threaded), fired outside the lock on purpose
        self._epoch_end_callbacks: List[Callable[[int], None]] = []
        self._job_end_callbacks: List[Callable[[], None]] = []
        self._task_failed_callbacks: List[Callable[[TaskSpec], None]] = []
        # permanently failed tasks whose callbacks haven't fired yet
        # (collected under the lock, flushed outside it)
        self._pending_failed: List[TaskSpec] = []    # guarded_by: _lock
        # training version counter: bumps on every finished training task
        self._completed_versions = 0                 # guarded_by: _lock
        # goodput accounting (observability/goodput.py): completed
        # training records, and the wasted-work ledger — every entry is
        # journaled (`wasted_work`) inside the same critical section as
        # the transition that caused it, so a master restart replays the
        # bill intact
        self._records_completed = 0                  # guarded_by: _lock
        self._wasted_records = 0                     # guarded_by: _lock
        self._wasted_events = 0                      # guarded_by: _lock
        self._wasted_by_reason: Dict[str, Dict[str, int]] = {}  # guarded_by: _lock
        # the evidence buckets bill at most once per task (in-memory: a
        # master restart may re-bill one, which their at-least-once
        # semantics tolerate) — a client re-sending the same rejected
        # report must not grow the journal or the ratio per attempt
        self._fenced_billed: set = set()             # guarded_by: _lock
        self._stale_billed: set = set()              # guarded_by: _lock
        # final exclusive SAVE_MODEL task (reference: the master's save-model
        # task at job end, SURVEY §2.1): created once, after everything else
        # drains, before job-end fires
        self._final_save_model = final_save_model
        self._save_model_created = False             # guarded_by: _lock

        snap = journal.dispatcher_snapshot() if journal is not None else None
        if snap is not None:
            self._restore(snap)
        elif self._training_shards:
            self._start_next_epoch()
        else:
            # evaluation-only / prediction-only jobs: no training epochs.
            # Eval tasks are injected later by the EvaluationService trigger.
            self._training_done = True
            if self._prediction_shards:
                self._create_tasks(self._prediction_shards, pb.PREDICTION)
            elif not self._evaluation_shards:
                # nothing to do at all — the job is born finished
                self._job_end_fired = True

    def _restore(self, snap) -> None:  # holds: _lock (construction)
        """Rebuild queue state from a replayed journal (master recovery).
        Runs during __init__ (single-threaded). In-flight leases were
        already conservatively requeued by the replay; the shard/config
        arguments keep only their roles as defaults — the journal is the
        source of truth for everything it recorded."""
        self._todo = deque(TaskSpec(**t) for t in snap.todo)
        self._next_task_id = snap.next_task_id
        self._epoch = snap.epoch
        if snap.num_epochs is not None:
            self._num_epochs = min(self._num_epochs, snap.num_epochs)
        self._finished_training = snap.finished_training
        self._failed_permanently = snap.failed_permanently
        self._completed_versions = snap.completed_versions
        self._stop_training = snap.stop_training
        self._save_model_created = snap.save_model_created
        self._records_completed = snap.records_completed
        self._wasted_records = snap.wasted_records
        self._wasted_events = snap.wasted_events
        self._wasted_by_reason = {
            k: dict(v) for k, v in snap.wasted_by_reason.items()
        }
        # the conservative lease requeue is the crash's wasted-work bill:
        # every requeued TRAINING span re-trains whole. Journaled NOW by
        # the successor (the crashed master could not), one entry per
        # task, in the construction-time single-threaded window.
        for entry in snap.requeued:
            self._note_wasted_locked(
                "crash_requeue", int(entry.get("task_id", -1)),
                int(entry.get("records", 0)),
            )
        if self._training_shards:
            # epoch_end / training_done / job_end CALLBACKS are volatile
            # (they create eval jobs and run zoo hooks) and run OUTSIDE
            # the lock that journals the flag — a crash in between would
            # otherwise skip them forever. Restore the terminal flags as
            # NOT fired: poke() re-derives them from the replayed queues
            # and re-fires the callbacks at-least-once (replayed eval
            # tasks were dropped, so a re-fired epoch-end trigger
            # recreates its eval job fresh).
            self._epoch_end_fired = False
            self._job_end_fired = False
            self._training_done = False
        else:
            # evaluation-/prediction-only: mirror the non-restore init —
            # no training epochs; an interrupted eval job is re-triggered
            # by the service, so job-end must be re-derivable
            self._training_done = True
            self._epoch_end_fired = snap.epoch_end_fired
            self._job_end_fired = (
                snap.job_end_fired if not self._evaluation_shards else False
            )
        self._set_queue_gauges_locked()
        logger.warning(
            "dispatcher restored from control journal: epoch %d, %d todo "
            "(%d requeued from in-flight leases), %d finished, %d failed",
            self._epoch, len(self._todo), snap.requeued_leases,
            self._finished_training, self._failed_permanently,
        )

    # _j / _take_commit_locked / _await: the ack-after-fsync plumbing is
    # CommitGate (master/journal.py) — shared with Membership so the
    # durability protocol cannot drift between the two

    # ------------------------------------------------------------------ #
    # wasted-work ledger (observability/goodput.py)


    #: how deep the rejection paths look into todo when resolving a
    #: claimed task: requeued leases land at the FRONT (appendleft), so
    #: a bounded scan covers the real ghost-report case while keeping
    #: the hammerable rejection path O(1)-ish instead of O(todo) under
    #: the control-plane lock
    _REJECT_SCAN_BOUND = 64

    def _resolve_front_locked(self, task_id: int):  # holds: _lock
        """The claimed task's spec, from the live lease or the front of
        todo (bounded); None = unresolvable, rejected unbilled."""
        lease = self._doing.get(task_id)
        if lease is not None:
            return lease.task
        return next(
            (t for t in itertools.islice(
                self._todo, self._REJECT_SCAN_BOUND)
             if t.task_id == task_id),
            None,
        )

    def _note_wasted_locked(  # holds: _lock
        self, reason: str, task_id: int, records: int,
    ) -> None:
        """One wasted-work entry: counted, metric'd, and journaled inside
        the SAME critical section as the transition that caused it (disk
        order is mutation order, so replay reconstructs the bill
        exactly). `reason` values come from goodput.WASTED_REASONS — a
        bounded vocabulary, every call site a literal."""
        records = max(0, int(records))
        self._wasted_events += 1
        self._wasted_records += records
        ent = self._wasted_by_reason.setdefault(
            reason, {"events": 0, "records": 0})
        ent["events"] += 1
        ent["records"] += records
        record_wasted(reason, records)
        self._j(
            "wasted_work", reason=reason, task_id=task_id, records=records,
        )

    def wasted_work(self) -> Dict[str, Any]:
        """The wasted-work rollup FleetGoodput (and /goodput) reads:
        journal-durable totals, per-reason buckets, and the wasted ratio
        against completed training records."""
        with self._lock:
            wasted = self._wasted_records
            completed = self._records_completed
            return {
                "wasted_records": wasted,
                "wasted_events": self._wasted_events,
                "records_completed": completed,
                "wasted_ratio": round(
                    wasted / max(1, wasted + completed), 6),
                "by_reason": {
                    k: dict(v) for k, v in self._wasted_by_reason.items()
                },
            }

    def note_fenced_report(self, task_id: int, records: int) -> None:
        """A completed ReportTaskResult rejected by the generation fence
        (servicer, pre-mutation): the work behind it is discarded — the
        restarted master's replay already requeued the lease whole. The
        claimed records land in the `fenced_report` evidence bucket
        (overlapping the `crash_requeue` re-training bill on purpose:
        one bucket bills the re-run, the other proves finished work was
        thrown away).

        Same credibility gates as the stale_report bucket: the claim
        must resolve to a TRAINING task the dispatcher can still see, is
        clamped to its real span, bills at most ONCE per task, and is
        never awaited — a fence rejection is a cheap path a stale client
        can hammer, and an unvalidated claim would inflate the wasted
        ratio (the wasted_work_ratio alert's input) without bound."""
        with self._lock:
            spec = self._resolve_front_locked(task_id)
            claimed = max(0, int(records))
            if (
                spec is None or spec.type != pb.TRAINING
                or claimed <= 0 or task_id in self._fenced_billed
            ):
                return
            self._fenced_billed.add(task_id)
            self._note_wasted_locked(
                "fenced_report", task_id, min(claimed, spec.num_records)
            )
            # advisory evidence — flushed on the journal's cadence
            self._take_commit_locked()

    # ------------------------------------------------------------------ #
    # task creation

    def _split(self, shards: List[Shard]) -> List[Tuple[str, int, int]]:
        # pure over immutable config: safe with or without the lock
        spans = []
        for name, start, end in shards:
            s = start
            while s < end:
                e = min(s + self._records_per_task, end)
                spans.append((name, s, e))
                s = e
        return spans

    def _create_tasks(  # holds: _lock
        self, shards: List[Shard], task_type: int, eval_job_id: int = -1,
        front: bool = False,
        journal_prelude: Optional[List[Tuple[str, Dict[str, Any]]]] = None,
    ) -> int:
        spans = self._split(shards)
        if self._shuffle and task_type == pb.TRAINING:
            self._rng.shuffle(spans)
        tasks = []
        for name, s, e in spans:
            tasks.append(
                TaskSpec(
                    task_id=self._next_task_id,
                    type=task_type,
                    shard_name=name,
                    start=s,
                    end=e,
                    epoch=max(self._epoch, 0),
                    eval_job_id=eval_job_id,
                )
            )
            self._next_task_id += 1
        if front:
            self._todo.extendleft(reversed(tasks))
        else:
            self._todo.extend(tasks)
        if self._journal is not None and (tasks or journal_prelude):
            # one fsync for the whole batch (prelude included); front
            # batches are journaled in reversed order so sequential
            # front-insertion on replay reproduces this exact queue order
            ordered = reversed(tasks) if front else tasks
            records = list(journal_prelude or [])
            records.extend(
                ("task_create", {"task": dataclasses.asdict(t), "front": front})
                for t in ordered
            )
            self._pending_commit = self._journal.append_many(records)
        return len(tasks)

    def _start_next_epoch(self) -> None:  # holds: _lock
        self._epoch += 1
        self._epoch_end_fired = False
        # epoch_advance commits in the SAME fsync as its task batch: a
        # crash landing between a lone epoch_advance and the creations
        # would replay an epoch with an empty todo — the successor would
        # fire epoch_end over zero tasks and skip the epoch's data entirely
        n = self._create_tasks(
            self._training_shards, pb.TRAINING,
            journal_prelude=[("epoch_advance", {"epoch": self._epoch})],
        )
        logger.info("epoch %d: created %d training tasks", self._epoch, n)

    def num_evaluation_tasks(self) -> int:
        """How many tasks one eval job creates (pure function of shards)."""
        return len(self._split(self._evaluation_shards))

    def create_evaluation_tasks(self, eval_job_id: int) -> int:
        """Evaluation tasks jump the queue (reference behavior: eval tasks
        are prioritized so metrics reflect the current model version)."""
        with self._lock:
            n = self._create_tasks(
                self._evaluation_shards, pb.EVALUATION, eval_job_id, front=True
            )
            commit = self._take_commit_locked()
        self._await(commit)
        logger.info("eval job %d: created %d evaluation tasks", eval_job_id, n)
        return n

    # ------------------------------------------------------------------ #
    # leasing / reporting

    def get(self, worker_id: int) -> Optional[TaskSpec]:
        """One lease (the classic protocol): get_many with max_tasks=1."""
        tasks = self.get_many(worker_id, 1)
        return tasks[0] if tasks else None

    def get_many(self, worker_id: int, max_tasks: int = 1) -> List[TaskSpec]:
        """Lease up to ``max_tasks`` tasks in ONE pass under the lock and
        ONE journal commit (batched leases): the per-round-trip costs —
        lock acquisition, journal fsync (group-committed), RPC overhead —
        amortize across the batch. Lease-expiry/requeue/fencing semantics
        stay per task; an empty list means WAIT (or job done)."""
        max_tasks = max(1, max_tasks)
        callbacks: List[Callable] = []
        with self._lock:
            self._reap_expired_locked()
            if not self._todo:
                callbacks = self._maybe_advance_epoch_locked()
        # callbacks (epoch-end eval triggers, …) may enqueue new tasks and
        # must run outside the lock — they re-enter the dispatcher
        self._flush_callbacks(callbacks)
        with self._lock:
            if not self._todo:
                self._set_queue_gauges_locked()
                return []
            now = self._clock()
            tasks: List[TaskSpec] = []
            records = []
            while self._todo and len(tasks) < max_tasks:
                task = self._todo.popleft()
                self._doing[task.task_id] = _Lease(worker_id, task, now)
                records.append(
                    ("task_lease",
                     {"task_id": task.task_id, "worker_id": worker_id})
                )
                tasks.append(task)
            # journaled (enqueued) BEFORE the leases are observable; the
            # whole batch commits under one fsync, and a crash after this
            # point replays every lease and requeues it
            if self._journal is not None:
                self._pending_commit = self._journal.append_many(records)
            commit = self._take_commit_locked()
            self._set_queue_gauges_locked()
        # ack-after-fsync: the GetTask response IS the acknowledgment —
        # it must not leave before the lease records are durable
        self._await(commit)
        # lease-transition event OUTSIDE the lock (file I/O never runs
        # under the dispatcher lock)
        _TASKS_LEASED.inc(len(tasks))
        _LEASE_BATCH.observe(len(tasks))
        tracing.event(
            "task.lease", task_ids=[t.task_id for t in tasks],
            worker_id=worker_id, batch=len(tasks),
        )
        return tasks

    def _set_queue_gauges_locked(self) -> None:  # holds: _lock
        _QUEUE_TODO.set(len(self._todo))
        _QUEUE_DOING.set(len(self._doing))

    def _flush_callbacks(self, callbacks: List[Callable]) -> None:
        with self._lock:
            failed, self._pending_failed = self._pending_failed, []
        for task in failed:
            for cb in self._task_failed_callbacks:
                cb(task)
        for cb in callbacks:
            cb()

    def report(
        self,
        task_id: int,
        worker_id: int,
        success: bool,
        err: str = "",
        preempted: bool = False,
        records_processed: int = 0,
    ) -> bool:
        """Returns False for an unknown/stale lease (e.g. the task was
        already recovered from this worker and completed elsewhere)."""
        callbacks: List[Callable] = []
        stale = False
        held_by: Optional[int] = None
        with self._lock:
            lease = self._doing.get(task_id)
            stale = lease is None or lease.worker_id != worker_id
            if stale:
                _STALE_REPORTS.inc()
                held_by = lease.worker_id if lease is not None else None
                # Bill ONLY a credible discarded-work claim: a TRAINING
                # task the dispatcher can still see (held by a newer
                # lease, or requeued onto todo — the kill-worker ghost
                # report) whose reporter claims completed records. A
                # failed/empty stale report discards nothing, and a
                # report for a task id the dispatcher cannot resolve is
                # unvalidated remote input — rejected unbilled, or a
                # misbehaving client could inflate the wasted ratio (the
                # wasted_work_ratio alert's input) without bound.
                spec = self._resolve_front_locked(task_id)
                if (
                    spec is not None and spec.type == pb.TRAINING
                    and (success or records_processed > 0)
                    and task_id not in self._stale_billed
                ):
                    self._stale_billed.add(task_id)
                    claimed = records_processed or spec.num_records
                    self._note_wasted_locked(
                        "stale_report", task_id,
                        min(claimed, spec.num_records),
                    )
                # the entry is advisory EVIDENCE, flushed on the
                # journal's normal cadence — deliberately NOT awaited:
                # the rejection must stay a cheap, never-raising path (a
                # JournalCommitError here would read as delivery failure
                # and flip the worker's drain-checkpoint retention)
                self._take_commit_locked()
            else:
                del self._doing[task_id]
                task = lease.task
            if stale:
                pass   # rejection path finishes after the lock releases
            elif success:
                if task.type == pb.TRAINING:
                    self._finished_training += 1
                    self._completed_versions += 1
                    self._records_completed += task.num_records
                self._j(
                    "task_finish", task_id=task_id,
                    training=task.type == pb.TRAINING,
                    records=(
                        task.num_records if task.type == pb.TRAINING else 0
                    ),
                )
                _TASKS_FINISHED.inc()
            elif preempted:
                # Drain report: the first `records_processed` records were
                # applied (and are covered by the worker's preemption
                # checkpoint); requeue only the remainder, retry-free.
                done = max(0, min(records_processed, task.end - task.start))
                if task.start + done >= task.end:
                    if task.type == pb.TRAINING:
                        self._finished_training += 1
                        self._completed_versions += 1
                        self._records_completed += done
                    self._j(
                        "task_finish", task_id=task_id,
                        training=task.type == pb.TRAINING,
                        records=done if task.type == pb.TRAINING else 0,
                    )
                else:
                    task.start += done
                    # the drained remainder re-leases elsewhere: its
                    # batches were read (and possibly prefetched) once
                    # for nothing — the drain_requeue bucket; the `done`
                    # prefix COMPLETED (covered by the drain checkpoint)
                    self._requeue_locked(
                        task, "preemption remainder",
                        wasted_reason="drain_requeue",
                        completed=done,
                    )
                    logger.info(
                        "task %d preempted after %d records; requeued remainder "
                        "[%d, %d)", task_id, done, task.start, task.end,
                    )
            else:
                task.retries += 1
                if task.retries <= self._max_task_retries:
                    logger.info(
                        "task %d failed (%s); requeue retry %d",
                        task_id, err, task.retries,
                    )
                    self._requeue_locked(
                        task, "failure retry",
                        wasted_reason="failure_retry",
                    )
                else:
                    self._fail_permanently_locked(task, err)
            if not stale:
                callbacks = self._maybe_advance_epoch_locked()
                commit = self._take_commit_locked()
                self._set_queue_gauges_locked()
        if stale:
            if held_by is None:
                logger.warning(
                    "stale/unknown task report: task=%d worker=%d",
                    task_id, worker_id,
                )
            else:
                logger.warning(
                    "rejecting report for task %d from worker %d: lease "
                    "now held by worker %d", task_id, worker_id, held_by,
                )
            return False
        # ack-after-fsync: accepted=True is the acknowledgment the worker
        # keys destructive decisions off (drain-checkpoint retention) — it
        # must not leave before the finish/requeue record is durable
        self._await(commit)
        tracing.event(
            "task.report", task_id=task_id, worker_id=worker_id,
            success=bool(success), preempted=bool(preempted),
        )
        self._flush_callbacks(callbacks)
        return True

    def _requeue_locked(self, task: TaskSpec, why: str,
                        wasted_reason: Optional[str] = None,
                        completed: int = 0) -> None:
        """Put a task back on todo — unless it's a TRAINING task after
        request_stop_training, which would resurrect training the early stop
        already ended (the one-shot queue purge can't catch tasks that were
        in flight when the stop fired).

        `wasted_reason` bills the requeue to the wasted-work ledger
        (goodput.REQUEUE_REASONS; None = nothing wasted — e.g. a lease
        that never ran). `completed` journals drain-retired records so
        replay's records_completed matches the live counter."""
        # `completed` counts (and journals) for TRAINING only — replay
        # adds the journaled field unconditionally, so journaling it for
        # a non-training drain would make the replayed records_completed
        # diverge from the live counter
        completed = completed if task.type == pb.TRAINING else 0
        if completed > 0:
            self._records_completed += completed
        if self._stop_training and task.type == pb.TRAINING:
            logger.info(
                "dropping training task %d (%s) after stop request",
                task.task_id, why,
            )
            self._j(
                "task_drop", task_id=task.task_id, completed=completed,
            )
            return
        if wasted_reason is not None and task.type == pb.TRAINING:
            self._note_wasted_locked(
                wasted_reason, task.task_id, task.num_records)
        _TASKS_REQUEUED.inc()
        self._todo.appendleft(task)
        self._j(
            "task_requeue", task_id=task.task_id, start=task.start,
            retries=task.retries, completed=completed,
        )

    def _fail_permanently_locked(self, task: TaskSpec, err: str) -> None:
        self._failed_permanently += 1
        self._j("task_fail", task_id=task.task_id)
        _TASKS_FAILED.inc()
        self._pending_failed.append(task)
        logger.error(
            "task %d failed permanently after %d retries: %s",
            task.task_id, task.retries, err,
        )

    def recover_tasks(self, worker_id: int) -> int:
        """Requeue every task leased by a dead worker. THE elastic primitive
        (reference: task recovery on pod FAILED/DELETED events)."""
        with self._lock:
            stale = [t for t, l in self._doing.items() if l.worker_id == worker_id]
            for tid in stale:
                task = self._doing.pop(tid).task
                # the dead worker's span re-trains whole: the rescale
                # bill's wasted-records half (bench.py goodput asserts
                # the kill-worker scenario lands here)
                self._requeue_locked(
                    task, f"worker {worker_id} died",
                    wasted_reason="worker_died",
                )
            commit = self._take_commit_locked()
            self._set_queue_gauges_locked()
        self._await(commit)
        if stale:
            logger.info("recovered %d tasks from worker %d", len(stale), worker_id)
        return len(stale)

    def _reap_expired_locked(self) -> None:
        now = self._clock()
        expired = [
            tid
            for tid, lease in self._doing.items()
            if now - lease.lease_time > self._task_timeout_s
        ]
        for tid in expired:
            lease = self._doing.pop(tid)
            _LEASES_EXPIRED.inc()
            lease.task.retries += 1
            if lease.task.retries <= self._max_task_retries:
                logger.warning(
                    "task %d lease expired (worker %d); requeued",
                    tid, lease.worker_id,
                )
                self._requeue_locked(
                    lease.task, "lease expired",
                    wasted_reason="lease_expired",
                )
            else:
                self._fail_permanently_locked(lease.task, "lease expired")
        if expired:
            self._set_queue_gauges_locked()

    def _maybe_advance_epoch_locked(self) -> List[Callable]:
        """If the current epoch's training drained, fire epoch-end exactly
        once, then start the next epoch or finish training; fire job-end
        exactly once when everything (incl. eval/predict tasks) drains.

        Job-end is DEFERRED whenever other callbacks are pending: epoch-end
        callbacks typically enqueue the final eval job's tasks (outside the
        lock), and firing job-end in the same pass would let workers see
        job_done before those tasks exist."""
        callbacks: List[Callable] = []
        if self._training_shards and not self._training_done:
            training_left = any(
                t.type == pb.TRAINING for t in self._todo
            ) or any(l.task.type == pb.TRAINING for l in self._doing.values())
            if not training_left:
                if self._epoch >= 0 and not self._epoch_end_fired:
                    self._epoch_end_fired = True
                    self._j("epoch_end", epoch=self._epoch)
                    epoch = self._epoch
                    callbacks.extend(
                        lambda cb=cb: cb(epoch) for cb in self._epoch_end_callbacks
                    )
                if self._epoch + 1 < self._num_epochs:
                    self._start_next_epoch()
                else:
                    self._training_done = True
                    self._j("training_done")
        if callbacks:
            return callbacks
        if (
            self._training_done
            and not self._todo
            and not self._doing
            and not self._job_end_fired
        ):
            if (
                self._final_save_model
                and not self._save_model_created
                and self._finished_training > 0
            ):
                # everything else drained: one exclusive SAVE_MODEL task so a
                # durable end-of-job checkpoint exists no matter which worker
                # interval checkpointing last touched (its report re-enters
                # here and only then does job-end fire)
                self._save_model_created = True
                save_task = TaskSpec(
                    task_id=self._next_task_id,
                    type=pb.SAVE_MODEL,
                    shard_name="",
                    start=0,
                    end=0,
                    epoch=max(self._epoch, 0),
                )
                self._todo.append(save_task)
                self._next_task_id += 1
                self._j(
                    "task_create", task=dataclasses.asdict(save_task),
                    front=False,
                )
                logger.info("created final SAVE_MODEL task")
                return callbacks
            self._job_end_fired = True
            self._j("job_end")
            callbacks.extend(self._job_end_callbacks)
        return callbacks

    def request_stop_training(self, reason: str = "") -> None:
        """Early stopping: drop queued training tasks and schedule no more
        epochs; leased tasks drain normally, then the job ends through the
        usual epoch-end → final-eval → SAVE_MODEL → job-end sequence."""
        callbacks: List[Callable] = []
        with self._lock:
            self._stop_training = True   # _requeue_locked drops in-flight ones
            before = len(self._todo)
            self._todo = deque(t for t in self._todo if t.type != pb.TRAINING)
            dropped = before - len(self._todo)
            self._num_epochs = min(self._num_epochs, self._epoch + 1)
            self._j("stop_training", num_epochs=self._num_epochs)
            logger.info(
                "training stop requested (%s): dropped %d queued training "
                "tasks, no further epochs", reason or "no reason", dropped,
            )
            callbacks = self._maybe_advance_epoch_locked()
            commit = self._take_commit_locked()
        self._await(commit)
        self._flush_callbacks(callbacks)

    # ------------------------------------------------------------------ #
    # introspection / hooks

    def add_epoch_end_callback(self, cb: Callable[[int], None]) -> None:
        self._epoch_end_callbacks.append(cb)

    def add_job_end_callback(self, cb: Callable[[], None]) -> None:
        self._job_end_callbacks.append(cb)

    def add_task_failed_callback(self, cb: Callable[[TaskSpec], None]) -> None:
        """cb(task) fires when a task fails permanently (retries exhausted)."""
        self._task_failed_callbacks.append(cb)

    def poke(self) -> None:
        """Drive deferred state transitions (lease reaping, epoch/job end)
        without a worker RPC — the master's wait loop calls this so progress
        doesn't depend on workers polling."""
        with self._lock:
            self._reap_expired_locked()
            callbacks = self._maybe_advance_epoch_locked()
            commit = self._take_commit_locked()
        self._await(commit)
        self._flush_callbacks(callbacks)

    def finished(self) -> bool:
        """True only once job-end has actually fired — `_training_done` with
        empty queues is transiently observable while epoch-end callbacks are
        still enqueueing the final eval tasks, and must not look finished."""
        with self._lock:
            return self._job_end_fired

    @property
    def completed_versions(self) -> int:
        with self._lock:
            return self._completed_versions

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {
                "todo": len(self._todo),
                "doing": len(self._doing),
                "finished_training": self._finished_training,
                "failed_permanently": self._failed_permanently,
                "epoch": self._epoch,
            }
